"""Fleet — the distributed-training facade.

Reference: python/paddle/distributed/fleet/base/fleet_base.py:62,129,583,978
(fleet.init → RoleMaker env parse + rendezvous; distributed_optimizer wraps
the inner optimizer; minimize ranks + applies meta-optimizers that rewrite
the program).

TPU-native: init resolves the mesh from DistributedStrategy + device count
(replacing RoleMaker ring building), distributed_optimizer returns a wrapper
whose `minimize`/`step` work eagerly for API parity, and the strategy's real
effect is on `fleet.train_step(...)` / parallel.ShardedTrainStep — sharding
specs instead of program rewriting.
"""
from __future__ import annotations

from typing import Optional

import jax

from ...parallel import (DistributedStrategy, create_mesh, set_mesh,
                         get_mesh, ShardedTrainStep)
from ..env import ParallelEnv, init_parallel_env, get_rank, get_world_size
from .. import collective as _collective

_fleet_initialized = False
_strategy: Optional[DistributedStrategy] = None


class UserDefinedRoleMaker:
    """compat shim (reference role_maker.py) — env-var driven."""

    def __init__(self, is_collective=True, **kw):
        self._is_collective = is_collective


PaddleCloudRoleMaker = UserDefinedRoleMaker


def init(role_maker=None, is_collective=True, strategy=None):
    """fleet.init (fleet_base.py:129)."""
    global _fleet_initialized, _strategy
    _strategy = strategy or DistributedStrategy()
    init_parallel_env()
    n = len(jax.devices())
    axes = _strategy.mesh_axes(n)
    set_mesh(create_mesh(axes))
    _fleet_initialized = True


def is_first_worker() -> bool:
    return get_rank() == 0


def worker_index() -> int:
    return get_rank()


def worker_num() -> int:
    return get_world_size()


def barrier_worker():
    _collective.barrier()


class DistributedOptimizer:
    """fleet.distributed_optimizer result: wraps the user optimizer.

    Eager use (API parity): behaves exactly like the inner optimizer.
    The strategy is consumed when a compiled step is built via
    fleet.distributed_train_step / parallel.ShardedTrainStep.
    """

    def __init__(self, optimizer, strategy: DistributedStrategy):
        self._inner = optimizer
        self.user_defined_strategy = strategy

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self._inner.minimize(loss, startup_program, parameters,
                                    no_grad_set)

    def step(self):
        return self._inner.step()

    def clear_grad(self):
        return self._inner.clear_grad()


def distributed_optimizer(optimizer, strategy=None) -> DistributedOptimizer:
    """fleet_base.py:583."""
    global _strategy
    st = strategy or _strategy or DistributedStrategy()
    _strategy = st
    return DistributedOptimizer(optimizer, st)


def distributed_model(model):
    """fleet_base.py distributed_model: dygraph DDP wrap."""
    from ..parallel_layer import DataParallel
    return DataParallel(model)


def distributed_train_step(model, loss_fn, optimizer, strategy=None):
    """Build the compiled SPMD train step for the current fleet mesh —
    the TPU-native 'minimize': where the reference rewrites programs, we
    hand back one jitted step with sharded params/opt/batch.  The localsgd
    strategy flag selects the divergent-replica LocalSGDTrainStep
    (localsgd_optimizer.py equivalent)."""
    st = strategy or _strategy or DistributedStrategy()
    inner = getattr(optimizer, "_inner", optimizer)
    mesh = get_mesh(create_default=True)
    if st.localsgd:
        if (st.sharding or st.tensor_parallel or st.sequence_parallel
                or st.pipeline or st.gradient_merge or st.recompute
                or st.fp16_allreduce):
            raise ValueError(
                "localsgd composes with plain DP (+AMP) only — disable "
                "sharding/tensor_parallel/sequence_parallel/pipeline/"
                "gradient_merge/recompute/fp16_allreduce")
        from ...parallel.localsgd import LocalSGDTrainStep
        k = (st.localsgd_configs or {}).get("k_steps", 4)
        return LocalSGDTrainStep(
            model, loss_fn, inner, k_steps=k, mesh=mesh,
            amp_level=("O1" if st.amp else None),
            amp_dtype=st.amp_configs.dtype)
    return ShardedTrainStep(model, loss_fn, inner, strategy=st, mesh=mesh)


def get_strategy() -> Optional[DistributedStrategy]:
    return _strategy


class Role:
    """Worker/server role constants (reference: fleet/base/role_maker.py:26).
    The PS roles exist for API parity; collective (WORKER-only) is the TPU
    execution model."""
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class UtilBase:
    """fleet.util (reference: fleet/base/util_factory.py UtilBase) — the
    cross-worker helper surface over XLA collectives instead of Gloo."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):  # noqa: A002
        import jax.numpy as jnp
        import numpy as np
        from .. import collective as c
        from ...core.tensor import Tensor
        t = input if isinstance(input, Tensor) else Tensor(
            jnp.asarray(input))
        op = {"sum": c.ReduceOp.SUM, "min": c.ReduceOp.MIN,
              "max": c.ReduceOp.MAX}[mode]
        c.all_reduce(t, op=op)
        return np.asarray(t.numpy())

    def barrier(self, comm_world="worker"):
        barrier_worker()

    def all_gather(self, input, comm_world="worker"):  # noqa: A002
        from .. import collective as c
        from ...core.tensor import Tensor
        import jax.numpy as jnp
        out = []
        c.all_gather(out, Tensor(jnp.asarray(input)))
        return [o.numpy() for o in out]

    def get_file_shard(self, files):
        """Split a file list across workers (reference util_factory:
        contiguous blocks, remainder to the first workers)."""
        n, rank = worker_num(), worker_index()
        per, rem = divmod(len(files), n)
        start = rank * per + min(rank, rem)
        return list(files[start:start + per + (1 if rank < rem else 0)])

    def print_on_rank(self, message, rank_id=0):
        if worker_index() == rank_id:
            print(message, flush=True)


class Fleet:
    """Class form of the module-level facade (reference fleet_base.py:62
    Fleet; `paddle.distributed.fleet.fleet` is its singleton).  Methods
    delegate to the module functions so both spellings stay in sync."""

    util = UtilBase()

    def init(self, role_maker=None, is_collective=True, strategy=None):
        return init(role_maker, is_collective, strategy)

    def is_first_worker(self):
        return is_first_worker()

    def worker_index(self):
        return worker_index()

    def worker_num(self):
        return worker_num()

    def is_worker(self):
        return True  # collective mode: every process is a worker

    def is_server(self):
        return False  # PS scoped out (SURVEY §2.3)

    def barrier_worker(self):
        barrier_worker()

    def distributed_optimizer(self, optimizer, strategy=None):
        return distributed_optimizer(optimizer, strategy)

    def distributed_model(self, model):
        return distributed_model(model)


fleet = Fleet()

from .data_generator import (  # noqa: F401,E402
    DataGenerator, MultiSlotDataGenerator, MultiSlotStringDataGenerator,
)
