"""Fleet data generators — the stdin->stdout line protocol feeding
QueueDataset/MultiSlotDataFeed pipelines.

Reference: python/paddle/distributed/fleet/data_generator/
data_generator.py:19,237,278 (DataGenerator base + the MultiSlot
string/typed emitters).  The protocol per sample is
``<n_values> v1 v2 ... <n_values> v1 ...`` — one group per (slot, values)
pair, space-joined, newline-terminated — which is exactly what
`paddle_tpu.distributed.dataset` (and the native datafeed.cc reader)
consumes.  TPU-native note: the generators are pure host-side text
plumbing; they exist so era ETL scripts (`mydata.run_from_stdin()`) port
unchanged.
"""
from __future__ import annotations

import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    """Override `generate_sample(line)` to return a generator-factory
    yielding [(slot_name, values), ...]; optionally override
    `generate_batch(samples)` for cross-sample logic."""

    def __init__(self):
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        self.batch_size_ = int(batch_size)

    def generate_sample(self, line):
        raise NotImplementedError(
            "override generate_sample(line) -> generator factory yielding "
            "[(slot, values), ...]")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    def _gen_str(self, userdefined):
        raise NotImplementedError(
            "use MultiSlotDataGenerator or MultiSlotStringDataGenerator")

    def _flush_batch(self, batch, out):
        for sample in self.generate_batch(batch)():
            out.write(self._gen_str(sample))

    def run_from_stdin(self):
        """Era ETL entry: parse each stdin line via generate_sample, emit
        the MultiSlot line protocol on stdout."""
        self._run_lines(sys.stdin, sys.stdout)

    def run_from_memory(self):
        """Debug/benchmark entry: generate_sample(None) supplies samples
        (one batching/flush loop — shared with run_from_stdin)."""
        self._run_lines([None], sys.stdout)

    def _run_lines(self, lines, out):
        batch = []
        for line in lines:
            for sample in self.generate_sample(line)():
                if sample is None:
                    continue
                batch.append(sample)
                if len(batch) == self.batch_size_:
                    self._flush_batch(batch, out)
                    batch = []
        if batch:
            self._flush_batch(batch, out)


class MultiSlotStringDataGenerator(DataGenerator):
    """values are pre-stringified: [("words", ["1926", "08"]), ...] ->
    "2 1926 08 ..."."""

    def _gen_str(self, userdefined):
        if not isinstance(userdefined, (list, tuple)):
            raise ValueError(
                "generate_sample must yield a list/tuple of "
                "(slot, [str, ...]) pairs")
        groups = []
        for _, values in userdefined:
            groups.append(" ".join([str(len(values))] + list(values)))
        return " ".join(groups) + "\n"


class MultiSlotDataGenerator(DataGenerator):
    """values are ints/floats; type consistency per slot is the caller's
    contract (the reference tracks a proto_info for the same purpose)."""

    def _gen_str(self, userdefined):
        if not isinstance(userdefined, (list, tuple)):
            raise ValueError(
                "generate_sample must yield a list/tuple of "
                "(slot, [value, ...]) pairs")
        groups = []
        for _, values in userdefined:
            groups.append(" ".join(
                [str(len(values))] + [str(v) for v in values]))
        return " ".join(groups) + "\n"
