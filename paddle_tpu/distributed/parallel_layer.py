"""DataParallel — dygraph (eager) DDP wrapper.

Reference: python/paddle/fluid/dygraph/parallel.py:289 (DataParallel wraps a
Layer; imperative::Reducer buckets grads and ncclAllReduces them on comm
streams, imperative/reducer.h:116, reducer.cc MarkVarReady hooks).

TPU-native: there are no rank processes and no comm streams.  The wrapper
makes *eager* code data-parallel by sharding every batch input over the
"dp" axis of a device mesh (`jax.device_put` with a NamedSharding).  From
there JAX's eager per-op compilation propagates the sharding: activations
stay batch-sharded, and each parameter-grad op in the tape's vjp closures
contracts over the sharded batch axis, so **XLA inserts the all-reduce
inside the grad op itself** — the Reducer's bucketed ncclAllReduce becomes
compiler-scheduled ICI collectives, overlapped per-op instead of hooked at
MarkVarReady.

`scale_loss` is the identity (the loss is already the mean over the global
batch — the reference divides by nranks only because each rank computes a
local mean and the allreduce sums).  `apply_collective_grads` re-replicates
any grad whose sharding is not already fully replicated, in fused groups of
`comm_buffer_size` MB (the Reducer's bucket size knob).
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from ..parallel.mesh import create_mesh, get_mesh


def _dp_size(mesh) -> int:
    return mesh.shape.get("dp", 1)


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None, mesh=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        self.comm_buffer_size = comm_buffer_size
        m = mesh or get_mesh()
        if m is None and len(jax.devices()) > 1:
            m = create_mesh({"dp": len(jax.devices())})
        self._mesh = m
        self._batch_sharding = (
            NamedSharding(m, P("dp")) if m is not None and _dp_size(m) > 1
            else None)

    def _shard_arg(self, x):
        """Shard dim-0 of batch-like args over dp; pass others through."""
        if self._batch_sharding is None:
            return x
        dp = _dp_size(self._mesh)
        if isinstance(x, Tensor):
            if x.ndim >= 1 and x.shape[0] % dp == 0:
                data = jax.device_put(x._data, self._batch_sharding)
                return Tensor(data, stop_gradient=x.stop_gradient)
            return x
        if isinstance(x, jax.Array) and x.ndim >= 1 and x.shape[0] % dp == 0:
            return jax.device_put(x, self._batch_sharding)
        return x

    def forward(self, *inputs, **kwargs):
        inputs = tuple(self._shard_arg(a) for a in inputs)
        kwargs = {k: self._shard_arg(v) for k, v in kwargs.items()}
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        # identity: loss is the global-batch mean already (see module doc)
        return loss

    def apply_collective_grads(self):
        """Re-replicate non-replicated grads in comm_buffer_size-MB groups
        (the Reducer bucket knob, reducer.h:41)."""
        if self._mesh is None:
            return
        replicated = NamedSharding(self._mesh, P())
        bucket, bucket_bytes = [], 0
        cap = max(1, int(self.comm_buffer_size)) * (1 << 20)

        def flush():
            nonlocal bucket, bucket_bytes
            if not bucket:
                return
            moved = jax.device_put([p.grad._data for p in bucket],
                                   [replicated] * len(bucket))
            for p, g in zip(bucket, moved):
                p.grad = Tensor(g, stop_gradient=True)
            bucket, bucket_bytes = [], 0

        for p in self._layers.parameters():
            g = getattr(p, "grad", None)
            if g is None or not isinstance(g, Tensor):
                continue
            sh = getattr(g._data, "sharding", None)
            if sh is None or sh.is_fully_replicated:
                continue
            bucket.append(p)
            bucket_bytes += g._data.nbytes
            if bucket_bytes >= cap:
                flush()
        flush()

    # delegate everything stateful to the wrapped layer
    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    set_dict = set_state_dict
    load_dict = set_state_dict

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)
