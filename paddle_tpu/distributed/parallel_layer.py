"""DataParallel — dygraph DDP wrapper.

Reference: python/paddle/fluid/dygraph/parallel.py:289 (DataParallel wraps a
Layer; imperative::Reducer buckets grads and all-reduces them on comm
streams, imperative/reducer.h:116).

TPU-native: there are no per-rank processes to reduce across in the
single-controller model — the batch axis of a jitted step is sharded over
the "dp" mesh axis and XLA emits the gradient reduction (see
parallel.ShardedTrainStep).  This wrapper keeps API parity for eager code:
it forwards to the inner layer, and `scale_loss`/`apply_collective_grads`
are the identity (world of one per controller).  Multi-process eager DDP
(jax.distributed + pmap-style) is intentionally not the perf path.
"""
from __future__ import annotations

from ..nn.layer_base import Layer


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    # delegate everything stateful to the wrapped layer
    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    set_dict = set_state_dict
    load_dict = set_state_dict

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)
