"""Sharded + automatic checkpointing.

Reference: auto-checkpoint on preemption
(python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py:71 — epoch-range
context that snapshots train status to HDFS and resumes after restart) and the
PS checkpoint_notify machinery (operators/distributed_ops/checkpoint_notify_op.cc).

TPU-native design: parameters and optimizer states of a sharded train step
live as jax.Arrays distributed over a Mesh.  Saving gathers NOTHING: each
process writes only the addressable shards it owns (deduplicated by
replica_id), plus a JSON manifest of global shapes/dtypes/PartitionSpecs.
Restoring uses `jax.make_array_from_callback` so every device reads only its
own slice — works across topology changes by reassembling from the shard
files on demand.

Layout of a checkpoint directory:
    step-000042/
        manifest.json          global metadata (shapes, dtypes, specs, step)
        shards-p00000.npz      this process's owned shards
    latest                     text file naming the newest complete step dir

Writes are atomic: a temp dir is renamed into place only after the npz/json
are fully written, so a kill mid-save never corrupts the latest checkpoint.
"""
from __future__ import annotations

import json
import os
import queue
import re
import shutil
import tempfile
import threading
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


_obs_handles = None


def _obs():
    """(save_stall_histogram, async_in_flight_gauge) — observability
    handles, created once (registry.reset() zeroes in place)."""
    global _obs_handles
    if _obs_handles is None:
        from ..observability import metrics as _m
        _obs_handles = (
            _m.histogram("checkpoint_save_stall_seconds",
                         "wall time the training thread was stalled by a "
                         "checkpoint save (sync: full write; async: "
                         "snapshot + enqueue)"),
            _m.gauge("checkpoint_async_in_flight",
                     "snapshots queued or being written by the async "
                     "checkpoint writer"))
    return _obs_handles


# -- PartitionSpec (de)serialization ----------------------------------------

def _spec_to_json(spec) -> list:
    out = []
    for e in tuple(spec):
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            out.append(list(e))
        else:
            out.append(str(e))
    return out


def _spec_from_json(entries) -> P:
    out = []
    for e in entries:
        if isinstance(e, list):
            out.append(tuple(e))
        else:
            out.append(e)
    return P(*out)


def _index_key(name: str, index) -> str:
    starts = ",".join(str(0 if s.start is None else int(s.start))
                      for s in index)
    return f"{name}@{starts}"


# -- tree flattening (params + nested opt-state dicts) ----------------------

def _flatten(tree, prefix="", out=None):
    out = {} if out is None else out
    if isinstance(tree, dict):
        for k in sorted(tree):
            _flatten(tree[k], f"{prefix}{k}/", out)
    else:
        out[prefix[:-1]] = tree
    return out


def _unflatten(flat: Dict[str, object]):
    tree: Dict[str, object] = {}
    for key, v in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return tree


# -- save -------------------------------------------------------------------

def snapshot_tree(state_tree, step: int = 0,
                  extra_meta: Optional[dict] = None):
    """Phase 1 of a save: copy this process's owned device shards to HOST
    memory and build the manifest.  Returns (manifest, shards).

    The copies are real (np.array, copy=True), never views: the async
    checkpoint path hands the snapshot to a background writer while the
    train step DONATES and overwrites the source buffers — a zero-copy view
    would let the writer read the next step's params (or garbage).
    """
    flat = _flatten(state_tree)
    manifest = {"step": int(step), "arrays": {}, "extra": extra_meta or {},
                "n_processes": jax.process_count()}
    shards = {}
    for name, arr in flat.items():
        arr = jnp.asarray(arr)
        sharding = arr.sharding
        spec = (sharding.spec if isinstance(sharding, NamedSharding)
                else P())
        manifest["arrays"][name] = {
            "shape": list(arr.shape), "dtype": str(arr.dtype),
            "spec": _spec_to_json(spec),
        }
        for shard in getattr(arr, "addressable_shards", []):
            if shard.replica_id != 0:
                continue
            shards[_index_key(name, shard.index)] = np.array(shard.data,
                                                             copy=True)
    return manifest, shards


def _fsync_dir(path: str):
    """fsync a directory so the rename that published a checkpoint is
    durable before `latest` points at it (a power cut after rename but
    before the metadata hits disk must not leave `latest` dangling —
    though even then latest_step_dir falls back to the newest valid dir)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def publish_snapshot(directory: str, manifest: dict, shards: dict) -> str:
    """Phase 2 of a single-process save: write npz + manifest into a temp
    dir, atomically rename into place, fsync the parent dir, update
    `latest`.  Runs on the caller thread (sync save) or the
    AsyncCheckpointManager's writer thread."""
    from ..observability import span as _span
    from ..utils.monitor import stat_add
    step = manifest["step"]
    with _span("checkpoint_publish", args={"step": step}):
        stat_add("STAT_checkpoint_bytes_written",
                 sum(a.nbytes for a in shards.values()))
        return _publish_snapshot_inner(directory, manifest, shards)


def _publish_snapshot_inner(directory: str, manifest: dict,
                            shards: dict) -> str:
    from ..utils import faults as _faults
    step = manifest["step"]
    step_dir = os.path.join(directory, f"step-{step:09d}")
    tmp_dir = step_dir + f".tmp-p{jax.process_index():05d}"
    os.makedirs(tmp_dir, exist_ok=True)
    # fsync file CONTENTS before the publishing rename: a rename can be
    # durable while the data pages are not, and a post-crash step dir with
    # a valid manifest but truncated shards would win the latest-fallback
    # scan over the genuinely complete previous checkpoint
    with open(os.path.join(tmp_dir,
                           f"shards-p{jax.process_index():05d}.npz"),
              "wb") as f:
        np.savez(f, **shards)
        f.flush()
        os.fsync(f.fileno())
    with open(os.path.join(tmp_dir, "manifest.json"), "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    # fault point: a kill HERE (files written, not yet renamed) must leave
    # the previous checkpoint fully restorable
    _faults.maybe_kill_mid_save()
    if os.path.exists(step_dir):
        shutil.rmtree(step_dir)
    os.rename(tmp_dir, step_dir)
    _fsync_dir(directory)
    _write_atomic(os.path.join(directory, "latest"),
                  os.path.basename(step_dir))
    return step_dir


def save_sharded(state_tree, directory: str, step: int = 0,
                 extra_meta: Optional[dict] = None) -> str:
    """Write a sharded checkpoint of a pytree of jax.Arrays (nested dicts).

    No host gather: each process saves only shards with replica_id == 0 among
    its addressable shards.  Returns the final step directory path.
    """
    stall_h, _ = _obs()
    with stall_h.time():
        manifest, shards = snapshot_tree(state_tree, step, extra_meta)
        pidx = jax.process_index()
        if jax.process_count() == 1:
            return publish_snapshot(directory, manifest, shards)
        return _save_sharded_multi(state_tree, directory, step, manifest,
                                   shards, pidx)


def _save_sharded_multi(state_tree, directory, step, manifest, shards, pidx):
    from ..utils.monitor import stat_add
    stat_add("STAT_checkpoint_bytes_written",
             sum(a.nbytes for a in shards.values()))

    step_dir = os.path.join(directory, f"step-{step:09d}")
    tmp_dir = step_dir + f".tmp-p{pidx:05d}"
    os.makedirs(tmp_dir, exist_ok=True)
    npz_name = f"shards-p{pidx:05d}.npz"
    # same durability rule as publish_snapshot: shard CONTENTS are synced
    # before anything publishes them, so a post-crash dir with a valid
    # manifest can't hold truncated shards
    with open(os.path.join(tmp_dir, npz_name), "wb") as f:
        np.savez(f, **shards)
        f.flush()
        os.fsync(f.fileno())
    # multi-host on a shared fs: every process lands its npz, then a
    # global barrier, THEN process 0 publishes manifest + latest — a
    # reader never sees a manifest without all its shards
    os.makedirs(step_dir, exist_ok=True)
    os.replace(os.path.join(tmp_dir, npz_name),
               os.path.join(step_dir, npz_name))
    shutil.rmtree(tmp_dir, ignore_errors=True)
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(f"paddle_tpu-ckpt-{step}")
    if pidx == 0:
        # scrub stale shards from an earlier save with more processes
        # BEFORE publishing the manifest, so readers without the
        # n_processes filter can't overlay them
        n = jax.process_count()
        for f in os.listdir(step_dir):
            if (f.startswith("shards-p") and f.endswith(".npz")
                    and int(f[len("shards-p"):-len(".npz")]) >= n):
                os.unlink(os.path.join(step_dir, f))
        _write_atomic(os.path.join(step_dir, "manifest.json"),
                      json.dumps(manifest))
        _fsync_dir(step_dir)
        _write_atomic(os.path.join(directory, "latest"),
                      os.path.basename(step_dir))
    return step_dir


def _write_atomic(path: str, content: str):
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".")
    with os.fdopen(fd, "w") as f:
        f.write(content)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


# -- restore ----------------------------------------------------------------

class _ShardStore:
    """Lazily-opened shard files for one checkpoint step dir.

    Only shards-pNNNNN.npz with N < the manifest's n_processes are read: a
    re-save into an existing step dir from a smaller process count must not
    overlay stale higher-numbered shard files from the earlier save.
    """

    def __init__(self, step_dir: str, n_processes: Optional[int] = None):
        names = [f for f in sorted(os.listdir(step_dir))
                 if f.startswith("shards-p") and f.endswith(".npz")]
        if n_processes is not None:
            names = [f for f in names
                     if int(f[len("shards-p"):-len(".npz")]) < n_processes]
        self.files = [np.load(os.path.join(step_dir, f)) for f in names]
        self._full_cache: Dict[str, np.ndarray] = {}

    def lookup(self, name: str, index, shape, dtype):
        key = _index_key(name, index)
        want = tuple(
            (dim if s.stop is None else s.stop) - (0 if s.start is None
                                                   else s.start)
            for s, dim in zip(index, shape))
        for f in self.files:
            if key in f.files and f[key].shape == want:
                return f[key]
        return self._assemble(name, shape, dtype)[tuple(index)]

    def _assemble(self, name: str, shape, dtype) -> np.ndarray:
        """Topology changed between save and restore: rebuild the full array
        from whatever shards exist (correct, costs host memory for `name`)."""
        if name in self._full_cache:
            return self._full_cache[name]
        full = np.zeros(shape, dtype)
        covered = np.zeros(shape, bool)
        prefix = f"{name}@"
        for f in self.files:
            for key in f.files:
                if not key.startswith(prefix):
                    continue
                starts = [int(x) for x in key[len(prefix):].split(",")]
                data = f[key]
                idx = tuple(slice(s, s + d) for s, d in
                            zip(starts, data.shape))
                full[idx] = data
                covered[idx] = True
        if not covered.all():
            missing = covered.size - int(covered.sum())
            raise ValueError(
                f"checkpoint is incomplete for '{name}': {missing} of "
                f"{covered.size} elements have no shard (lost/partial "
                "shard file?)")
        self._full_cache[name] = full
        return full


def _has_valid_manifest(step_dir: str) -> bool:
    try:
        with open(os.path.join(step_dir, "manifest.json")) as f:
            json.load(f)
        return True
    except (OSError, ValueError):
        return False


def latest_step_dir(directory: str) -> Optional[str]:
    """Resolve the newest restorable checkpoint.

    The `latest` pointer is a hint, not the ground truth: it can be missing
    (crash before the first pointer write), name a step dir that retention
    GC deleted on another process, or name a dir whose manifest never
    landed (kill mid-publish on a non-atomic fs).  Any of those falls back
    to the newest step-* dir that actually has a loadable manifest — the
    atomicity contract says such a dir is complete.
    """
    ptr = os.path.join(directory, "latest")
    try:
        with open(ptr) as f:
            name = f.read().strip()
    except OSError:
        name = None
    if name:
        step_dir = os.path.join(directory, name)
        if os.path.isdir(step_dir) and _has_valid_manifest(step_dir):
            return step_dir
    # fallback scan, newest first
    try:
        entries = os.listdir(directory)
    except OSError:
        return None
    steps = sorted((int(m.group(1)), d) for d in entries
                   if (m := _STEP_DIR_RE.match(d)))
    for _, d in reversed(steps):
        step_dir = os.path.join(directory, d)
        if os.path.isdir(step_dir) and _has_valid_manifest(step_dir):
            return step_dir
    return None


def restore_sharded(directory: str, mesh: Optional[Mesh] = None,
                    shardings: Optional[dict] = None, step: Optional[int] = None):
    """Restore (state_tree, step, extra_meta) from a checkpoint directory.

    shardings: optional flat-or-nested dict overriding the saved
    PartitionSpecs (e.g. restoring onto a different mesh layout). When a mesh
    is given (or discoverable), arrays come back sharded; otherwise they are
    restored as host-local full arrays.
    """
    step_dir = (os.path.join(directory, f"step-{step:09d}") if step is not None
                else latest_step_dir(directory))
    if step_dir is None or not os.path.isdir(step_dir):
        return None
    with open(os.path.join(step_dir, "manifest.json")) as f:
        manifest = json.load(f)
    store = _ShardStore(step_dir, manifest.get("n_processes"))
    flat_shardings = _flatten(shardings) if shardings else {}

    out = {}
    for name, meta in manifest["arrays"].items():
        shape = tuple(meta["shape"])
        dtype = np.dtype(meta["dtype"])
        sharding = flat_shardings.get(name)
        if sharding is None and mesh is not None:
            spec = _spec_from_json(meta["spec"])
            # drop axes the restore mesh doesn't have
            entries = [e if _axes_exist(e, mesh) else None
                       for e in tuple(spec)]
            sharding = NamedSharding(mesh, P(*entries))
        if sharding is not None:
            arr = jax.make_array_from_callback(
                shape, sharding,
                lambda idx, n=name, sh=shape, dt=dtype:
                    store.lookup(n, idx, sh, dt))
        else:
            arr = jnp.asarray(store._assemble(name, shape, dtype))
        out[name] = arr
    return _unflatten(out), manifest["step"], manifest.get("extra", {})


def _axes_exist(entry, mesh: Mesh) -> bool:
    if entry is None:
        return True
    names = entry if isinstance(entry, (tuple, list)) else (entry,)
    return all(n in mesh.shape for n in names)


# -- train-step glue (shared by jit.TrainStep / parallel.ShardedTrainStep) --

_STEP_DIR_RE = re.compile(r"^step-(\d+)$")


def train_state_extras(optimizer=None, extra_meta: Optional[dict] = None,
                       scaler=None, data_cursor: Optional[dict] = None) -> dict:
    """Collect the non-array training state for a checkpoint's extra dict:
    host rng stream, LR scheduler, GradScaler loss-scaling state, and the
    data-iterator cursor.  Shared by the sync and async save paths."""
    from ..core import rng as _rng
    extra = dict(extra_meta or {})
    extra["__rng__"] = np.asarray(_rng.get_rng_state()).tolist()
    sched = getattr(optimizer, "_lr_scheduler", None)
    if sched is not None:
        extra["__lr_sched__"] = sched.state_dict()
    if scaler is not None:
        extra["__scaler__"] = scaler.state_dict()
    if data_cursor is not None:
        extra["__data_cursor__"] = dict(data_cursor)
    return extra


def save_train_state(directory: str, params, opt_state, step: int,
                     extra_meta: Optional[dict] = None,
                     optimizer=None, scaler=None,
                     data_cursor: Optional[dict] = None) -> str:
    """Snapshot params + optimizer state + the host rng stream + the LR
    scheduler state (+ GradScaler loss-scaling state and the data-iterator
    cursor when given), so a resumed run reproduces the uninterrupted one
    even with dropout, a warmup/decay schedule, and dynamic loss scaling
    active."""
    from ..utils.monitor import stat_add
    stat_add("STAT_checkpoint_saves")
    extra = train_state_extras(optimizer, extra_meta, scaler, data_cursor)
    return save_sharded({"params": params, "opt": opt_state}, directory,
                        step, extra)


def restore_train_extras(optimizer, step: int, extra: dict,
                         scaler=None) -> dict:
    """Apply the non-array training state (step count, rng stream, LR
    scheduler, GradScaler) from a checkpoint's extra dict.  Shared by every
    train-step restore path.  Mutates `extra` (pops the internal keys);
    returns the user-facing meta dict.  A saved data cursor surfaces as
    meta["data_cursor"] for the caller's loader to fast-forward."""
    from ..core import rng as _rng
    optimizer._step_count = step
    rng_state = extra.pop("__rng__", None)
    if rng_state is not None:
        _rng.set_rng_state(jnp.asarray(rng_state, jnp.uint32))
    sched_state = extra.pop("__lr_sched__", None)
    if sched_state is not None:
        sched = getattr(optimizer, "_lr_scheduler", None)
        if sched is not None:
            sched.set_state_dict(sched_state)
    scaler_state = extra.pop("__scaler__", None)
    if scaler_state is not None and scaler is not None:
        scaler.load_state_dict(scaler_state)
    cursor = extra.pop("__data_cursor__", None)
    if cursor is not None:
        extra["data_cursor"] = cursor
    return {"step": step, **extra}


def apply_train_state(model, optimizer, restored, scaler=None):
    """Write a restore_sharded result back into model/optimizer/rng/scheduler.
    Returns (meta_dict, opt_state_tree)."""
    tree, step, extra = restored
    sd = model.state_dict()
    for k, v in tree["params"].items():
        sd[k]._set_data(v)
    meta = restore_train_extras(optimizer, step, extra, scaler=scaler)
    # stateless optimizers (SGD) save empty per-param dicts, which the
    # flatten/unflatten roundtrip drops — callers merge over a fresh
    # init_opt_state structure via merge_opt_state
    return meta, tree.get("opt", {})


def merge_opt_state(fresh: dict, restored: dict) -> dict:
    """Per-param merge: restored entries win; params whose state vanished in
    the save (empty dicts) keep the freshly initialized structure."""
    return {k: restored.get(k, fresh[k]) for k in fresh}


# -- checkpoint manager + auto-checkpoint -----------------------------------

class CheckpointManager:
    """Periodic sharded checkpointing with retention and resume.

    The TPU-native answer to auto_checkpoint.py: training state snapshots
    every `save_interval_steps` (or `save_interval_seconds`), keeps the last
    `max_to_keep`, and `restore_latest` resumes bit-exact after a kill.
    """

    def __init__(self, directory: str, max_to_keep: int = 2,
                 save_interval_steps: int = 100,
                 save_interval_seconds: Optional[float] = None,
                 keep_every_k_steps: Optional[int] = None):
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.save_interval_steps = save_interval_steps
        self.save_interval_seconds = save_interval_seconds
        # retention milestones: steps divisible by K survive the
        # keep-last-N pruning forever (long-run archaeology checkpoints)
        self.keep_every_k_steps = keep_every_k_steps
        self._last_saved_step = None
        self._last_saved_time = time.monotonic()
        os.makedirs(directory, exist_ok=True)
        if jax.process_index() == 0:  # clear debris from a killed save
            for d in os.listdir(directory):
                if ".tmp-p" in d:
                    shutil.rmtree(os.path.join(directory, d),
                                  ignore_errors=True)

    def should_save(self, step: int) -> bool:
        if self.save_interval_seconds is not None:
            return (time.monotonic() - self._last_saved_time
                    >= self.save_interval_seconds)
        if self._last_saved_step is None:
            return step >= self.save_interval_steps
        return step - self._last_saved_step >= self.save_interval_steps

    def save(self, state_tree, step: int, extra_meta: Optional[dict] = None):
        path = save_sharded(state_tree, self.directory, step, extra_meta)
        self._last_saved_step = step
        self._last_saved_time = time.monotonic()
        self._prune()
        return path

    def maybe_save(self, state_tree, step: int,
                   extra_meta: Optional[dict] = None):
        if self.should_save(step):
            return self.save(state_tree, step, extra_meta)
        return None

    def restore_latest(self, mesh=None, shardings=None):
        return restore_sharded(self.directory, mesh=mesh,
                               shardings=shardings)

    def all_steps(self):
        out = []
        for d in os.listdir(self.directory):
            m = _STEP_DIR_RE.match(d)
            if m and os.path.isdir(os.path.join(self.directory, d)):
                out.append(int(m.group(1)))
        return sorted(out)

    def _prune(self):
        if jax.process_index() != 0:
            return
        steps = self.all_steps()
        k = self.keep_every_k_steps
        for s in steps[:-self.max_to_keep]:
            if k and s % k == 0:
                continue  # milestone checkpoint: kept forever
            shutil.rmtree(os.path.join(self.directory,
                                       f"step-{s:09d}"), ignore_errors=True)


class AsyncCheckpointManager(CheckpointManager):
    """Checkpointing off the training thread.

    `save` runs only the device->host snapshot (a memcpy of this process's
    owned shards) on the caller, then hands the snapshot to a background
    writer thread that does the npz serialization, atomic rename, dir
    fsync, `latest` update, and retention GC.  The step loop's stall per
    save drops from "full serialize+write" to "snapshot + enqueue"
    (probes/resilience_probe.py measures the ratio).

    - The in-flight queue is BOUNDED (`max_in_flight`, default 1): a writer
      that can't keep up applies backpressure instead of buffering an
      unbounded number of full model copies in host RAM.
    - `wait_until_finished()` blocks until every accepted save is durable
      (call before reading metrics that must include the save, and at exit).
    - A watchdog flags a write stuck longer than `watchdog_seconds`
      (wedged NFS mount, dead disk): the next save/wait raises
      ExecutionTimeoutError on the training thread instead of silently
      wedging the run with stale checkpoints.
    - Writer-thread exceptions are re-raised on the next save/wait — a save
      that failed on the background thread must not be silently dropped.

    Multi-process saves fall back to the synchronous path: the global
    publish barrier (sync_global_devices) must run where every process
    participates, not on a per-host writer thread.
    """

    def __init__(self, directory: str, max_to_keep: int = 2,
                 save_interval_steps: int = 100,
                 save_interval_seconds: Optional[float] = None,
                 keep_every_k_steps: Optional[int] = None,
                 max_in_flight: int = 1,
                 watchdog_seconds: float = 600.0):
        super().__init__(directory, max_to_keep, save_interval_steps,
                         save_interval_seconds, keep_every_k_steps)
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, max_in_flight))
        self._watchdog_seconds = watchdog_seconds
        self._cv = threading.Condition()
        self._outstanding = 0
        self._write_started: Optional[float] = None
        self._errors: list = []
        self._closed = False
        self._writer = threading.Thread(target=self._writer_loop,
                                        name="paddle_tpu-ckpt-writer",
                                        daemon=True)
        self._writer.start()

    # -- background writer ---------------------------------------------------
    def _writer_loop(self):
        from ..utils.monitor import stat_add
        while True:
            item = self._queue.get()
            if item is None:
                return
            manifest, shards = item
            with self._cv:
                self._write_started = time.monotonic()
            try:
                publish_snapshot(self.directory, manifest, shards)
                self._prune()
                stat_add("STAT_checkpoint_async_writes")
            except BaseException as e:  # surfaced on the training thread
                with self._cv:
                    self._errors.append(e)
            finally:
                with self._cv:
                    self._write_started = None
                    self._outstanding -= 1
                    _obs()[1].set(self._outstanding)
                    self._cv.notify_all()

    def _raise_pending(self):
        with self._cv:
            if self._errors:
                e = self._errors.pop(0)
                raise RuntimeError(
                    "async checkpoint write failed on the background "
                    f"writer: {type(e).__name__}: {e}") from e
            started = self._write_started
        if (started is not None and self._watchdog_seconds is not None
                and time.monotonic() - started > self._watchdog_seconds):
            from ..core.errors import ExecutionTimeoutError
            raise ExecutionTimeoutError(
                f"[ExecutionTimeout] async checkpoint write has been "
                f"running for over {self._watchdog_seconds:.0f}s (wedged "
                "filesystem?) — checkpoints are no longer landing")

    # -- API -----------------------------------------------------------------
    def save(self, state_tree, step: int, extra_meta: Optional[dict] = None):
        """Snapshot on the caller thread, write in the background.  Blocks
        only when `max_in_flight` earlier saves are still being written
        (backpressure), or re-raises a pending background failure."""
        if jax.process_count() > 1:
            return super().save(state_tree, step, extra_meta)
        self._raise_pending()
        if self._closed:
            raise RuntimeError("AsyncCheckpointManager is closed")
        from ..utils.monitor import stat_add
        stat_add("STAT_checkpoint_saves")
        stall_h, inflight_g = _obs()
        t0 = time.perf_counter()
        manifest, shards = snapshot_tree(state_tree, step, extra_meta)
        with self._cv:
            self._outstanding += 1
            inflight_g.set(self._outstanding)
        while True:
            try:
                # bounded put, re-checking the watchdog while blocked: a
                # wedged writer must surface as ExecutionTimeoutError on
                # the training thread, not as an eternal queue.put
                self._queue.put((manifest, shards), timeout=0.5)
                break
            except queue.Full:
                try:
                    self._raise_pending()
                except BaseException:
                    with self._cv:
                        self._outstanding -= 1
                        inflight_g.set(self._outstanding)
                        self._cv.notify_all()
                    raise
        # the training thread's stall: snapshot + (possibly backpressured)
        # enqueue — the background write itself is not a stall
        stall_h.observe(time.perf_counter() - t0)
        self._last_saved_step = step
        self._last_saved_time = time.monotonic()
        return os.path.join(self.directory, f"step-{step:09d}")

    def save_train_state(self, params, opt_state, step: int,
                         extra_meta: Optional[dict] = None, optimizer=None,
                         scaler=None, data_cursor: Optional[dict] = None):
        """Async analogue of module-level save_train_state (rng / scheduler /
        scaler / cursor extras included)."""
        extra = train_state_extras(optimizer, extra_meta, scaler, data_cursor)
        return self.save({"params": params, "opt": opt_state}, step, extra)

    def wait_until_finished(self, timeout: Optional[float] = None) -> bool:
        """Block until every accepted save is durably published.  Returns
        False on timeout; re-raises background write errors and fires the
        watchdog for a wedged write."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._outstanding > 0:
                wait = 0.5
                if deadline is not None:
                    wait = min(wait, deadline - time.monotonic())
                    if wait <= 0:
                        return False
                self._cv.wait(wait)
                self._raise_pending()  # Condition lock is an RLock
        self._raise_pending()
        return True

    def restore_latest(self, mesh=None, shardings=None):
        self.wait_until_finished()
        return super().restore_latest(mesh=mesh, shardings=shardings)

    def close(self, timeout: Optional[float] = None):
        """Flush pending writes and stop the writer thread.  Bounded even
        when the writer is wedged: the manager closes (further saves
        rejected), the sentinel is delivered best-effort, and the daemon
        writer thread is left to die with the process rather than hanging
        shutdown on a full queue."""
        if self._closed:
            return
        self._closed = True  # reject further saves even if the flush fails
        try:
            self.wait_until_finished(timeout)
        finally:
            try:
                self._queue.put_nowait(None)
            except queue.Full:
                pass  # wedged writer will never consume it; thread is daemon
            self._writer.join(timeout=5.0)


def train_epoch_range(n_epochs: int, manager: CheckpointManager):
    """Resume-aware epoch iterator (reference: acp.train_epoch_range,
    auto_checkpoint.py:71): yields only epochs not yet completed according to
    the newest checkpoint's metadata. The caller is responsible for calling
    `manager.save(state, step, extra_meta={"epoch": e})` at epoch ends."""
    start = 0
    restored = latest_step_dir(manager.directory)
    if restored is not None:
        with open(os.path.join(restored, "manifest.json")) as f:
            extra = json.load(f).get("extra", {})
        if "epoch" in extra:
            start = int(extra["epoch"]) + 1
    for e in range(start, n_epochs):
        yield e
