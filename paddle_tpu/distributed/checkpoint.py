"""Sharded + automatic checkpointing.

Reference: auto-checkpoint on preemption
(python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py:71 — epoch-range
context that snapshots train status to HDFS and resumes after restart) and the
PS checkpoint_notify machinery (operators/distributed_ops/checkpoint_notify_op.cc).

TPU-native design: parameters and optimizer states of a sharded train step
live as jax.Arrays distributed over a Mesh.  Saving gathers NOTHING: each
process writes only the addressable shards it owns (deduplicated by
replica_id), plus a JSON manifest of global shapes/dtypes/PartitionSpecs.
Restoring uses `jax.make_array_from_callback` so every device reads only its
own slice — works across topology changes by reassembling from the shard
files on demand.

Layout of a checkpoint directory:
    step-000042/
        manifest.json          global metadata (shapes, dtypes, specs, step)
        shards-p00000.npz      this process's owned shards
    latest                     text file naming the newest complete step dir

Writes are atomic: a temp dir is renamed into place only after the npz/json
are fully written, so a kill mid-save never corrupts the latest checkpoint.
"""
from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


# -- PartitionSpec (de)serialization ----------------------------------------

def _spec_to_json(spec) -> list:
    out = []
    for e in tuple(spec):
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            out.append(list(e))
        else:
            out.append(str(e))
    return out


def _spec_from_json(entries) -> P:
    out = []
    for e in entries:
        if isinstance(e, list):
            out.append(tuple(e))
        else:
            out.append(e)
    return P(*out)


def _index_key(name: str, index) -> str:
    starts = ",".join(str(0 if s.start is None else int(s.start))
                      for s in index)
    return f"{name}@{starts}"


# -- tree flattening (params + nested opt-state dicts) ----------------------

def _flatten(tree, prefix="", out=None):
    out = {} if out is None else out
    if isinstance(tree, dict):
        for k in sorted(tree):
            _flatten(tree[k], f"{prefix}{k}/", out)
    else:
        out[prefix[:-1]] = tree
    return out


def _unflatten(flat: Dict[str, object]):
    tree: Dict[str, object] = {}
    for key, v in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return tree


# -- save -------------------------------------------------------------------

def save_sharded(state_tree, directory: str, step: int = 0,
                 extra_meta: Optional[dict] = None) -> str:
    """Write a sharded checkpoint of a pytree of jax.Arrays (nested dicts).

    No host gather: each process saves only shards with replica_id == 0 among
    its addressable shards.  Returns the final step directory path.
    """
    flat = _flatten(state_tree)
    pidx = jax.process_index()
    step_dir = os.path.join(directory, f"step-{step:09d}")
    tmp_dir = step_dir + f".tmp-p{pidx:05d}"
    os.makedirs(tmp_dir, exist_ok=True)

    manifest = {"step": int(step), "arrays": {}, "extra": extra_meta or {},
                "n_processes": jax.process_count()}
    shards = {}
    for name, arr in flat.items():
        arr = jnp.asarray(arr)
        sharding = arr.sharding
        spec = (sharding.spec if isinstance(sharding, NamedSharding)
                else P())
        manifest["arrays"][name] = {
            "shape": list(arr.shape), "dtype": str(arr.dtype),
            "spec": _spec_to_json(spec),
        }
        for shard in getattr(arr, "addressable_shards", []):
            if shard.replica_id != 0:
                continue
            shards[_index_key(name, shard.index)] = np.asarray(shard.data)

    npz_name = f"shards-p{pidx:05d}.npz"
    np.savez(os.path.join(tmp_dir, npz_name), **shards)

    if jax.process_count() == 1:
        # atomic publish: manifest lands inside the tmp dir, one rename
        with open(os.path.join(tmp_dir, "manifest.json"), "w") as f:
            json.dump(manifest, f)
        if os.path.exists(step_dir):
            shutil.rmtree(step_dir)
        os.rename(tmp_dir, step_dir)
    else:
        # multi-host on a shared fs: every process lands its npz, then a
        # global barrier, THEN process 0 publishes manifest + latest — a
        # reader never sees a manifest without all its shards
        os.makedirs(step_dir, exist_ok=True)
        os.replace(os.path.join(tmp_dir, npz_name),
                   os.path.join(step_dir, npz_name))
        shutil.rmtree(tmp_dir, ignore_errors=True)
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(f"paddle_tpu-ckpt-{step}")
        if pidx == 0:
            # scrub stale shards from an earlier save with more processes
            # BEFORE publishing the manifest, so readers without the
            # n_processes filter can't overlay them
            n = jax.process_count()
            for f in os.listdir(step_dir):
                if (f.startswith("shards-p") and f.endswith(".npz")
                        and int(f[len("shards-p"):-len(".npz")]) >= n):
                    os.unlink(os.path.join(step_dir, f))
            _write_atomic(os.path.join(step_dir, "manifest.json"),
                          json.dumps(manifest))
    if pidx == 0:
        _write_atomic(os.path.join(directory, "latest"),
                      os.path.basename(step_dir))
    return step_dir


def _write_atomic(path: str, content: str):
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".")
    with os.fdopen(fd, "w") as f:
        f.write(content)
    os.replace(tmp, path)


# -- restore ----------------------------------------------------------------

class _ShardStore:
    """Lazily-opened shard files for one checkpoint step dir.

    Only shards-pNNNNN.npz with N < the manifest's n_processes are read: a
    re-save into an existing step dir from a smaller process count must not
    overlay stale higher-numbered shard files from the earlier save.
    """

    def __init__(self, step_dir: str, n_processes: Optional[int] = None):
        names = [f for f in sorted(os.listdir(step_dir))
                 if f.startswith("shards-p") and f.endswith(".npz")]
        if n_processes is not None:
            names = [f for f in names
                     if int(f[len("shards-p"):-len(".npz")]) < n_processes]
        self.files = [np.load(os.path.join(step_dir, f)) for f in names]
        self._full_cache: Dict[str, np.ndarray] = {}

    def lookup(self, name: str, index, shape, dtype):
        key = _index_key(name, index)
        want = tuple(
            (dim if s.stop is None else s.stop) - (0 if s.start is None
                                                   else s.start)
            for s, dim in zip(index, shape))
        for f in self.files:
            if key in f.files and f[key].shape == want:
                return f[key]
        return self._assemble(name, shape, dtype)[tuple(index)]

    def _assemble(self, name: str, shape, dtype) -> np.ndarray:
        """Topology changed between save and restore: rebuild the full array
        from whatever shards exist (correct, costs host memory for `name`)."""
        if name in self._full_cache:
            return self._full_cache[name]
        full = np.zeros(shape, dtype)
        covered = np.zeros(shape, bool)
        prefix = f"{name}@"
        for f in self.files:
            for key in f.files:
                if not key.startswith(prefix):
                    continue
                starts = [int(x) for x in key[len(prefix):].split(",")]
                data = f[key]
                idx = tuple(slice(s, s + d) for s, d in
                            zip(starts, data.shape))
                full[idx] = data
                covered[idx] = True
        if not covered.all():
            missing = covered.size - int(covered.sum())
            raise ValueError(
                f"checkpoint is incomplete for '{name}': {missing} of "
                f"{covered.size} elements have no shard (lost/partial "
                "shard file?)")
        self._full_cache[name] = full
        return full


def latest_step_dir(directory: str) -> Optional[str]:
    ptr = os.path.join(directory, "latest")
    if not os.path.exists(ptr):
        return None
    with open(ptr) as f:
        name = f.read().strip()
    step_dir = os.path.join(directory, name)
    return step_dir if os.path.isdir(step_dir) else None


def restore_sharded(directory: str, mesh: Optional[Mesh] = None,
                    shardings: Optional[dict] = None, step: Optional[int] = None):
    """Restore (state_tree, step, extra_meta) from a checkpoint directory.

    shardings: optional flat-or-nested dict overriding the saved
    PartitionSpecs (e.g. restoring onto a different mesh layout). When a mesh
    is given (or discoverable), arrays come back sharded; otherwise they are
    restored as host-local full arrays.
    """
    step_dir = (os.path.join(directory, f"step-{step:09d}") if step is not None
                else latest_step_dir(directory))
    if step_dir is None or not os.path.isdir(step_dir):
        return None
    with open(os.path.join(step_dir, "manifest.json")) as f:
        manifest = json.load(f)
    store = _ShardStore(step_dir, manifest.get("n_processes"))
    flat_shardings = _flatten(shardings) if shardings else {}

    out = {}
    for name, meta in manifest["arrays"].items():
        shape = tuple(meta["shape"])
        dtype = np.dtype(meta["dtype"])
        sharding = flat_shardings.get(name)
        if sharding is None and mesh is not None:
            spec = _spec_from_json(meta["spec"])
            # drop axes the restore mesh doesn't have
            entries = [e if _axes_exist(e, mesh) else None
                       for e in tuple(spec)]
            sharding = NamedSharding(mesh, P(*entries))
        if sharding is not None:
            arr = jax.make_array_from_callback(
                shape, sharding,
                lambda idx, n=name, sh=shape, dt=dtype:
                    store.lookup(n, idx, sh, dt))
        else:
            arr = jnp.asarray(store._assemble(name, shape, dtype))
        out[name] = arr
    return _unflatten(out), manifest["step"], manifest.get("extra", {})


def _axes_exist(entry, mesh: Mesh) -> bool:
    if entry is None:
        return True
    names = entry if isinstance(entry, (tuple, list)) else (entry,)
    return all(n in mesh.shape for n in names)


# -- train-step glue (shared by jit.TrainStep / parallel.ShardedTrainStep) --

_STEP_DIR_RE = re.compile(r"^step-(\d+)$")


def save_train_state(directory: str, params, opt_state, step: int,
                     extra_meta: Optional[dict] = None,
                     optimizer=None) -> str:
    """Snapshot params + optimizer state + the host rng stream + the LR
    scheduler state, so a resumed run reproduces the uninterrupted one even
    with dropout and a warmup/decay schedule active."""
    from ..core import rng as _rng
    from ..utils.monitor import stat_add
    stat_add("STAT_checkpoint_saves")
    extra = dict(extra_meta or {})
    extra["__rng__"] = np.asarray(_rng.get_rng_state()).tolist()
    sched = getattr(optimizer, "_lr_scheduler", None)
    if sched is not None:
        extra["__lr_sched__"] = sched.state_dict()
    return save_sharded({"params": params, "opt": opt_state}, directory,
                        step, extra)


def restore_train_extras(optimizer, step: int, extra: dict) -> dict:
    """Apply the non-array training state (step count, rng stream, LR
    scheduler) from a checkpoint's extra dict.  Shared by every train-step
    restore path.  Mutates `extra` (pops the internal keys); returns the
    user-facing meta dict."""
    from ..core import rng as _rng
    optimizer._step_count = step
    rng_state = extra.pop("__rng__", None)
    if rng_state is not None:
        _rng.set_rng_state(jnp.asarray(rng_state, jnp.uint32))
    sched_state = extra.pop("__lr_sched__", None)
    if sched_state is not None:
        sched = getattr(optimizer, "_lr_scheduler", None)
        if sched is not None:
            sched.set_state_dict(sched_state)
    return {"step": step, **extra}


def apply_train_state(model, optimizer, restored):
    """Write a restore_sharded result back into model/optimizer/rng/scheduler.
    Returns (meta_dict, opt_state_tree)."""
    tree, step, extra = restored
    sd = model.state_dict()
    for k, v in tree["params"].items():
        sd[k]._set_data(v)
    meta = restore_train_extras(optimizer, step, extra)
    # stateless optimizers (SGD) save empty per-param dicts, which the
    # flatten/unflatten roundtrip drops — callers merge over a fresh
    # init_opt_state structure via merge_opt_state
    return meta, tree.get("opt", {})


def merge_opt_state(fresh: dict, restored: dict) -> dict:
    """Per-param merge: restored entries win; params whose state vanished in
    the save (empty dicts) keep the freshly initialized structure."""
    return {k: restored.get(k, fresh[k]) for k in fresh}


# -- checkpoint manager + auto-checkpoint -----------------------------------

class CheckpointManager:
    """Periodic sharded checkpointing with retention and resume.

    The TPU-native answer to auto_checkpoint.py: training state snapshots
    every `save_interval_steps` (or `save_interval_seconds`), keeps the last
    `max_to_keep`, and `restore_latest` resumes bit-exact after a kill.
    """

    def __init__(self, directory: str, max_to_keep: int = 2,
                 save_interval_steps: int = 100,
                 save_interval_seconds: Optional[float] = None):
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.save_interval_steps = save_interval_steps
        self.save_interval_seconds = save_interval_seconds
        self._last_saved_step = None
        self._last_saved_time = time.monotonic()
        os.makedirs(directory, exist_ok=True)
        if jax.process_index() == 0:  # clear debris from a killed save
            for d in os.listdir(directory):
                if ".tmp-p" in d:
                    shutil.rmtree(os.path.join(directory, d),
                                  ignore_errors=True)

    def should_save(self, step: int) -> bool:
        if self.save_interval_seconds is not None:
            return (time.monotonic() - self._last_saved_time
                    >= self.save_interval_seconds)
        if self._last_saved_step is None:
            return step >= self.save_interval_steps
        return step - self._last_saved_step >= self.save_interval_steps

    def save(self, state_tree, step: int, extra_meta: Optional[dict] = None):
        path = save_sharded(state_tree, self.directory, step, extra_meta)
        self._last_saved_step = step
        self._last_saved_time = time.monotonic()
        self._prune()
        return path

    def maybe_save(self, state_tree, step: int,
                   extra_meta: Optional[dict] = None):
        if self.should_save(step):
            return self.save(state_tree, step, extra_meta)
        return None

    def restore_latest(self, mesh=None, shardings=None):
        return restore_sharded(self.directory, mesh=mesh,
                               shardings=shardings)

    def all_steps(self):
        out = []
        for d in os.listdir(self.directory):
            m = _STEP_DIR_RE.match(d)
            if m and os.path.isdir(os.path.join(self.directory, d)):
                out.append(int(m.group(1)))
        return sorted(out)

    def _prune(self):
        if jax.process_index() != 0:
            return
        steps = self.all_steps()
        for s in steps[:-self.max_to_keep]:
            shutil.rmtree(os.path.join(self.directory,
                                       f"step-{s:09d}"), ignore_errors=True)


def train_epoch_range(n_epochs: int, manager: CheckpointManager):
    """Resume-aware epoch iterator (reference: acp.train_epoch_range,
    auto_checkpoint.py:71): yields only epochs not yet completed according to
    the newest checkpoint's metadata. The caller is responsible for calling
    `manager.save(state, step, extra_meta={"epoch": e})` at epoch ends."""
    start = 0
    restored = latest_step_dir(manager.directory)
    if restored is not None:
        with open(os.path.join(restored, "manifest.json")) as f:
            extra = json.load(f).get("extra", {})
        if "epoch" in extra:
            start = int(extra["epoch"]) + 1
    for e in range(start, n_epochs):
        yield e
