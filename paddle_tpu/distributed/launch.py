"""Launcher CLI: ``python -m paddle_tpu.distributed.launch train.py``.

Reference: python/paddle/distributed/fleet/launch.py:196,248,319 +
launch_utils.py:56,257,429 — builds a Cluster/Pod model from --ips/--gpus,
starts one subprocess per device with PADDLE_TRAINER_ID/... env vars,
redirects logs, and watches children (tearing the pod down on any failure —
the launcher IS the reference's failure-detection story for collective jobs).

TPU-native: one process per *host* (not per chip; XLA owns all local chips),
`jax.distributed.initialize` replaces the nccl-id exchange.  --nproc_per_node
with JAX_PLATFORMS=cpu still works for CI-style multi-process testing.
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def _parse_args(argv=None):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--ips", type=str, default="127.0.0.1",
                   help="comma-separated host ips (reference --ips)")
    p.add_argument("--host_rank", type=int, default=0,
                   help="this host's index into --ips")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per host (1 for TPU; >1 for CPU testing)")
    p.add_argument("--coordinator_port", type=int, default=12355)
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def start_local_trainers(args) -> int:
    """Fork local trainer processes with PADDLE_* env (launch_utils.py:429)."""
    ips = args.ips.split(",")
    nnodes = len(ips)
    nproc = args.nproc_per_node
    world = nnodes * nproc
    coordinator = f"{ips[0]}:{args.coordinator_port}"
    endpoints = ",".join(f"{ip}:{args.coordinator_port + i}"
                         for ip in ips for i in range(nproc))
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)

    procs = []
    for local in range(nproc):
        rank = args.host_rank * nproc + local
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
            "PADDLE_CURRENT_ENDPOINT":
                f"{ips[args.host_rank]}:{args.coordinator_port + local}",
            "PADDLE_COORDINATOR": coordinator,
        })
        cmd = [sys.executable, "-u", args.training_script,
               *args.training_script_args]
        log = (open(os.path.join(args.log_dir, f"worker.{rank}.log"), "w")
               if args.log_dir else None)
        procs.append((rank, subprocess.Popen(cmd, env=env, stdout=log,
                                             stderr=subprocess.STDOUT
                                             if log else None), log))

    # watch loop: any child failing tears down the pod
    # (reference launch_utils.py watch_local_trainers)
    code = 0
    try:
        while procs:
            alive = []
            for rank, proc, log in procs:
                ret = proc.poll()
                if ret is None:
                    alive.append((rank, proc, log))
                elif ret != 0:
                    print(f"[launch] worker {rank} FAILED (exit {ret}); "
                          "terminating pod", file=sys.stderr)
                    code = ret
                    for _, p2, _ in procs:
                        if p2.poll() is None:
                            p2.send_signal(signal.SIGTERM)
                    procs = []
                    alive = []
                    break
            procs = alive
            if procs:
                time.sleep(1)
    except KeyboardInterrupt:
        for _, p, _ in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        code = 130
    return code


def launch(argv=None) -> int:
    args = _parse_args(argv)
    return start_local_trainers(args)


if __name__ == "__main__":
    sys.exit(launch())
