"""Fleet-grade serving: a replica manager + router over N ServingEngines.

The gateway (PR 6) made ONE engine production-shaped; this module makes
the engine COUNT a runtime variable.  `FleetRouter` fronts N
`ServingEngine` replicas — in-process for tier-1/CPU, with every
interaction funneled through a surface a subprocess replica could
implement over IPC — and `ReplicaManager` owns their lifecycle:

- **Routing** is least-loaded (occupancy + queue depth) with session
  affinity: requests sharing a ``session`` key stick to one replica
  while it stays healthy (KV-prefix locality once the radix cache
  lands), and re-home automatically when it is fenced.
- **Health** is positive evidence, not hope: a replica is routable only
  after `warmup()` reports every program compiled (`engine.warm`), its
  per-step wall time feeds an EWMA that fences a browned-out replica
  (`slow_threshold_ms`), and a step that RAISES is a crash — the
  replica is fenced immediately.  Every successful step also beats a
  heartbeat, exported as `heartbeat_age_s` telemetry: in-process the
  raising step IS the liveness verdict (one thread drives everyone), so
  age-based fencing is the subprocess deployment's job, alarmed on this
  signal.
- **Failover** generalizes the PR-6 preempt/restore snapshot into the
  run-transfer codec (serving/transfer.py): a fenced-but-alive replica's
  residents are preempted, encoded, and restored onto surviving
  replicas, resuming bit-identical to an uninterrupted run.  A CRASHED
  replica's snapshots die with it: each lost run is re-prefilled from
  its prompt on a healthy replica when the request opted in
  (``resubmit=True``, greedy-only — the fleet forwards only the
  not-yet-delivered suffix, so the stream stays bit-identical
  end-to-end), otherwise it fails with the typed `ReplicaLostError`.
  Either way: NEVER a hung consumer.
- **Draining** (`drain(rid)`) stops admissions, migrates residents to
  peers (or lets them finish in place when the fleet is full), then
  closes the empty replica — which makes rollout zero-downtime: boot a
  replacement from a PR-9 program set (seconds, zero compiles), warm
  it, add it, drain the old one (`rollout()` sequences this across the
  whole fleet).

The in-process threading contract mirrors the gateway's: ONE thread
drives `step()` — either the fleet's own `start()` loop or a
`ServingGateway` fronting the router (the router implements the
engine-facing surface the gateway consumes: `make_request`,
`try_admit`, `preempt_slot`/`restore_run`, `scheduler` depth/occupancy
views, `step`, `_abort_all`).  `submit` is safe from any thread.

Chaos knobs (utils.faults): ``PDTPU_FAULT_REPLICA_CRASH=replica:tick``
(SIGKILL-equivalent mid-decode loss) and
``PDTPU_FAULT_REPLICA_SLOW=ms[:every_n[:replica]]`` (brownout) — the
fleet probe (probes/fleet_probe.py) drives both under Poisson traffic
plus a full rolling restart.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..core.errors import InvalidArgumentError, UnavailableError
from ..utils import faults
from ..utils.monitor import stat_add
from .engine import PreemptedRun, ServingEngine
from .request import Request, Response, RequestCancelled
from .scheduler import DeadlineExceededError, QueueFullError
from .transfer import (RunTransferError, check_compatible, decode_run,
                       encode_run)

__all__ = ["FleetRouter", "ReplicaManager", "Replica", "ReplicaLostError"]

# replica lifecycle states
BOOTING = "booting"      # added, not yet warm — never routed to
HEALTHY = "healthy"      # warm + fast: routable
DEGRADED = "degraded"    # fenced by slow-step health; residents migrate
DRAINING = "draining"    # admissions stopped; residents migrate/finish
CRASHED = "crashed"      # step raised / injected kill; state abandoned
CLOSED = "closed"        # engine closed (drain finished or shutdown)

_LIVE = (BOOTING, HEALTHY, DEGRADED, DRAINING)


class ReplicaLostError(UnavailableError):
    """The replica serving this run crashed and its KV snapshot was lost
    with it; the request did not opt into resubmission (or no capacity
    was left to resubmit into).  The typed terminal state — retry the
    request if it is idempotent for you."""
    code = "Unavailable"


class _InjectedReplicaCrash(RuntimeError):
    """PDTPU_FAULT_REPLICA_CRASH fired: the SIGKILL-equivalent for an
    in-process replica (raised BEFORE the engine can fail its runs)."""


class _ForwardingResponse(Response):
    """The resubmission bridge: a crashed replica's lost greedy run is
    replayed from its prompt on a survivor, and this response receives
    the replay — swallowing the first `skip` tokens (already delivered
    to the consumer before the crash) and forwarding the rest into the
    ORIGINAL response object the consumer is iterating.  Greedy decode
    is deterministic in the prompt, so the swallowed prefix is
    bit-identical to what was already delivered and the consumer sees
    one seamless, bit-identical stream.

    It is itself a full Response (the serving engine's emit/sweep
    bookkeeping runs against it), and chains: if the replay's replica
    crashes too, the next resubmission targets the original response
    with a recomputed skip."""

    def __init__(self, request: Request, target: Response, skip: int):
        super().__init__(request)
        self._target = target
        self._skip = int(skip)

    @property
    def cancelled(self) -> bool:
        # the consumer cancels the ORIGINAL stream; the engine sweeping
        # the replay must honor it
        return self._cancel_requested or self._target.cancelled

    def _push_token(self, tok: int, logp: float = 0.0):
        super()._push_token(tok, logp)
        if self._skip > 0:
            self._skip -= 1
            return
        self._target._push_token(tok, logp)

    def _finish(self, reason: str):
        super()._finish(reason)
        self._target._finish(reason)

    def _fail(self, exc: BaseException):
        super()._fail(exc)
        self._target._fail(exc)


_obs_handles = None


def _obs():
    """Cached fleet observability handles (registry.reset() zeroes the
    values in place, handles stay valid)."""
    global _obs_handles
    if _obs_handles is None:
        from ..observability import metrics as _m
        _obs_handles = {
            "up": _m.gauge(
                "serving_replica_up",
                "1 while the replica is routable (healthy + warm), else 0",
                labelnames=("replica",)),
            "inflight": _m.gauge(
                "serving_replica_inflight",
                "decoding slots + queued requests on the replica",
                labelnames=("replica",)),
            "replicas_up": _m.gauge(
                "fleet_replicas_up", "routable replicas in the fleet"),
            "failovers": _m.counter(
                "fleet_failovers_total",
                "replica fences (crash or brownout) that triggered "
                "failover handling"),
            "migrated": _m.counter(
                "fleet_migrated_runs_total",
                "in-flight runs moved between replicas via the run "
                "transfer codec"),
        }
    return _obs_handles


class Replica:
    """One managed ServingEngine + its health state.  `rid` is a
    monotonically increasing integer, never reused — it is also the
    index the replica fault knobs target."""

    def __init__(self, rid: int, engine: ServingEngine):
        self.id = rid
        self.engine = engine
        self.state = HEALTHY if engine.warm else BOOTING
        self.steps = 0
        self.last_beat = time.monotonic()
        self.step_ewma: Optional[float] = None  # seconds
        self.fast_steps = 0
        self.fence_reason: Optional[str] = None
        self.created_at = time.monotonic()

    def routable(self) -> bool:
        return self.state == HEALTHY and self.engine.warm

    def load(self) -> int:
        s = self.engine.scheduler
        return s.occupancy() + s.queue_depth()

    def note_step_time(self, dt: float, threshold: Optional[float]):
        a = 0.3
        self.step_ewma = (dt if self.step_ewma is None
                          else a * dt + (1 - a) * self.step_ewma)
        if threshold is not None:
            if dt < 0.5 * threshold:
                self.fast_steps += 1
            else:
                self.fast_steps = 0

    def snapshot(self) -> Dict:
        return {
            "state": self.state,
            "warm": bool(self.engine.warm),
            "occupancy": self.engine.scheduler.occupancy(),
            "queue_depth": self.engine.scheduler.queue_depth(),
            "steps": self.steps,
            "step_ewma_ms": (None if self.step_ewma is None
                             else round(self.step_ewma * 1e3, 3)),
            "heartbeat_age_s": round(time.monotonic() - self.last_beat, 3),
            "fence_reason": self.fence_reason,
            "post_warmup_compiles": (self.engine.post_warmup_compiles()
                                     if self.engine.warm else None),
        }


class ReplicaManager:
    """Replica lifecycle: stepping, health, fencing, migration, drain.

    All mutation of replica state runs on the driving thread (the fleet
    loop or the gateway loop) except `add`/`drain`/`close`, which only
    flip state flags under the lock — the driving thread picks the
    change up on its next tick."""

    def __init__(self, slow_threshold_ms: Optional[float] = None,
                 probation_steps: int = 5):
        self._replicas: Dict[int, Replica] = {}
        self._next_id = 0
        self._lock = threading.Lock()
        self._ticks = 0
        self.slow_threshold_s = (None if slow_threshold_ms is None
                                 else float(slow_threshold_ms) / 1e3)
        self.probation_steps = int(probation_steps)
        # runs preempted off a fenced replica that no peer could hold
        # yet (paged-block shortfall): retried every tick, swept for
        # cancel/deadline, failed terminally at close
        self._parked: List[PreemptedRun] = []
        self._n = {"failovers": 0, "migrated": 0, "resubmits": 0,
                   "lost": 0, "reroutes": 0, "drains": 0}

    # -- membership ---------------------------------------------------
    def add(self, engine: ServingEngine) -> Replica:
        if engine._thread is not None:
            raise InvalidArgumentError(
                "replica engine loop already started; the fleet drives "
                "engine.step() itself — construct the engine without "
                "start()")
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            rep = Replica(rid, engine)
            self._replicas[rid] = rep
        self._publish_up(rep)
        return rep

    def get(self, rid: int) -> Optional[Replica]:
        with self._lock:
            return self._replicas.get(rid)

    def replicas(self, states=None) -> List[Replica]:
        with self._lock:
            reps = list(self._replicas.values())
        if states is None:
            return reps
        return [r for r in reps if r.state in states]

    def routable(self) -> List[Replica]:
        return [r for r in self.replicas((HEALTHY,)) if r.routable()]

    def remove(self, rid: int):
        """Forget a closed/crashed replica (rollout teardown)."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                return
            if rep.state not in (CLOSED, CRASHED):
                raise InvalidArgumentError(
                    f"replica {rid} is {rep.state}; drain it before "
                    "remove (or let crash handling finish)")
            del self._replicas[rid]
        _obs()["up"].labels(replica=str(rid)).set(0)
        self._publish_counts()

    def warm_all(self) -> Dict[int, Dict]:
        """warmup() every not-yet-warm replica; booting replicas become
        healthy (routable) once every program is compiled."""
        reports = {}
        for rep in self.replicas(_LIVE):
            if not rep.engine.warm:
                reports[rep.id] = rep.engine.warmup()
            if rep.state == BOOTING and rep.engine.warm:
                rep.state = HEALTHY
                self._publish_up(rep)
        self.refresh_warm_marks()
        return reports

    def refresh_warm_marks(self):
        """Re-baseline every warm replica's post-warmup compile marks.
        The observability program registry is process-global, so replica
        B's warmup compiles would otherwise count against replica A's
        post-warmup-zero contract (`serving_decode` is one registry
        entry, N replicas).  Called after every membership warm event
        (warm_all, rollout boot), which makes
        `post_warmup_compiles()` mean: compiles since the fleet's most
        recent warmup — still exactly the zero-compiles-under-traffic
        fleet contract."""
        for rep in self.replicas(_LIVE):
            if rep.engine.warm:
                rep.engine._warm_marks = rep.engine._compile_marks()

    def drain(self, rid: int):
        """Fence `rid` for graceful removal: no new admissions; queued
        requests re-route now, residents migrate (or finish in place)
        over the next ticks, then the engine closes."""
        rep = self.get(rid)
        if rep is None:
            raise InvalidArgumentError(f"no replica {rid}")
        if rep.state not in (BOOTING, HEALTHY, DEGRADED):
            return
        rep.state = DRAINING
        rep.fence_reason = "drain"
        self._n["drains"] += 1
        stat_add("STAT_fleet_drains")
        self._publish_up(rep)
        # queued-but-never-prefilled work lost nothing: hand it to peers
        # — but the draining replica is ALIVE, so when no peer has queue
        # space the entry goes back on its own queue and is served
        # before the drain completes (the same finish-in-place policy
        # residents get; zero-drop rollout must hold under queue
        # pressure too)
        for req, resp in rep.engine.scheduler.drain_pending():
            self._reroute(req, resp, exclude_id=rid,
                          fallback_engine=rep.engine)

    # -- the driving tick ---------------------------------------------
    def tick(self) -> bool:
        """One fleet iteration on the driving thread: step every live
        replica (crash fault + brownout fault consulted per step, wall
        time fed to health), fence what the health verdicts demand,
        migrate residents off fenced replicas, retry parked runs, close
        drained-empty replicas."""
        self._ticks += 1
        did = False
        crash_cfg = faults.replica_crash_config()
        for rep in self.replicas(_LIVE):
            if rep.state == BOOTING:
                continue
            if (rep.state == DEGRADED and not rep.engine.has_work()
                    and self._ticks % 16):
                # probation sampling: an idle fenced replica is stepped
                # only occasionally, so a browned-out replica's injected
                # step latency cannot keep stalling the shared loop
                continue
            try:
                # the brownout sleep counts INTO the measured step time
                # (it models a slow replica; health must see it)
                t0 = time.perf_counter()
                faults.maybe_slow_replica(rep.id, rep.steps)
                if crash_cfg is not None and crash_cfg == (rep.id,
                                                           rep.steps):
                    rep.steps += 1
                    raise _InjectedReplicaCrash(
                        f"replica {rep.id} lost at tick {rep.steps - 1} "
                        "(PDTPU_FAULT_REPLICA_CRASH)")
                stepped = rep.engine.step()
                dt = time.perf_counter() - t0
                rep.steps += 1
                rep.last_beat = time.monotonic()
                rep.note_step_time(dt, self.slow_threshold_s)
                did = stepped or did
            except BaseException as e:  # noqa: BLE001 — fence, never hang
                self._on_crash(rep, e)
                did = True
        self._update_health()
        did = self._pump_migrations() or did
        did = self._pump_parked() or did
        self._sweep_parked()
        did = self._finish_drains() or did
        self._publish_inflight()
        return did

    # -- health --------------------------------------------------------
    def _update_health(self):
        thr = self.slow_threshold_s
        if thr is None:
            return
        for rep in self.replicas((HEALTHY, DEGRADED)):
            if (rep.state == HEALTHY and rep.steps >= 3
                    and rep.step_ewma is not None and rep.step_ewma > thr):
                rep.state = DEGRADED
                rep.fence_reason = (
                    f"slow: step EWMA {rep.step_ewma * 1e3:.1f}ms > "
                    f"{thr * 1e3:.1f}ms")
                self._n["failovers"] += 1
                stat_add("STAT_fleet_failovers")
                _obs()["failovers"].inc()
                self._publish_up(rep)
            elif (rep.state == DEGRADED and rep.step_ewma is not None
                    and rep.step_ewma < 0.5 * thr
                    and rep.fast_steps >= self.probation_steps):
                # brownout over: probation passed, return to rotation
                rep.state = HEALTHY
                rep.fence_reason = None
                self._publish_up(rep)

    def _on_crash(self, rep: Replica, exc: BaseException):
        """SIGKILL-equivalent loss: the engine had no chance to fail its
        runs and its device state is gone.  Fence it, then give every
        resident stream a future — resubmission for greedy opt-ins,
        the typed ReplicaLostError for the rest, a plain re-route for
        queued work that never started.  Parked OOM snapshots count as
        lost too: in the real deployment they lived in the dead
        process."""
        rep.state = CRASHED
        rep.fence_reason = repr(exc)
        self._n["failovers"] += 1
        stat_add("STAT_fleet_failovers")
        _obs()["failovers"].inc()
        self._publish_up(rep)
        engine = rep.engine
        lost = [(run.req, run.resp) for run in engine._slots.values()]
        # release the scheduler's host-side slot bookkeeping too: the
        # engine is abandoned, but its occupancy gauge / slots-active
        # stat / Request refs must not be pinned forever by a dead
        # replica that stays listed until remove()
        for slot in list(engine._slots):
            engine.scheduler.release(slot)
        engine._slots.clear()
        if engine.kv == "paged":
            lost.extend((p.req, p.resp) for p in engine._oom_paused)
            engine._oom_paused = []
        for req, resp in lost:
            self._failover_lost(req, resp, rep.id)
        # queued-but-never-prefilled: nothing was delivered, re-route
        # (the in-process queue survives; a subprocess router holds the
        # same queue on ITS side of the wire, so the semantics carry)
        for req, resp in engine.scheduler.drain_pending():
            self._reroute(req, resp, exclude_id=rep.id)

    def _failover_lost(self, req: Request, resp: Response, crashed_id: int):
        produced = len(resp.tokens_so_far())
        if req.resubmit and req.greedy:
            if self._resubmit(req, resp, produced, crashed_id):
                self._n["resubmits"] += 1
                stat_add("STAT_fleet_resubmits")
                return
        self._n["lost"] += 1
        stat_add("STAT_fleet_lost_runs")
        resp._fail(ReplicaLostError(
            f"request {req.id}: replica {crashed_id} crashed mid-decode "
            f"and its run snapshot was lost ({produced} tokens were "
            "delivered); "
            + ("no surviving replica could take the resubmission"
               if req.resubmit and req.greedy else
               "submit with resubmit=True (greedy) to opt into "
               "re-prefill-from-prompt recovery")))

    def _resubmit(self, req: Request, resp: Response, produced: int,
                  crashed_id: int) -> bool:
        """Replay a lost greedy run from its prompt on a survivor; the
        forwarding response swallows the `produced` already-delivered
        tokens so the consumer's stream continues bit-identically."""
        # chains: if resp is itself a forwarding bridge (second crash),
        # target the ORIGINAL stream with a recomputed skip — the
        # bridge's internal token count equals what the original has
        # seen end-to-end
        target = resp._target if isinstance(resp, _ForwardingResponse) \
            else resp
        for rep in self._targets(exclude_id=crashed_id):
            engine = rep.engine
            try:
                shadow, _ = engine.make_request(
                    req.prompt, req.max_new_tokens,
                    decode_strategy="greedy_search",
                    eos_token_id=req.eos_token_id, seed=req.seed,
                    priority=req.priority, tenant=req.tenant,
                    spec=(req.spec if engine.draft_model is not None
                          else False),
                    session=req.session, resubmit=True)
            except Exception:
                continue
            # the original deadline keeps ticking from the original
            # submission — a crash must not silently extend a budget
            shadow.deadline = req.deadline
            fwd = _ForwardingResponse(shadow, target, skip=produced)
            try:
                engine.scheduler.submit(shadow, fwd)
            except QueueFullError:
                continue
            return True
        return False

    def _reroute(self, req: Request, resp: Response, exclude_id: int,
                 fallback_engine=None):
        """Re-home a queued (never-prefilled) request.  `fallback_engine`
        is the still-alive source engine of a DRAIN: with no peer queue
        space the request stays on it and is served before the drain
        completes.  A CRASH has no fallback — the engine is gone — so
        exhausting the peers is the typed terminal state."""
        for rep in self._targets(exclude_id=exclude_id):
            try:
                rep.engine.scheduler.submit(req, resp)
            except QueueFullError:
                continue
            self._n["reroutes"] += 1
            stat_add("STAT_fleet_reroutes")
            return
        if fallback_engine is not None:
            try:
                # its queue was just drained, so space exists
                fallback_engine.scheduler.submit(req, resp)
                return
            except QueueFullError:
                pass
        self._n["lost"] += 1
        stat_add("STAT_fleet_lost_runs")
        resp._fail(ReplicaLostError(
            f"request {req.id}: replica {exclude_id} was fenced before "
            "prefill and no surviving replica had queue space"))

    def _targets(self, exclude_id: Optional[int] = None) -> List[Replica]:
        reps = [r for r in self.routable() if r.id != exclude_id]
        reps.sort(key=lambda r: (r.load(), r.id))
        return reps

    # -- migration -----------------------------------------------------
    def _pump_migrations(self) -> bool:
        """Move residents off fenced-but-alive replicas (drain or
        brownout) through the run-transfer codec.  A run is only
        preempted once a peer with a free slot exists; a paged-block
        shortfall at restore parks the snapshot for retry instead of
        dropping it."""
        did = False
        for rep in self.replicas((DRAINING, DEGRADED)):
            for slot in sorted(rep.engine._slots):
                target = self._pick_slot_target(exclude_id=rep.id)
                if target is None:
                    break  # fleet full: residents finish in place
                run = rep.engine._slots.get(slot)
                if run is None:
                    continue
                paused = rep.engine.preempt_slot(slot)
                blob = encode_run(paused)
                try:
                    snap = decode_run(blob, req=paused.req,
                                      resp=paused.resp,
                                      engine=target.engine)
                except RunTransferError as e:
                    # incompatible peer: the run must fail typed, not be
                    # written into a pool it does not fit
                    self._n["lost"] += 1
                    stat_add("STAT_fleet_lost_runs")
                    paused.resp._fail(e)
                    did = True
                    continue
                if target.engine.restore_run(snap):
                    snap.req.migrations += 1
                    self._n["migrated"] += 1
                    stat_add("STAT_fleet_migrated_runs")
                    _obs()["migrated"].inc()
                else:
                    self._parked.append(snap)
                did = True
        return did

    def _pick_slot_target(self, exclude_id: int) -> Optional[Replica]:
        cands = [r for r in self._targets(exclude_id)
                 if r.engine.scheduler.free_slot_count() > 0]
        return cands[0] if cands else None

    def _pump_parked(self) -> bool:
        did = False
        still = []
        for snap in self._parked:
            placed = False
            for rep in self._targets():
                if rep.engine.scheduler.free_slot_count() <= 0:
                    continue
                if rep.engine.restore_run(snap):
                    snap.req.migrations += 1
                    self._n["migrated"] += 1
                    stat_add("STAT_fleet_migrated_runs")
                    _obs()["migrated"].inc()
                    placed = did = True
                    break
            if not placed:
                still.append(snap)
        self._parked = still
        return did

    def _sweep_parked(self):
        """Parked snapshots still honor cancel/deadline — a run waiting
        out a full fleet must reach its terminal state on time."""
        keep = []
        for p in self._parked:
            if p.resp.cancelled:
                p.resp._fail(RequestCancelled(
                    f"request {p.req.id} cancelled while parked for "
                    "replica migration"))
            elif p.req.deadline is not None and p.req.deadline.expired():
                p.resp._fail(DeadlineExceededError(
                    f"request {p.req.id} deadline "
                    f"({p.req.deadline.seconds}s) expired while parked "
                    "for replica migration"))
            else:
                keep.append(p)
        self._parked = keep

    def _finish_drains(self) -> bool:
        did = False
        for rep in self.replicas((DRAINING,)):
            if not rep.engine.has_work():
                rep.engine.close()
                rep.state = CLOSED
                self._publish_up(rep)
                did = True
        return did

    # -- shutdown ------------------------------------------------------
    def abort_all(self, make_exc: Callable):
        for rep in self.replicas(_LIVE):
            rep.engine._abort_all(make_exc)
        parked, self._parked = self._parked, []
        for p in parked:
            p.resp._fail(make_exc(p.req))

    def close_all(self):
        for rep in self.replicas(_LIVE):
            rep.engine.close()
            rep.state = CLOSED
            self._publish_up(rep)
        parked, self._parked = self._parked, []
        for p in parked:
            p.resp._fail(RequestCancelled(
                f"request {p.req.id} aborted: fleet closed while the run "
                "was parked for migration"))

    # -- observability -------------------------------------------------
    def _publish_up(self, rep: Replica):
        _obs()["up"].labels(replica=str(rep.id)).set(
            1 if rep.routable() else 0)
        self._publish_counts()

    def _publish_counts(self):
        _obs()["replicas_up"].set(len(self.routable()))

    def _publish_inflight(self):
        obs = _obs()
        for rep in self.replicas(_LIVE):
            obs["inflight"].labels(replica=str(rep.id)).set(rep.load())

    def counters(self) -> Dict:
        return dict(self._n, parked=len(self._parked))


class _FleetSchedulerView:
    """The slice of RequestScheduler the gateway's signals consume,
    aggregated over the fleet: free slots on ROUTABLE replicas only
    (fenced capacity must not attract admissions), occupancy and queue
    depth over everything still alive (that work is real)."""

    def __init__(self, manager: ReplicaManager):
        self._m = manager

    def free_slot_count(self) -> int:
        return sum(r.engine.scheduler.free_slot_count()
                   for r in self._m.routable())

    def occupancy(self) -> int:
        return sum(r.engine.scheduler.occupancy()
                   for r in self._m.replicas(_LIVE))

    def queue_depth(self) -> int:
        return sum(r.engine.scheduler.queue_depth()
                   for r in self._m.replicas(_LIVE))

    def has_work(self) -> bool:
        return any(r.engine.scheduler.has_work()
                   for r in self._m.replicas(_LIVE))


class FleetRouter:
    """N replicas behind one front door.

    ::

        fleet = FleetRouter([make_engine() for _ in range(3)],
                            slow_threshold_ms=50)
        fleet.warmup()                  # all replicas routable
        fleet.start()                   # or front it with ServingGateway
        r = fleet.submit(prompt, 64, session="user-7", resubmit=True)
        for tok in r: ...
        fleet.rollout(lambda: ServingEngine(model, program_set=path, ...))
        fleet.close()

    Implements the engine-facing surface `ServingGateway` consumes, so
    ``ServingGateway(fleet, ...)`` turns the PR-6 multi-tenant front
    door into a cluster front door — the gateway's loop drives
    `fleet.step()` exactly as it drove a single engine's."""

    def __init__(self, replicas=(),
                 slow_threshold_ms: Optional[float] = None,
                 affinity: bool = True, max_sessions: int = 4096):
        self.manager = ReplicaManager(slow_threshold_ms=slow_threshold_ms)
        for engine in replicas:
            self.manager.add(engine)
        self._affinity_enabled = bool(affinity)
        # LRU-bounded: one entry per live session key, refreshed on use —
        # a long-lived fleet serving millions of distinct users must not
        # grow an entry per user ever seen
        self._affinity: Dict[str, int] = {}
        self._max_sessions = max(1, int(max_sessions))
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._work = threading.Event()
        self._closed = False
        self._close_lock = threading.Lock()
        self._dead: Optional[BaseException] = None

    # -- membership / lifecycle ---------------------------------------
    def add_replica(self, engine: ServingEngine) -> int:
        """Add a replica (warm it first, or call `warmup()`); returns its
        id.  A not-yet-warm replica is never routed to."""
        if self._closed:
            raise UnavailableError("fleet is closed")
        return self.manager.add(engine).id

    def drain(self, rid: int):
        self.manager.drain(rid)
        with self._lock:
            self._affinity = {s: r for s, r in self._affinity.items()
                              if r != rid}
        self._work.set()

    def remove(self, rid: int):
        self.manager.remove(rid)

    def warmup(self) -> Dict[int, Dict]:
        return self.manager.warm_all()

    def rollout(self, factory: Callable[[], ServingEngine],
                timeout: float = 300.0, drive: bool = False) -> List[int]:
        """Zero-downtime rolling restart: for each current replica, boot
        a replacement via `factory` (typically
        ``ServingEngine(model, program_set=...)`` — seconds, zero
        compiles), warm it, add it, drain the old one and wait for its
        residents to migrate or finish, then remove it.  Traffic keeps
        flowing the whole time.  `drive=True` steps the fleet from this
        thread while waiting (ONLY when nothing else drives the loop —
        no `start()`, no gateway); the default polls."""
        old_ids = [r.id for r in self.manager.replicas(_LIVE)]
        new_ids = []
        for rid in old_ids:
            engine = factory()
            if not engine.warm:
                engine.warmup()
            new_ids.append(self.add_replica(engine))
            # the boot's warmup compiles (zero when factory loads a
            # program set) must not count against the PEERS' post-warmup
            # marks — the registry is process-global
            self.manager.refresh_warm_marks()
            self.drain(rid)
            t0 = time.monotonic()
            while True:
                rep = self.manager.get(rid)
                if rep is None or rep.state in (CLOSED, CRASHED):
                    break
                if drive:
                    self.step()
                else:
                    time.sleep(0.005)
                if time.monotonic() - t0 > timeout:
                    raise TimeoutError(
                        f"replica {rid} did not drain in {timeout}s "
                        f"({rep.engine.scheduler.occupancy()} residents)")
            self.remove(rid)
        return new_ids

    # -- engine-compatible surface (what ServingGateway consumes) -----
    @property
    def scheduler(self) -> _FleetSchedulerView:
        return _FleetSchedulerView(self.manager)

    @property
    def max_slots(self) -> int:
        return sum(r.engine.max_slots for r in self.manager.replicas(_LIVE))

    @property
    def warm(self) -> bool:
        live = self.manager.replicas(_LIVE)
        return bool(live) and all(r.engine.warm for r in live)

    @property
    def _slots(self) -> Dict:
        """Merged {(replica_id, slot): run} view over live replicas —
        the gateway's preemption victim scan."""
        merged = {}
        for rep in self.manager.replicas(_LIVE):
            for slot, run in rep.engine._slots.items():
                merged[(rep.id, slot)] = run
        return merged

    def make_request(self, prompt, max_new_tokens: int, **kwargs):
        """Validate against a live replica's limits (the fleet is
        homogeneous by contract: every replica serves the same model
        with the same engine config)."""
        if self._closed:
            raise UnavailableError("fleet is closed")
        if self._dead is not None:
            raise UnavailableError(f"fleet loop died: {self._dead!r}")
        reps = self.manager.routable() or self.manager.replicas(_LIVE)
        if not reps:
            raise UnavailableError("fleet has no live replicas")
        return reps[0].engine.make_request(prompt, max_new_tokens,
                                           **kwargs)

    def try_admit(self, req: Request, resp: Response) -> bool:
        """Place the request NOW on the best replica (affinity, then
        least-loaded) — the gateway's admission path; must run on the
        driving thread."""
        for rep in self._route_order(req.session):
            if rep.engine.try_admit(req, resp):
                self._note_affinity(req.session, rep.id)
                return True
        return False

    def preempt_slot(self, key) -> PreemptedRun:
        rid, slot = key
        rep = self.manager.get(rid)
        if rep is None or rep.state not in _LIVE:
            raise InvalidArgumentError(f"replica {rid} is not live")
        return rep.engine.preempt_slot(slot)

    def restore_run(self, paused: PreemptedRun) -> bool:
        """Resume a preempted run on ANY replica with capacity — the
        gateway's restore path, now fleet-wide (the snapshot format is
        replica-portable by construction)."""
        for rep in self.manager._targets():
            if rep.engine.scheduler.free_slot_count() <= 0:
                continue
            try:
                check_compatible(encode_run(paused), rep.engine)
            except RunTransferError:
                continue
            if rep.engine.restore_run(paused):
                return True
        return False

    def step(self) -> bool:
        if self._closed:
            return False
        return self.manager.tick()

    def has_work(self) -> bool:
        return (any(r.engine.has_work()
                    for r in self.manager.replicas(_LIVE))
                or bool(self.manager._parked))

    def _abort_all(self, make_exc):
        self.manager.abort_all(make_exc)

    # -- submission (caller threads) ----------------------------------
    def submit(self, prompt, max_new_tokens: int, block: bool = False,
               timeout: Optional[float] = None, **kwargs) -> Response:
        """Route one request: session-affine when `session=` was given
        and its replica is still healthy, least-loaded otherwise.  Raises
        the same typed errors `ServingEngine.submit` raises; every
        accepted request's Response reaches a terminal state even if its
        replica later dies (failover / resubmit / typed error)."""
        req, resp = self.make_request(prompt, max_new_tokens, **kwargs)
        last_exc = None
        for rep in self._route_order(req.session):
            try:
                rep.engine.scheduler.submit(req, resp, block=block,
                                            timeout=timeout)
            except QueueFullError as e:
                last_exc = e
                continue
            self._note_affinity(req.session, rep.id)
            self._work.set()
            return resp
        raise last_exc or UnavailableError(
            "no routable replica accepted the request")

    def _route_order(self, session: Optional[str]) -> List[Replica]:
        reps = self.manager._targets()
        if not (self._affinity_enabled and session):
            return reps
        with self._lock:
            rid = self._affinity.get(session)
        if rid is not None:
            for i, rep in enumerate(reps):
                if rep.id == rid:
                    if i:
                        reps.insert(0, reps.pop(i))
                    return reps
            # the pinned replica is gone/fenced: re-home below
            with self._lock:
                self._affinity.pop(session, None)
        return reps

    def _note_affinity(self, session: Optional[str], rid: int):
        if self._affinity_enabled and session:
            with self._lock:
                # dict order is insertion order: delete-then-insert makes
                # this an LRU touch, and overflow evicts the oldest entry
                self._affinity.pop(session, None)
                self._affinity[session] = rid
                while len(self._affinity) > self._max_sessions:
                    self._affinity.pop(next(iter(self._affinity)))

    # -- driving -------------------------------------------------------
    def run_until_drained(self, timeout: Optional[float] = None):
        t0 = time.monotonic()
        while self.has_work():
            self.step()
            if timeout is not None and time.monotonic() - t0 > timeout:
                raise TimeoutError(f"fleet did not drain in {timeout}s")

    def start(self):
        """Background fleet loop.  Not for use under a gateway — the
        gateway's loop drives `step()` itself."""
        if self._thread is not None:
            return
        if self._closed:
            raise UnavailableError("fleet is closed")
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    did = self.step()
                except BaseException as e:  # noqa: BLE001 — no hangs
                    self._dead = e
                    self._abort_all(lambda req: UnavailableError(
                        f"request {req.id} aborted: fleet loop died: "
                        f"{e!r}"))
                    return
                if not did:
                    self._work.wait(0.002)
                    self._work.clear()

        self._thread = threading.Thread(target=loop, name="serving-fleet",
                                        daemon=True)
        self._thread.start()

    def close(self):
        """Close every replica; every outstanding request reaches a
        terminal state.  Idempotent and safe under concurrent
        double-close (same contract as the engine/gateway)."""
        self._closed = True
        self._stop.set()
        self._work.set()
        with self._close_lock:
            if self._thread is not None:
                self._thread.join(timeout=5.0)
                self._thread = None
            self.manager.close_all()
        with self._lock:
            self._affinity.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- introspection -------------------------------------------------
    def health(self) -> Dict:
        """Per-replica health + fleet aggregates — the gateway's
        /healthz fleet block."""
        reps = self.manager.replicas()
        return {
            "replicas": {str(r.id): r.snapshot() for r in reps},
            "routable": len(self.manager.routable()),
            "total": len(reps),
            "warm": self.warm,
            **self.manager.counters(),
        }

    def post_warmup_compiles(self) -> int:
        """Worst replica's post-warmup compile count (the fleet contract
        is 0 on every replica); -1 if any live replica never warmed."""
        vals = [r.engine.post_warmup_compiles()
                for r in self.manager.replicas(_LIVE)]
        return max(vals) if vals else -1

    def metrics(self) -> Dict:
        live = self.manager.replicas(_LIVE)
        totals = {"requests_completed": 0, "requests_errored": 0,
                  "tokens_out": 0}
        per = {}
        for rep in self.manager.replicas():
            try:
                m = rep.engine.metrics()
            except Exception:
                m = {}
            if rep.state in _LIVE:
                for k in totals:
                    totals[k] += m.get(k) or 0
            per[str(rep.id)] = {"state": rep.state,
                                "occupancy": m.get("slot_occupancy"),
                                "queue_depth": m.get("queue_depth"),
                                "completed": m.get("requests_completed"),
                                "errored": m.get("requests_errored")}
        return {
            **totals,
            "replicas": per,
            "routable": len(self.manager.routable()),
            "live": len(live),
            "sessions": len(self._affinity),
            "max_slots": self.max_slots,
            "warm": self.warm,
            "post_warmup_compiles": (self.post_warmup_compiles()
                                     if self.warm else None),
            **{f"fleet_{k}": v for k, v in self.manager.counters().items()},
        }
