"""Fleet-grade serving: a replica manager + router over N ServingEngines.

The gateway (PR 6) made ONE engine production-shaped; this module makes
the engine COUNT a runtime variable.  `FleetRouter` fronts N
`ServingEngine` replicas — in-process for tier-1/CPU, with every
interaction funneled through a surface a subprocess replica could
implement over IPC — and `ReplicaManager` owns their lifecycle:

- **Routing** is least-loaded (occupancy + queue depth) with session
  affinity: requests sharing a ``session`` key stick to one replica
  while it stays healthy (KV-prefix locality once the radix cache
  lands), and re-home automatically when it is fenced.
- **Health** is positive evidence, not hope: a replica is routable only
  after `warmup()` reports every program compiled (`engine.warm`), its
  per-step wall time feeds an EWMA that fences a browned-out replica
  (`slow_threshold_ms`), and a step that RAISES is a crash — the
  replica is fenced immediately.  Every successful step also beats a
  heartbeat, exported as `heartbeat_age_s` telemetry: in-process the
  raising step IS the liveness verdict (one thread drives everyone), so
  age-based fencing is the subprocess deployment's job, alarmed on this
  signal.
- **Failover** generalizes the PR-6 preempt/restore snapshot into the
  run-transfer codec (serving/transfer.py): a fenced-but-alive replica's
  residents are preempted, encoded, and restored onto surviving
  replicas, resuming bit-identical to an uninterrupted run.  A CRASHED
  replica's snapshots die with it: each lost run is re-prefilled from
  its prompt on a healthy replica when the request opted in
  (``resubmit=True``, greedy-only — the fleet forwards only the
  not-yet-delivered suffix, so the stream stays bit-identical
  end-to-end), otherwise it fails with the typed `ReplicaLostError`.
  Either way: NEVER a hung consumer.
- **Draining** (`drain(rid)`) stops admissions, migrates residents to
  peers (or lets them finish in place when the fleet is full), then
  closes the empty replica — which makes rollout zero-downtime: boot a
  replacement from a PR-9 program set (seconds, zero compiles), warm
  it, add it, drain the old one (`rollout()` sequences this across the
  whole fleet).

**Process isolation** (serving/worker.py): `add_worker(spec)` spawns a
replica as its OWN OS process — a `SubprocessReplica` whose engine
proxy (`WorkerClient`) speaks the length-prefixed npz RPC and
implements the exact engine surface above, so routing, affinity,
gateway fronting, drain and rollout work unchanged over a MIXED
in-process/subprocess fleet.  Subprocess health adds the signal the
in-process fleet cannot have: an **out-of-band heartbeat** (the worker
atomically rewrites a step-counter+wall-clock file after every step),
so a replica whose step WEDGES — a hang, not a raise; the socket stays
connected and no call ever returns — is fenced on heartbeat AGE
(`heartbeat_timeout_s`), SIGKILLed after `kill_grace_s`, and restarted
by the supervisor with exponential backoff + jitter (`RestartBackoff`
over utils.retry) under a restart budget.  Residents of a wedged or
crashed worker fail over through the existing paths (resubmit / typed
`ReplicaLostError` / queue re-route — the local proxy queue holds
every not-yet-shipped request); budget exhaustion removes the replica
for good.

The in-process threading contract mirrors the gateway's: ONE thread
drives `step()` — either the fleet's own `start()` loop or a
`ServingGateway` fronting the router (the router implements the
engine-facing surface the gateway consumes: `make_request`,
`try_admit`, `preempt_slot`/`restore_run`, `scheduler` depth/occupancy
views, `step`, `_abort_all`).  `submit` is safe from any thread.

Chaos knobs (utils.faults): ``PDTPU_FAULT_REPLICA_CRASH=replica:tick``
(SIGKILL-equivalent mid-decode loss),
``PDTPU_FAULT_REPLICA_SLOW=ms[:every_n[:replica]]`` (brownout) and
``PDTPU_FAULT_REPLICA_WEDGE=replica:tick`` (a subprocess worker's step
blocks forever — only the heartbeat can see it) — the fleet probe
(probes/fleet_probe.py) drives all three under Poisson traffic plus a
full rolling restart and a supervised worker restart.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..core.errors import InvalidArgumentError, UnavailableError
from ..utils import faults
from ..utils.monitor import stat_add
from ..utils.retry import RetryPolicy
from .engine import PreemptedRun, ServingEngine
from .request import Request, Response, RequestCancelled
from .scheduler import DeadlineExceededError, QueueFullError
from .transfer import (RunTransferError, check_compatible, decode_run,
                       encode_run)
from .worker import RemoteWorkerClient, WorkerClient, WorkerDiedError

__all__ = ["FleetRouter", "ReplicaManager", "Replica",
           "SubprocessReplica", "RemoteReplica", "RestartBackoff",
           "ReplicaLostError"]

# replica lifecycle states
BOOTING = "booting"      # added, not yet warm — never routed to
HEALTHY = "healthy"      # warm + fast: routable
DEGRADED = "degraded"    # fenced by slow-step health; residents migrate
DRAINING = "draining"    # admissions stopped; residents migrate/finish
CRASHED = "crashed"      # step raised / injected kill; state abandoned
WEDGED = "wedged"        # subprocess heartbeat went stale mid-step: the
#                          process is alive but not making progress —
#                          fenced like a crash (its state is unreachable),
#                          then SIGKILLed after the grace period
CLOSED = "closed"        # engine closed (drain finished or shutdown)

_LIVE = (BOOTING, HEALTHY, DEGRADED, DRAINING)


class ReplicaLostError(UnavailableError):
    """The replica serving this run crashed and its KV snapshot was lost
    with it; the request did not opt into resubmission (or no capacity
    was left to resubmit into).  The typed terminal state — retry the
    request if it is idempotent for you."""
    code = "Unavailable"


class _InjectedReplicaCrash(RuntimeError):
    """PDTPU_FAULT_REPLICA_CRASH fired: the SIGKILL-equivalent for an
    in-process replica (raised BEFORE the engine can fail its runs)."""


class _ForwardingResponse(Response):
    """The resubmission bridge: a crashed replica's lost greedy run is
    replayed from its prompt on a survivor, and this response receives
    the replay — swallowing the first `skip` tokens (already delivered
    to the consumer before the crash) and forwarding the rest into the
    ORIGINAL response object the consumer is iterating.  Greedy decode
    is deterministic in the prompt, so the swallowed prefix is
    bit-identical to what was already delivered and the consumer sees
    one seamless, bit-identical stream.

    It is itself a full Response (the serving engine's emit/sweep
    bookkeeping runs against it), and chains: if the replay's replica
    crashes too, the next resubmission targets the original response
    with a recomputed skip."""

    def __init__(self, request: Request, target: Response, skip: int):
        super().__init__(request)
        self._target = target
        self._skip = int(skip)

    @property
    def cancelled(self) -> bool:
        # the consumer cancels the ORIGINAL stream; the engine sweeping
        # the replay must honor it
        return self._cancel_requested or self._target.cancelled

    def _push_token(self, tok: int, logp: float = 0.0):
        super()._push_token(tok, logp)
        if self._skip > 0:
            self._skip -= 1
            return
        self._target._push_token(tok, logp)

    def _finish(self, reason: str):
        super()._finish(reason)
        self._target._finish(reason)

    def _fail(self, exc: BaseException):
        super()._fail(exc)
        self._target._fail(exc)


_obs_handles = None


def _obs():
    """Cached fleet observability handles (registry.reset() zeroes the
    values in place, handles stay valid)."""
    global _obs_handles
    if _obs_handles is None:
        from ..observability import metrics as _m
        _obs_handles = {
            "up": _m.gauge(
                "serving_replica_up",
                "1 while the replica is routable (healthy + warm), else 0",
                labelnames=("replica",)),
            "inflight": _m.gauge(
                "serving_replica_inflight",
                "decoding slots + queued requests on the replica",
                labelnames=("replica",)),
            "replicas_up": _m.gauge(
                "fleet_replicas_up", "routable replicas in the fleet"),
            "failovers": _m.counter(
                "fleet_failovers_total",
                "replica fences (crash or brownout) that triggered "
                "failover handling"),
            "migrated": _m.counter(
                "fleet_migrated_runs_total",
                "in-flight runs moved between replicas via the run "
                "transfer codec"),
            "hb_age": _m.gauge(
                "serving_replica_heartbeat_age_seconds",
                "seconds since the replica's last heartbeat (out-of-band "
                "file for subprocess workers, step beat in-process) — "
                "the subprocess-deployment alarm signal",
                labelnames=("replica",)),
            "workers": _m.gauge(
                "fleet_worker_processes",
                "live subprocess worker replicas (process alive)"),
            "wedges": _m.counter(
                "fleet_wedged_replicas_total",
                "replicas fenced on heartbeat age (wedged step: process "
                "alive, no progress)"),
            "worker_restarts": _m.counter(
                "fleet_worker_restarts_total",
                "supervised subprocess worker restarts performed"),
            "refreshes": _m.counter(
                "fleet_weight_refreshes_total",
                "replica weight flips applied (continuous refresh — one "
                "per replica per publish, rollbacks included)"),
            "rollbacks": _m.counter(
                "fleet_rollbacks_total",
                "published weight sets rejected by the canary gate and "
                "rolled back to the previous weights_sha"),
            "scale_up": _m.counter(
                "fleet_scale_up_total",
                "autoscaler scale-up actions (workers spawned on "
                "sustained SLO pressure)"),
            "scale_down": _m.counter(
                "fleet_scale_down_total",
                "autoscaler scale-down actions (least-loaded replica "
                "drained — never killed)"),
            "target_replicas": _m.gauge(
                "fleet_target_replicas",
                "the autoscaler's current desired replica count"),
        }
    return _obs_handles


class RestartBackoff:
    """The supervisor's restart schedule: exponential backoff with full
    jitter over a hard restart budget — `utils.retry.RetryPolicy`'s
    schedule (a crashed worker is just another flaky service), with the
    sleep inverted into an absolute next-attempt time so the fleet tick
    stays non-blocking.  `rng` is injectable (deterministic tests)."""

    def __init__(self, max_restarts: int = 3, base_delay: float = 0.5,
                 max_delay: float = 30.0, jitter: float = 0.5,
                 rng: Optional[Callable[[float, float], float]] = None):
        self.max_restarts = max(0, int(max_restarts))
        self._policy = RetryPolicy(retries=self.max_restarts,
                                   base_delay=base_delay,
                                   max_delay=max_delay, jitter=jitter)
        self._rng = rng if rng is not None else random.uniform

    def delay_for(self, attempt: int) -> Optional[float]:
        """Jittered delay before restart number `attempt` (1-based), or
        None once the budget is exhausted."""
        if attempt < 1 or attempt > self.max_restarts:
            return None
        delay = list(self._policy.delays())[attempt - 1]
        if self._policy.jitter:
            delay += self._rng(0.0, self._policy.jitter * delay)
        return delay


class Replica:
    """One managed ServingEngine + its health state.  `rid` is a
    monotonically increasing integer, never reused — it is also the
    index the replica fault knobs target."""

    kind = "inproc"

    def __init__(self, rid: int, engine: ServingEngine):
        self.id = rid
        self.engine = engine
        self.state = HEALTHY if engine.warm else BOOTING
        self.steps = 0
        self.last_beat = time.monotonic()
        self.step_ewma: Optional[float] = None  # seconds
        self.fast_steps = 0
        self.fence_reason: Optional[str] = None
        self.created_at = time.monotonic()
        # a replica with a weight flip pending is fenced from NEW
        # admissions (routable() below) while its queued work finishes
        # in place on the OLD weights — the flip applies at the idle
        # boundary, so no stream ever spans two weight sets
        self.flipping = False
        # remove()-of-a-draining-replica: the autoscaler's retire path
        # flags the replica instead of racing the drain completion; the
        # drain-finish sweep performs the remove itself
        self.remove_after_drain = False

    def routable(self) -> bool:
        return (self.state == HEALTHY and self.engine.warm
                and not self.flipping)

    def load(self) -> int:
        s = self.engine.scheduler
        return s.occupancy() + s.queue_depth()

    def note_step_time(self, dt: float, threshold: Optional[float]):
        a = 0.3
        self.step_ewma = (dt if self.step_ewma is None
                          else a * dt + (1 - a) * self.step_ewma)
        if threshold is not None:
            if dt < 0.5 * threshold:
                self.fast_steps += 1
            else:
                self.fast_steps = 0

    def observe_step(self, dt: float, threshold: Optional[float]):
        """Health bookkeeping for one successful driving-tick step: the
        step IS the heartbeat in-process (one thread drives everyone —
        a step that returns proves liveness)."""
        self.last_beat = time.monotonic()
        self.note_step_time(dt, threshold)

    def heartbeat_age(self) -> Optional[float]:
        """Seconds since the last liveness evidence."""
        return max(0.0, time.monotonic() - self.last_beat)

    def _adapter_shas(self) -> Optional[Dict[str, str]]:
        """name -> artifact sha256 of every LoRA adapter resident on
        THIS replica (worker replicas report theirs through status-frame
        metrics) — the /healthz "is tenant X actually loaded here"
        answer, per replica."""
        try:
            fn = getattr(self.engine, "adapter_shas", None)
            if fn is not None:
                return fn() or None
            lora = (self.engine.metrics() or {}).get("lora") or {}
        except Exception:
            return None
        return lora.get("shas") or None

    def snapshot(self) -> Dict:
        age = self.heartbeat_age()
        return {
            "kind": self.kind,
            "state": self.state,
            "warm": bool(self.engine.warm),
            "occupancy": self.engine.scheduler.occupancy(),
            "queue_depth": self.engine.scheduler.queue_depth(),
            "steps": self.steps,
            "step_ewma_ms": (None if self.step_ewma is None
                             else round(self.step_ewma * 1e3, 3)),
            "heartbeat_age_s": (None if age is None else round(age, 3)),
            "fence_reason": self.fence_reason,
            "post_warmup_compiles": (self.engine.post_warmup_compiles()
                                     if self.engine.warm else None),
            # which weights this replica actually serves, how many flips
            # it absorbed, and whether a flip is pending — the /healthz
            # at-a-glance answer during a refresh
            "weights_sha": getattr(self.engine, "weights_sha", None),
            "refresh_epoch": getattr(self.engine, "refresh_epoch", 0),
            "flipping": self.flipping,
            # loaded LoRA adapters (name -> artifact sha) on this replica
            "adapters": self._adapter_shas(),
        }


class SubprocessReplica(Replica):
    """A replica whose engine is a `WorkerClient` proxy over its own OS
    process.  Same state machine, plus: out-of-band heartbeat age (the
    wedge detector), worker-reported step times feeding the brownout
    EWMA (pump time on this side measures nothing), and a `lineage`
    record the supervisor uses to restart it — the spec, the stable
    worker index the fault knobs target, and the cumulative restart
    count the budget caps."""

    kind = "subprocess"

    def __init__(self, rid: int, client: WorkerClient, lineage: Dict):
        super().__init__(rid, client)
        self.lineage = lineage

    def observe_step(self, dt: float, threshold: Optional[float]):
        # dt here is manager-side PUMP time; the worker reports its real
        # per-step wall times (brownout sleeps included) in status frames
        for wdt in self.engine.take_step_times():
            self.note_step_time(wdt, threshold)

    def heartbeat_age(self, fresh: bool = False) -> Optional[float]:
        age = self.engine.heartbeat_age(fresh=fresh)
        if age is None:
            # no beat file yet (early boot): fall back to manager-side
            # evidence so the snapshot stays meaningful
            return max(0.0, time.monotonic() - self.last_beat)
        # mirror into last_beat so manager-side views stay consistent
        self.last_beat = time.monotonic() - age
        return age

    def snapshot(self) -> Dict:
        snap = super().snapshot()
        snap.update({
            "pid": self.engine.pid,
            "process_alive": self.engine.process_alive(),
            "worker_index": self.lineage.get("index"),
            "restarts": self.lineage.get("restarts", 0),
            "worker_steps": self.engine.heartbeat_steps(),
            # which weights this replica actually serves + its session
            # epoch — the at-a-glance answer /healthz operators need
            "weights_sha": getattr(self.engine, "weights_sha", None),
            "epoch": getattr(self.engine, "epoch", 0),
        })
        return snap


class RemoteReplica(SubprocessReplica):
    """A replica attached over real TCP (`RemoteWorkerClient`): the
    manager never owned its process, liveness rides beat frames on a
    dedicated side connection instead of a heartbeat file, and the
    supervisor's 'restart' is a RE-ATTACH to the same address with an
    incremented epoch token.  Everything else — wedge fencing on beat
    age, failover, drain, rollout — is inherited unchanged: that is the
    point of the epoch/beat design."""

    kind = "remote"

    def snapshot(self) -> Dict:
        snap = super().snapshot()
        snap["address"] = self.lineage.get("address")
        snap["bytes_shipped"] = getattr(self.engine, "bytes_shipped", 0)
        return snap


class ReplicaManager:
    """Replica lifecycle: stepping, health, fencing, migration, drain.

    All mutation of replica state runs on the driving thread (the fleet
    loop or the gateway loop) except `add`/`drain`/`close`, which only
    flip state flags under the lock — the driving thread picks the
    change up on its next tick."""

    def __init__(self, slow_threshold_ms: Optional[float] = None,
                 probation_steps: int = 5,
                 heartbeat_timeout_s: Optional[float] = 10.0,
                 kill_grace_s: float = 2.0,
                 restart_backoff: Optional[RestartBackoff] = None,
                 _clock: Callable[[], float] = time.monotonic):
        self._replicas: Dict[int, Replica] = {}
        self._next_id = 0
        self._lock = threading.Lock()
        self._ticks = 0
        self.slow_threshold_s = (None if slow_threshold_ms is None
                                 else float(slow_threshold_ms) / 1e3)
        self.probation_steps = int(probation_steps)
        # subprocess liveness: a worker whose out-of-band heartbeat is
        # older than this is WEDGED (fenced + failed over even though no
        # in-band call returned), SIGKILLed kill_grace_s later, and
        # restarted under restart_backoff's budget.  Applies ONLY to
        # SubprocessReplica — in-process, a raising step IS the verdict.
        self.heartbeat_timeout_s = (None if heartbeat_timeout_s is None
                                    else float(heartbeat_timeout_s))
        self.kill_grace_s = float(kill_grace_s)
        self.restart_backoff = (RestartBackoff()
                                if restart_backoff is None
                                else restart_backoff)
        self._clock = _clock
        self._pending_kills: List[tuple] = []   # (rep, kill_at)
        self._restarts: List[Dict] = []         # {lineage, at, from}
        # runs preempted off a fenced replica that no peer could hold
        # yet (paged-block shortfall): retried every tick, swept for
        # cancel/deadline, failed terminally at close
        self._parked: List[PreemptedRun] = []
        # pending weight flips: {"rid", "sha", "path", "state", "done",
        # "ok", "error"} — applied by _pump_flips at each replica's idle
        # boundary (engine.has_work() false), so a flip never lands
        # mid-stream
        self._flips: List[Dict] = []
        # LoRA adapters the fleet serves: name -> (path, sha).  Applied
        # to every live replica at load_adapter() and re-applied to each
        # freshly-warm replica (boot, supervised restart) so the fleet
        # converges — a restarted worker's empty registry must not make
        # a tenant's adapter silently vanish from part of the fleet
        self._adapters: Dict[str, Tuple[str, Optional[str]]] = {}
        self._n = {"failovers": 0, "migrated": 0, "resubmits": 0,
                   "lost": 0, "reroutes": 0, "drains": 0, "wedges": 0,
                   "worker_restarts": 0, "restarts_exhausted": 0,
                   "weight_refreshes": 0, "rollbacks": 0,
                   "scale_up": 0, "scale_down": 0}

    # -- membership ---------------------------------------------------
    def add(self, engine: ServingEngine) -> Replica:
        if engine._thread is not None:
            raise InvalidArgumentError(
                "replica engine loop already started; the fleet drives "
                "engine.step() itself — construct the engine without "
                "start()")
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            rep = Replica(rid, engine)
            self._replicas[rid] = rep
        self._publish_up(rep)
        return rep

    def add_worker(self, spec: Dict, lineage: Optional[Dict] = None,
                   boot_timeout_s: float = 180.0,
                   rpc_timeout_s: float = 15.0,
                   address: Optional[str] = None,
                   **client_extra) -> "SubprocessReplica":
        """Spawn a subprocess engine worker from a boot spec (model
        factory + engine config + optional AOT program set — see
        serving/worker.py) and register it BOOTING; the driving tick
        polls the handshake and flips it healthy once the worker reports
        warm.  `address="HOST:PORT"` attaches to a STANDALONE remote
        worker (``--listen``) over TCP instead of spawning one: the boot
        spec (plus the ``spec["weights"]`` npz artifact and optionally
        the program set) ships over the attach handshake under a
        manager-issued epoch token, and a supervisor 'restart' is a
        re-attach with the epoch incremented — the stale session is told
        to abort, never to resume.  `lineage` is internal (the
        supervisor's restart path reuses the original
        spec/index/budget/address record)."""
        client_kw = {"boot_timeout_s": float(boot_timeout_s),
                     "rpc_timeout_s": float(rpc_timeout_s)}
        client_kw.update(client_extra)
        with self._lock:
            rid = self._next_id
            self._next_id += 1
        if lineage is None:
            # the worker INDEX (fault-knob target) stays stable across
            # restarts; the replica id never recurs
            lineage = {"spec": dict(spec), "index": rid, "restarts": 0,
                       "client_kw": client_kw, "exhausted": False,
                       "address": address, "epoch": 0}
        if lineage.get("address"):
            # every (re)attach gets a FRESH epoch token: the fence that
            # makes a healed stale session abort instead of double-serve
            lineage["epoch"] = lineage.get("epoch", 0) + 1
            client = RemoteWorkerClient(
                lineage["spec"], address=lineage["address"],
                index=lineage["index"], epoch=lineage["epoch"],
                **lineage.get("client_kw", client_kw))
            rep = RemoteReplica(rid, client, lineage)
        else:
            client = WorkerClient(lineage["spec"],
                                  index=lineage["index"],
                                  **lineage.get("client_kw", client_kw))
            rep = SubprocessReplica(rid, client, lineage)
        with self._lock:
            self._replicas[rid] = rep
        self._publish_up(rep)
        return rep

    def get(self, rid: int) -> Optional[Replica]:
        with self._lock:
            return self._replicas.get(rid)

    def replicas(self, states=None) -> List[Replica]:
        with self._lock:
            reps = list(self._replicas.values())
        if states is None:
            return reps
        return [r for r in reps if r.state in states]

    def routable(self) -> List[Replica]:
        return [r for r in self.replicas((HEALTHY,)) if r.routable()]

    def remove(self, rid: int):
        """Forget a closed/crashed replica (rollout teardown).  Calling
        it on a replica that is still MID-DRAIN — the autoscaler's
        retire path, racing the drain completion — is NOT an error: the
        replica is flagged and the drain-finish sweep removes it the
        moment its last resident finishes.  Idempotent: repeat calls
        (and a call landing after the drain completed) are no-ops or
        plain removes."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                return
            if rep.state == DRAINING:
                # deferred remove: _finish_drains closes the engine at
                # the idle boundary and performs the remove itself —
                # never a close-vs-remove race on the state machine
                rep.remove_after_drain = True
                return
            if rep.state not in (CLOSED, CRASHED, WEDGED):
                raise InvalidArgumentError(
                    f"replica {rid} is {rep.state}; drain it before "
                    "remove (or let crash handling finish)")
            del self._replicas[rid]
        if isinstance(rep, SubprocessReplica):
            # reap: a removed worker leaves no orphan.  A crashed/wedged
            # corpse gets the non-graceful path — it cannot answer a
            # close verb, and the graceful 2s wait would stall the
            # driving thread (every OTHER replica) for nothing
            rep.engine.close(graceful=rep.state == CLOSED)
        _obs()["up"].labels(replica=str(rid)).set(0)
        self._publish_counts()

    def warm_all(self) -> Dict[int, Dict]:
        """warmup() every not-yet-warm replica; booting replicas become
        healthy (routable) once every program is compiled."""
        reports = {}
        for rep in self.replicas(_LIVE):
            if not rep.engine.warm:
                reports[rep.id] = rep.engine.warmup()
            if rep.state == BOOTING and rep.engine.warm:
                rep.state = HEALTHY
                self._converge_adapters(rep)
                self._publish_up(rep)
        self.refresh_warm_marks()
        return reports

    def refresh_warm_marks(self):
        """Re-baseline every warm replica's post-warmup compile marks.
        The observability program registry is process-global, so replica
        B's warmup compiles would otherwise count against replica A's
        post-warmup-zero contract (`serving_decode` is one registry
        entry, N replicas).  Called after every membership warm event
        (warm_all, rollout boot), which makes
        `post_warmup_compiles()` mean: compiles since the fleet's most
        recent warmup — still exactly the zero-compiles-under-traffic
        fleet contract."""
        for rep in self.replicas(_LIVE):
            if rep.engine.warm:
                rep.engine._warm_marks = rep.engine._compile_marks()

    def drain(self, rid: int):
        """Fence `rid` for graceful removal: no new admissions; queued
        requests re-route now, residents migrate (or finish in place)
        over the next ticks, then the engine closes."""
        rep = self.get(rid)
        if rep is None:
            raise InvalidArgumentError(f"no replica {rid}")
        if rep.state not in (BOOTING, HEALTHY, DEGRADED):
            return
        rep.state = DRAINING
        rep.fence_reason = "drain"
        self._n["drains"] += 1
        stat_add("STAT_fleet_drains")
        self._publish_up(rep)
        # queued-but-never-prefilled work lost nothing: hand it to peers
        # — but the draining replica is ALIVE, so when no peer has queue
        # space the entry goes back on its own queue and is served
        # before the drain completes (the same finish-in-place policy
        # residents get; zero-drop rollout must hold under queue
        # pressure too)
        for req, resp in rep.engine.scheduler.drain_pending():
            self._reroute(req, resp, exclude_id=rid,
                          fallback_engine=rep.engine)

    # -- the driving tick ---------------------------------------------
    def tick(self) -> bool:
        """One fleet iteration on the driving thread: step every live
        replica (crash fault + brownout fault consulted per step, wall
        time fed to health), poll subprocess boot handshakes, fence what
        the health verdicts — including out-of-band heartbeat age —
        demand, SIGKILL wedged workers past their grace period, run the
        restart supervisor, migrate residents off fenced replicas, retry
        parked runs, close drained-empty replicas."""
        self._ticks += 1
        did = False
        crash_cfg = faults.replica_crash_config()
        for rep in self.replicas(_LIVE):
            if rep.state == BOOTING:
                did = self._poll_boot(rep) or did
                continue
            if (rep.state == DEGRADED and not rep.engine.has_work()
                    and self._ticks % 16
                    and not isinstance(rep, SubprocessReplica)):
                # probation sampling: an idle fenced IN-PROCESS replica
                # is stepped only occasionally, so a browned-out
                # replica's injected step latency cannot keep stalling
                # the shared loop.  A subprocess pump is always cheap
                # (the slow step runs in the worker) and skipping it
                # would starve its status/health feed.
                continue
            try:
                # the brownout sleep counts INTO the measured step time
                # (it models a slow replica; health must see it).  For a
                # subprocess replica the knob fires in the WORKER loop;
                # the manager-side call is a no-op there (index spaces
                # are disjoint only by convention — the worker consults
                # its own index).
                t0 = time.perf_counter()
                if not isinstance(rep, SubprocessReplica):
                    faults.maybe_slow_replica(rep.id, rep.steps)
                if crash_cfg is not None and crash_cfg == (rep.id,
                                                           rep.steps):
                    rep.steps += 1
                    raise _InjectedReplicaCrash(
                        f"replica {rep.id} lost at tick {rep.steps - 1} "
                        "(PDTPU_FAULT_REPLICA_CRASH)")
                stepped = rep.engine.step()
                dt = time.perf_counter() - t0
                rep.steps += 1
                rep.observe_step(dt, self.slow_threshold_s)
                did = stepped or did
            except BaseException as e:  # noqa: BLE001 — fence, never hang
                self._on_crash(rep, e)
                did = True
        self._check_heartbeats()
        self._update_health()
        did = self._pump_migrations() or did
        did = self._pump_parked() or did
        self._sweep_parked()
        did = self._pump_flips() or did
        did = self._finish_drains() or did
        did = self._pump_kills() or did
        did = self._pump_restarts() or did
        self._publish_inflight()
        return did

    def _poll_boot(self, rep: Replica) -> bool:
        """Advance a BOOTING subprocess replica's handshake (in-process
        replicas become healthy via warm_all).  Boot failure — process
        exit, typed fatal, timeout — burns a restart attempt."""
        if not isinstance(rep, SubprocessReplica):
            return False
        try:
            ready = rep.engine.poll_ready()
        except WorkerDiedError as e:
            rep.state = CRASHED
            rep.fence_reason = f"boot failed: {e}"
            self._publish_up(rep)
            rep.engine.kill()
            self._schedule_restart(rep)
            return True
        if ready and rep.state == BOOTING:
            rep.state = HEALTHY
            rep.last_beat = time.monotonic()
            self._converge_adapters(rep)
            self._publish_up(rep)
            return True
        return False

    # -- out-of-band heartbeat: the wedged-worker detector -------------
    def _check_heartbeats(self):
        """Fence any live subprocess replica whose heartbeat file age
        exceeds the threshold — the case PR 12 could not see: the step
        never returns, the socket stays connected, and only the
        out-of-band signal says 'no progress'."""
        if self.heartbeat_timeout_s is None:
            return
        for rep in self.replicas((HEALTHY, DEGRADED, DRAINING)):
            if not isinstance(rep, SubprocessReplica):
                continue
            age = rep.heartbeat_age()
            if age is not None and age > self.heartbeat_timeout_s:
                # confirm against a FRESH file read before fencing: the
                # cached record may predate the worker's warmup beat (a
                # false wedge would burn a restart-budget attempt)
                age = rep.heartbeat_age(fresh=True)
                if age is not None and age > self.heartbeat_timeout_s:
                    self._on_wedge(rep, age)

    def _on_wedge(self, rep: Replica, age: float):
        """A wedged worker's device state is UNREACHABLE (any RPC would
        hang), so failover treats it exactly like a crash; the process
        itself gets `kill_grace_s` to unwedge on its own (a GC pause, an
        allocator stall) before SIGKILL, and the supervisor restarts it
        under the backoff budget."""
        rep.state = WEDGED
        rep.fence_reason = (f"wedged: heartbeat age {age:.2f}s > "
                            f"{self.heartbeat_timeout_s:.2f}s threshold")
        self._n["wedges"] += 1
        self._n["failovers"] += 1
        stat_add("STAT_fleet_wedges")
        stat_add("STAT_fleet_failovers")
        _obs()["wedges"].inc()
        _obs()["failovers"].inc()
        self._publish_up(rep)
        self._fail_over_all(rep)
        self._pending_kills.append((rep, self._clock()
                                    + self.kill_grace_s))
        self._schedule_restart(rep)

    def _pump_kills(self) -> bool:
        """SIGKILL wedged workers whose grace period expired.  Double
        kill of an already-dead pid is a no-op (WorkerClient.kill)."""
        if not self._pending_kills:
            return False
        now = self._clock()
        due = [e for e in self._pending_kills if e[1] <= now]
        if not due:
            return False
        self._pending_kills = [e for e in self._pending_kills
                               if e[1] > now]
        for rep, _ in due:
            rep.engine.kill()
        return True

    # -- the restart supervisor ----------------------------------------
    def _schedule_restart(self, rep: Replica):
        if not isinstance(rep, SubprocessReplica):
            return
        # a WEDGED worker keeps its kill_grace_s before SIGKILL; the
        # replacement must not spawn (and reap the corpse) earlier, or
        # the grace period the knob promises never actually happens
        min_delay = (self.kill_grace_s + 0.05 if rep.state == WEDGED
                     else 0.0)
        self._schedule_restart_lineage(rep.lineage, from_id=rep.id,
                                       min_delay=min_delay)

    def _schedule_restart_lineage(self, lineage: Dict,
                                  from_id: Optional[int] = None,
                                  min_delay: float = 0.0):
        if lineage.get("exhausted"):
            return
        attempt = lineage.get("restarts", 0) + 1
        delay = self.restart_backoff.delay_for(attempt)
        if delay is None:
            # budget exhausted: the replica is gone for good.  Every
            # consumer already reached a typed terminal state when the
            # incarnation was fenced; this only stops the respawning.
            lineage["exhausted"] = True
            self._n["restarts_exhausted"] += 1
            stat_add("STAT_fleet_restarts_exhausted")
            if from_id is not None:
                self.remove(from_id)
            return
        lineage["restarts"] = attempt
        self._restarts.append({"lineage": lineage,
                               "at": self._clock() + max(delay, min_delay),
                               "from": from_id})

    def _pump_restarts(self) -> bool:
        if not self._restarts:
            return False
        now = self._clock()
        due = [r for r in self._restarts if r["at"] <= now]
        if not due:
            return False
        self._restarts = [r for r in self._restarts if r["at"] > now]
        for r in due:
            lineage = r["lineage"]
            # retire the dead incarnation the moment its successor exists
            if r.get("from") is not None and self.get(r["from"]) is not None:
                self.remove(r["from"])
            try:
                self.add_worker(lineage["spec"], lineage=lineage)
            except Exception:  # spawn itself failed: burn another attempt
                self._schedule_restart_lineage(lineage)
                continue
            self._n["worker_restarts"] += 1
            stat_add("STAT_fleet_worker_restarts")
            _obs()["worker_restarts"].inc()
        return True

    # -- health --------------------------------------------------------
    def _update_health(self):
        thr = self.slow_threshold_s
        if thr is None:
            return
        for rep in self.replicas((HEALTHY, DEGRADED)):
            if (rep.state == HEALTHY and rep.steps >= 3
                    and rep.step_ewma is not None and rep.step_ewma > thr):
                rep.state = DEGRADED
                rep.fence_reason = (
                    f"slow: step EWMA {rep.step_ewma * 1e3:.1f}ms > "
                    f"{thr * 1e3:.1f}ms")
                self._n["failovers"] += 1
                stat_add("STAT_fleet_failovers")
                _obs()["failovers"].inc()
                self._publish_up(rep)
            elif (rep.state == DEGRADED and rep.step_ewma is not None
                    and rep.step_ewma < 0.5 * thr
                    and rep.fast_steps >= self.probation_steps):
                # brownout over: probation passed, return to rotation
                rep.state = HEALTHY
                rep.fence_reason = None
                self._publish_up(rep)

    def _on_crash(self, rep: Replica, exc: BaseException):
        """SIGKILL-equivalent loss: the engine had no chance to fail its
        runs and its device state is gone.  Fence it, then give every
        resident stream a future — resubmission for greedy opt-ins,
        the typed ReplicaLostError for the rest, a plain re-route for
        queued work that never started.  A crashed subprocess worker is
        additionally reaped (no zombies) and handed to the restart
        supervisor."""
        rep.state = CRASHED
        rep.fence_reason = repr(exc)
        self._n["failovers"] += 1
        stat_add("STAT_fleet_failovers")
        _obs()["failovers"].inc()
        self._publish_up(rep)
        self._fail_over_all(rep)
        if isinstance(rep, SubprocessReplica):
            rep.engine.kill()
            self._schedule_restart(rep)

    def _fail_over_all(self, rep: Replica):
        """Give every consumer of an unreachable replica a future.
        Parked OOM snapshots count as lost too: in the real deployment
        they lived in the dead process.  For a subprocess replica,
        `_slots` is the proxy's residency mirror (everything shipped to
        the worker) and the scheduler queue is the LOCAL not-yet-shipped
        backlog — together they cover every accepted request."""
        engine = rep.engine
        lost = [(run.req, run.resp) for run in engine._slots.values()]
        # release the scheduler's host-side slot bookkeeping too: the
        # engine is abandoned, but its occupancy gauge / slots-active
        # stat / Request refs must not be pinned forever by a dead
        # replica that stays listed until remove()
        for slot in list(engine._slots):
            engine.scheduler.release(slot)
        engine._slots.clear()
        if getattr(engine, "kv", "fixed") == "paged":
            lost.extend((p.req, p.resp) for p in engine._oom_paused)
            engine._oom_paused = []
        for req, resp in lost:
            self._failover_lost(req, resp, rep.id)
        # queued-but-never-prefilled: nothing was delivered, re-route
        for req, resp in engine.scheduler.drain_pending():
            self._reroute(req, resp, exclude_id=rep.id)

    def _failover_lost(self, req: Request, resp: Response, crashed_id: int):
        produced = len(resp.tokens_so_far())
        if req.resubmit and req.greedy:
            if self._resubmit(req, resp, produced, crashed_id):
                self._n["resubmits"] += 1
                stat_add("STAT_fleet_resubmits")
                return
        self._n["lost"] += 1
        stat_add("STAT_fleet_lost_runs")
        resp._fail(ReplicaLostError(
            f"request {req.id}: replica {crashed_id} crashed mid-decode "
            f"and its run snapshot was lost ({produced} tokens were "
            "delivered); "
            + ("no surviving replica could take the resubmission"
               if req.resubmit and req.greedy else
               "submit with resubmit=True (greedy) to opt into "
               "re-prefill-from-prompt recovery")))

    def _resubmit(self, req: Request, resp: Response, produced: int,
                  crashed_id: int) -> bool:
        """Replay a lost greedy run from its prompt on a survivor; the
        forwarding response swallows the `produced` already-delivered
        tokens so the consumer's stream continues bit-identically."""
        # chains: if resp is itself a forwarding bridge (second crash),
        # target the ORIGINAL stream with a recomputed skip — the
        # bridge's internal token count equals what the original has
        # seen end-to-end
        target = resp._target if isinstance(resp, _ForwardingResponse) \
            else resp
        for rep in self._targets(exclude_id=crashed_id):
            engine = rep.engine
            try:
                shadow, _ = engine.make_request(
                    req.prompt, req.max_new_tokens,
                    decode_strategy="greedy_search",
                    eos_token_id=req.eos_token_id, seed=req.seed,
                    priority=req.priority, tenant=req.tenant,
                    spec=(req.spec if engine.draft_model is not None
                          else False),
                    session=req.session, resubmit=True)
            except Exception:
                continue
            # the original deadline keeps ticking from the original
            # submission — a crash must not silently extend a budget
            shadow.deadline = req.deadline
            fwd = _ForwardingResponse(shadow, target, skip=produced)
            try:
                engine.scheduler.submit(shadow, fwd)
            except QueueFullError:
                continue
            return True
        return False

    def _reroute(self, req: Request, resp: Response, exclude_id: int,
                 fallback_engine=None):
        """Re-home a queued (never-prefilled) request.  `fallback_engine`
        is the still-alive source engine of a DRAIN: with no peer queue
        space the request stays on it and is served before the drain
        completes.  A CRASH has no fallback — the engine is gone — so
        exhausting the peers is the typed terminal state."""
        for rep in self._targets(exclude_id=exclude_id):
            try:
                rep.engine.scheduler.submit(req, resp)
            except QueueFullError:
                continue
            self._n["reroutes"] += 1
            stat_add("STAT_fleet_reroutes")
            return
        if fallback_engine is not None:
            try:
                # its queue was just drained, so space exists
                fallback_engine.scheduler.submit(req, resp)
                return
            except QueueFullError:
                pass
        self._n["lost"] += 1
        stat_add("STAT_fleet_lost_runs")
        resp._fail(ReplicaLostError(
            f"request {req.id}: replica {exclude_id} was fenced before "
            "prefill and no surviving replica had queue space"))

    def _targets(self, exclude_id: Optional[int] = None) -> List[Replica]:
        reps = [r for r in self.routable() if r.id != exclude_id]
        reps.sort(key=lambda r: (r.load(), r.id))
        return reps

    # -- migration -----------------------------------------------------
    def _pump_migrations(self) -> bool:
        """Move residents off fenced-but-alive replicas (drain or
        brownout) through the run-transfer codec.  A run is only
        preempted once a peer with a free slot exists; a paged-block
        shortfall at restore parks the snapshot for retry instead of
        dropping it."""
        did = False
        for rep in self.replicas((DRAINING, DEGRADED)):
            try:
                did = self._migrate_residents(rep) or did
            except WorkerDiedError as e:
                # the SOURCE worker died mid-preempt (or turned out to
                # be wedged): crash semantics take over
                self._on_crash(rep, e)
                did = True
        return did

    def _migrate_residents(self, rep: Replica) -> bool:
        did = False
        for slot in sorted(rep.engine._slots):
            target = self._pick_slot_target(exclude_id=rep.id)
            if target is None:
                break  # fleet full: residents finish in place
            run = rep.engine._slots.get(slot)
            if run is None:
                continue
            try:
                paused = rep.engine.preempt_slot(slot)
            except InvalidArgumentError:
                # the run finished in the race window (a subprocess
                # worker keeps stepping between our scan and the RPC)
                continue
            blob = encode_run(paused)
            try:
                snap = decode_run(blob, req=paused.req,
                                  resp=paused.resp,
                                  engine=target.engine)
            except RunTransferError as e:
                # incompatible peer: the run must fail typed, not be
                # written into a pool it does not fit
                self._n["lost"] += 1
                stat_add("STAT_fleet_lost_runs")
                paused.resp._fail(e)
                did = True
                continue
            try:
                restored = target.engine.restore_run(snap)
            except RunTransferError as e:
                self._n["lost"] += 1
                stat_add("STAT_fleet_lost_runs")
                paused.resp._fail(e)
                did = True
                continue
            except WorkerDiedError as e:
                # the TARGET died mid-restore; the snapshot survives on
                # this side — park it and let failover handle the peer
                self._on_crash(target, e)
                self._parked.append(snap)
                did = True
                continue
            if restored:
                snap.req.migrations += 1
                self._n["migrated"] += 1
                stat_add("STAT_fleet_migrated_runs")
                _obs()["migrated"].inc()
            else:
                self._parked.append(snap)
            did = True
        return did

    def _pick_slot_target(self, exclude_id: int) -> Optional[Replica]:
        cands = [r for r in self._targets(exclude_id)
                 if r.engine.scheduler.free_slot_count() > 0]
        return cands[0] if cands else None

    def _pump_parked(self) -> bool:
        did = False
        still = []
        for snap in self._parked:
            placed = False
            for rep in self._targets():
                if rep.engine.scheduler.free_slot_count() <= 0:
                    continue
                try:
                    restored = rep.engine.restore_run(snap)
                except RunTransferError:
                    continue  # incompatible peer: try the next one
                except WorkerDiedError as e:
                    self._on_crash(rep, e)
                    continue
                if restored:
                    snap.req.migrations += 1
                    self._n["migrated"] += 1
                    stat_add("STAT_fleet_migrated_runs")
                    _obs()["migrated"].inc()
                    placed = did = True
                    break
            if not placed:
                still.append(snap)
        self._parked = still
        return did

    def _sweep_parked(self):
        """Parked snapshots still honor cancel/deadline — a run waiting
        out a full fleet must reach its terminal state on time."""
        keep = []
        for p in self._parked:
            if p.resp.cancelled:
                p.resp._fail(RequestCancelled(
                    f"request {p.req.id} cancelled while parked for "
                    "replica migration"))
            elif p.req.deadline is not None and p.req.deadline.expired():
                p.resp._fail(DeadlineExceededError(
                    f"request {p.req.id} deadline "
                    f"({p.req.deadline.seconds}s) expired while parked "
                    "for replica migration"))
            else:
                keep.append(p)
        self._parked = keep

    def _finish_drains(self) -> bool:
        did = False
        for rep in self.replicas((DRAINING,)):
            if rep.engine.has_work():
                continue
            # flip state under the lock so a concurrent remove() sees
            # either DRAINING (defers via the flag) or CLOSED (removes
            # directly) — never a half-closed in-between
            with self._lock:
                rep.state = CLOSED
                do_remove = rep.remove_after_drain
            rep.engine.close()
            self._publish_up(rep)
            if do_remove:
                # outside self._lock: remove() takes it (non-reentrant)
                self.remove(rep.id)
            did = True
        return did

    # -- continuous weight refresh ------------------------------------
    def flip_weights(self, rid: int, path: Optional[str] = None,
                     sha: Optional[str] = None,
                     state: Optional[Dict] = None) -> Dict:
        """Schedule a weight flip on replica `rid`: the replica is
        fenced from NEW admissions immediately (`routable()` excludes a
        flipping replica, so the router and the affinity map stop
        feeding it and sessions re-home), its queued/resident work
        finishes in place on the OLD weights, and `_pump_flips` applies
        the swap at the idle boundary — zero recompiles (the engine's
        compiled programs take the state as a per-call argument), zero
        dropped streams, and no stream ever spans two weight sets.

        In-process replicas take `state` (a host state dict) or `path`
        (a jit.save npz); subprocess/remote replicas take `path` +
        `sha` and the artifact ships over the sha256-verified channel.
        Returns the flip entry — poll ``entry["done"]`` /
        ``entry["ok"]`` / ``entry["error"]`` for the outcome.  A failed
        flip (ship error, sha mismatch, shape mismatch) leaves the
        replica serving the OLD weights and routable again."""
        rep = self.get(rid)
        if rep is None or rep.state not in _LIVE:
            raise InvalidArgumentError(
                f"replica {rid} is not live; cannot flip weights")
        entry = {"rid": rid, "path": path, "sha": sha, "state": state,
                 "done": False, "ok": None, "error": None}
        rep.flipping = True
        self._publish_up(rep)
        self._flips.append(entry)
        return entry

    def _pump_flips(self) -> bool:
        """Apply pending weight flips on replicas that reached their
        idle boundary.  A flip onto a replica that crashed/was fenced
        meanwhile fails typed; a worker dying mid-swap takes the normal
        crash path (failover + supervised restart with the NEW spec)."""
        if not self._flips:
            return False
        did = False
        still = []
        for entry in self._flips:
            rep = self.get(entry["rid"])
            if rep is None or rep.state not in _LIVE:
                entry.update(done=True, ok=False,
                             error=f"replica {entry['rid']} is no "
                                   "longer live")
                did = True
                continue
            if rep.engine.has_work():
                still.append(entry)  # old-weights work still in flight
                continue
            try:
                self._apply_flip(rep, entry)
                entry.update(done=True, ok=True)
                self._n["weight_refreshes"] += 1
                stat_add("STAT_fleet_weight_refreshes")
                _obs()["refreshes"].inc()
            except WorkerDiedError as e:
                # partition/death mid-flip: crash semantics — residents
                # were already drained (idle boundary), the supervisor
                # restarts from the updated lineage spec
                entry.update(done=True, ok=False, error=repr(e))
                self._on_crash(rep, e)
            except Exception as e:  # noqa: BLE001 — typed ship/shape errs
                # the swap was REJECTED (sha mismatch, shape mismatch,
                # truncated artifact): the replica still serves the old
                # weights — unfence it and report the failure
                entry.update(done=True, ok=False,
                             error=f"{type(e).__name__}: {e}")
            rep.flipping = False
            self._publish_up(rep)
            did = True
        self._flips = still
        return did

    def _apply_flip(self, rep: Replica, entry: Dict):
        if isinstance(rep, SubprocessReplica):
            if entry["path"] is None:
                raise InvalidArgumentError(
                    "a subprocess/remote replica flip needs a weight "
                    "artifact path (state dicts do not cross processes)")
            rep.engine.swap_weights(entry["path"], entry["sha"])
            # restarts must converge onto the new weights, not resurrect
            # the boot-time artifact
            rep.lineage["spec"]["weights"] = entry["path"]
        else:
            state = entry["state"]
            if state is None:
                if entry["path"] is None:
                    raise InvalidArgumentError(
                        "flip_weights needs `state` or `path`")
                import numpy as np
                with np.load(entry["path"], allow_pickle=False) as z:
                    state = {k: z[k] for k in z.files}
            rep.engine.swap_weights(state, entry["sha"])

    def flips_pending(self) -> int:
        return len(self._flips)

    # -- multi-tenant LoRA: fleet-wide adapter hot-load ----------------
    def load_adapter(self, name: str, path: str,
                     sha: Optional[str] = None) -> Dict[int, str]:
        """Page the LoRA adapter artifact at `path` into EVERY live
        warm replica's registry under `name`.  Additive and
        recompile-free, so unlike a weight flip there is NO idle
        fencing: in-flight streams keep decoding on their adapters
        while the new factor stacks page in.  In-process replicas read
        the file directly; subprocess replicas verify it over the local
        RPC; remote replicas receive it over the chunked
        sha256-verified channel — zero bytes when the identical
        artifact is already resident, one supervised re-ship when a
        chunk or read is corrupt.  The adapter is recorded so every
        later boot/restart converges (`_converge_adapters`).  Returns
        {rid: file_sha} for the replicas that now hold it.  A replica
        that refuses (corrupt read after re-ship, base mismatch, all
        slots pinned) keeps serving what it had — partial success is
        success, requests naming the adapter on the skewed replica fail
        typed at admission; only when EVERY replica refuses is the
        shared root cause re-raised and nothing recorded."""
        results: Dict[int, str] = {}
        errors: Dict[int, BaseException] = {}
        for rep in self.replicas(_LIVE):
            if not rep.engine.warm:
                continue  # _converge_adapters loads it when warm
            try:
                results[rep.id] = rep.engine.load_adapter(name, path)
            except WorkerDiedError as e:
                errors[rep.id] = e
                self._on_crash(rep, e)
            except Exception as e:  # noqa: BLE001 — typed per-replica
                #                     reject; the replica keeps serving
                errors[rep.id] = e
        if errors and not results:
            # every replica refused: surface the (shared) root cause
            raise next(iter(errors.values()))
        self._adapters[name] = (path, sha)
        stat_add("STAT_lora_fleet_loads")
        return results

    def _converge_adapters(self, rep: Replica):
        """Re-load every recorded adapter onto a freshly-warm replica
        (boot or supervised restart): a restarted worker's empty
        registry must not silently drop a tenant's adapter from part
        of the fleet.  A refusal leaves the replica serving — requests
        naming the missing adapter fail typed at admission (never a
        hung consumer) — but is counted so operators see the skew."""
        for name, (path, _sha) in list(self._adapters.items()):
            try:
                rep.engine.load_adapter(name, path)
            except Exception:  # noqa: BLE001 — typed refusal, counted
                stat_add("STAT_lora_converge_failures")

    # counters the refresher/autoscaler (which run OFF the driving
    # thread) report through, so every counter/stat/gauge stays in one
    # place
    def note_rollback(self):
        self._n["rollbacks"] += 1
        stat_add("STAT_fleet_rollbacks")
        _obs()["rollbacks"].inc()

    def note_scale(self, up: bool):
        key = "scale_up" if up else "scale_down"
        self._n[key] += 1
        stat_add(f"STAT_fleet_{key}")
        _obs()[key].inc()

    def set_target_replicas(self, n: int):
        _obs()["target_replicas"].set(int(n))

    # -- shutdown ------------------------------------------------------
    def abort_all(self, make_exc: Callable):
        for rep in self.replicas(_LIVE):
            rep.engine._abort_all(make_exc)
        parked, self._parked = self._parked, []
        for p in parked:
            p.resp._fail(make_exc(p.req))

    def close_all(self):
        # the supervisor dies with the fleet: no restart may spawn a
        # worker after close, and no wedged corpse may outlive it
        self._restarts = []
        self._pending_kills = []
        for rep in self.replicas(_LIVE):
            rep.engine.close()
            rep.state = CLOSED
            self._publish_up(rep)
        # reap EVERY subprocess — crashed/wedged corpses still listed
        # until remove() included: router close leaves no orphan
        # processes and no zombies behind (corpses get the non-graceful
        # path: no 2s wait on a process that cannot answer)
        for rep in self.replicas():
            if isinstance(rep, SubprocessReplica):
                rep.engine.close(graceful=rep.state == CLOSED)
        parked, self._parked = self._parked, []
        for p in parked:
            p.resp._fail(RequestCancelled(
                f"request {p.req.id} aborted: fleet closed while the run "
                "was parked for migration"))

    # -- observability -------------------------------------------------
    def _publish_up(self, rep: Replica):
        _obs()["up"].labels(replica=str(rep.id)).set(
            1 if rep.routable() else 0)
        self._publish_counts()

    def _publish_counts(self):
        _obs()["replicas_up"].set(len(self.routable()))

    def _publish_inflight(self):
        obs = _obs()
        workers_alive = 0
        for rep in self.replicas(_LIVE):
            obs["inflight"].labels(replica=str(rep.id)).set(rep.load())
            age = rep.heartbeat_age()
            if age is not None:
                obs["hb_age"].labels(replica=str(rep.id)).set(age)
            if (isinstance(rep, SubprocessReplica)
                    and rep.engine.process_alive()):
                workers_alive += 1
        obs["workers"].set(workers_alive)

    def stale_routable(self) -> List[int]:
        """Routable replica ids whose heartbeat age exceeds the wedge
        threshold RIGHT NOW — normally empty (a stale replica is fenced
        on the next tick), but nonzero when the DRIVING LOOP itself has
        stalled, which is exactly when an external health scraper is the
        only observer left."""
        if self.heartbeat_timeout_s is None:
            return []
        out = []
        for rep in self.routable():
            age = rep.heartbeat_age()
            if age is not None and age > self.heartbeat_timeout_s:
                out.append(rep.id)
        return out

    def counters(self) -> Dict:
        return dict(self._n, parked=len(self._parked),
                    pending_restarts=len(self._restarts),
                    pending_flips=len(self._flips))


class _FleetSchedulerView:
    """The slice of RequestScheduler the gateway's signals consume,
    aggregated over the fleet: free slots on ROUTABLE replicas only
    (fenced capacity must not attract admissions), occupancy and queue
    depth over everything still alive (that work is real)."""

    def __init__(self, manager: ReplicaManager):
        self._m = manager

    def free_slot_count(self) -> int:
        return sum(r.engine.scheduler.free_slot_count()
                   for r in self._m.routable())

    def occupancy(self) -> int:
        return sum(r.engine.scheduler.occupancy()
                   for r in self._m.replicas(_LIVE))

    def queue_depth(self) -> int:
        return sum(r.engine.scheduler.queue_depth()
                   for r in self._m.replicas(_LIVE))

    def has_work(self) -> bool:
        return any(r.engine.scheduler.has_work()
                   for r in self._m.replicas(_LIVE))


class FleetRouter:
    """N replicas behind one front door.

    ::

        fleet = FleetRouter([make_engine() for _ in range(3)],
                            slow_threshold_ms=50)
        fleet.warmup()                  # all replicas routable
        fleet.start()                   # or front it with ServingGateway
        r = fleet.submit(prompt, 64, session="user-7", resubmit=True)
        for tok in r: ...
        fleet.rollout(lambda: ServingEngine(model, program_set=path, ...))
        fleet.close()

    Implements the engine-facing surface `ServingGateway` consumes, so
    ``ServingGateway(fleet, ...)`` turns the PR-6 multi-tenant front
    door into a cluster front door — the gateway's loop drives
    `fleet.step()` exactly as it drove a single engine's."""

    def __init__(self, replicas=(),
                 slow_threshold_ms: Optional[float] = None,
                 affinity: bool = True, max_sessions: int = 4096,
                 prefix_affinity: bool = False,
                 prefix_affinity_tokens: int = 32,
                 heartbeat_timeout_s: Optional[float] = 10.0,
                 kill_grace_s: float = 2.0,
                 restart_backoff: Optional[RestartBackoff] = None,
                 workers=()):
        self.manager = ReplicaManager(
            slow_threshold_ms=slow_threshold_ms,
            heartbeat_timeout_s=heartbeat_timeout_s,
            kill_grace_s=kill_grace_s, restart_backoff=restart_backoff)
        for engine in replicas:
            self.manager.add(engine)
        for spec in workers:
            self.manager.add_worker(spec)
        self._affinity_enabled = bool(affinity)
        # prefix-affine routing (opt-in): sessionless requests pin by a
        # hash of (tenant, leading prompt tokens), so templated traffic
        # concentrates where its cached prefix blocks live — same LRU
        # map, same eviction policy, same fence re-homing as sessions
        self._prefix_affinity = bool(prefix_affinity)
        self._prefix_tokens = max(1, int(prefix_affinity_tokens))
        # LRU-bounded: one entry per live session key, refreshed on use —
        # a long-lived fleet serving millions of distinct users must not
        # grow an entry per user ever seen
        self._affinity: Dict[str, int] = {}
        self._max_sessions = max(1, int(max_sessions))
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._work = threading.Event()
        self._closed = False
        self._close_lock = threading.Lock()
        self._dead: Optional[BaseException] = None
        # the attached FleetRefresher (serving/refresh.py), if any: it
        # supplies the canary verdict behind `routable_verified` — with
        # none attached, every routable replica counts as verified
        self._refresher = None

    # -- membership / lifecycle ---------------------------------------
    def add_replica(self, engine: ServingEngine) -> int:
        """Add a replica (warm it first, or call `warmup()`); returns its
        id.  A not-yet-warm replica is never routed to."""
        if self._closed:
            raise UnavailableError("fleet is closed")
        return self.manager.add(engine).id

    def add_worker(self, spec: Dict, boot_timeout_s: float = 180.0,
                   rpc_timeout_s: float = 15.0,
                   address: Optional[str] = None,
                   **client_extra) -> int:
        """Spawn a SUBPROCESS replica from a worker boot spec (see
        serving/worker.py: model factory + engine config + optional AOT
        program set) and return its replica id.  The worker boots and
        warms in its own process; the driving loop flips it routable at
        the ready handshake (or block on `warmup()`).  Crash/wedge
        handling, SIGKILL and supervised restart are automatic.

        `address="HOST:PORT"` attaches to a REMOTE standalone worker
        (started with ``--listen``) instead of spawning one: weights
        (``spec["weights"]``) and optionally the program set
        (``spec["ship_program_set"]=True``) ship over the attach
        handshake, liveness rides beat frames, and partition fencing is
        epoch-tokened (see RemoteWorkerClient).  Extra keyword args
        (`manager_silence_s`, `connect_timeout_s`, ...) pass through to
        the client."""
        if self._closed:
            raise UnavailableError("fleet is closed")
        rep = self.manager.add_worker(spec, boot_timeout_s=boot_timeout_s,
                                      rpc_timeout_s=rpc_timeout_s,
                                      address=address, **client_extra)
        self._work.set()
        return rep.id

    def drain(self, rid: int):
        self.manager.drain(rid)
        with self._lock:
            self._affinity = {s: r for s, r in self._affinity.items()
                              if r != rid}
        self._work.set()

    def flip_weights(self, rid: int, path: Optional[str] = None,
                     sha: Optional[str] = None,
                     state: Optional[Dict] = None) -> Dict:
        """Schedule a zero-recompile weight flip on one replica (see
        ReplicaManager.flip_weights).  Sessions pinned to it re-home
        while the flip is pending."""
        entry = self.manager.flip_weights(rid, path=path, sha=sha,
                                          state=state)
        with self._lock:
            self._affinity = {s: r for s, r in self._affinity.items()
                              if r != rid}
        self._work.set()
        return entry

    def load_adapter(self, name: str, path: str,
                     sha: Optional[str] = None) -> Dict[int, str]:
        """Fleet-wide LoRA adapter hot-load (see
        ReplicaManager.load_adapter): page the artifact into every live
        warm replica's registry — additive, recompile-free, no fencing,
        and recorded so boots/restarts converge onto it."""
        out = self.manager.load_adapter(name, path, sha=sha)
        self._work.set()
        return out

    def attach_refresher(self, refresher):
        """Register the FleetRefresher whose canary verdicts back the
        `routable_verified` health field (and the gateway's 503-on-
        unverified-fleet rule)."""
        self._refresher = refresher

    def remove(self, rid: int):
        self.manager.remove(rid)

    def warmup(self) -> Dict[int, Dict]:
        return self.manager.warm_all()

    def rollout(self, factory: Callable[[], ServingEngine],
                timeout: float = 300.0, drive: bool = False) -> List[int]:
        """Zero-downtime rolling restart: for each current replica, boot
        a replacement via `factory` (typically
        ``ServingEngine(model, program_set=...)`` — seconds, zero
        compiles), warm it, add it, drain the old one and wait for its
        residents to migrate or finish, then remove it.  Traffic keeps
        flowing the whole time.  `drive=True` steps the fleet from this
        thread while waiting (ONLY when nothing else drives the loop —
        no `start()`, no gateway); the default polls."""
        old_ids = [r.id for r in self.manager.replicas(_LIVE)]
        new_ids = []
        for rid in old_ids:
            engine = factory()
            if not engine.warm:
                engine.warmup()
            new_ids.append(self.add_replica(engine))
            # the boot's warmup compiles (zero when factory loads a
            # program set) must not count against the PEERS' post-warmup
            # marks — the registry is process-global
            self.manager.refresh_warm_marks()
            self.drain(rid)
            t0 = time.monotonic()
            while True:
                rep = self.manager.get(rid)
                if rep is None or rep.state in (CLOSED, CRASHED):
                    break
                if drive:
                    self.step()
                else:
                    time.sleep(0.005)
                if time.monotonic() - t0 > timeout:
                    raise TimeoutError(
                        f"replica {rid} did not drain in {timeout}s "
                        f"({rep.engine.scheduler.occupancy()} residents)")
            self.remove(rid)
        return new_ids

    # -- engine-compatible surface (what ServingGateway consumes) -----
    @property
    def scheduler(self) -> _FleetSchedulerView:
        return _FleetSchedulerView(self.manager)

    @property
    def max_slots(self) -> int:
        return sum(r.engine.max_slots for r in self.manager.replicas(_LIVE))

    @property
    def warm(self) -> bool:
        live = self.manager.replicas(_LIVE)
        return bool(live) and all(r.engine.warm for r in live)

    @property
    def _slots(self) -> Dict:
        """Merged {(replica_id, slot): run} view over live replicas —
        the gateway's preemption victim scan."""
        merged = {}
        for rep in self.manager.replicas(_LIVE):
            for slot, run in rep.engine._slots.items():
                merged[(rep.id, slot)] = run
        return merged

    def make_request(self, prompt, max_new_tokens: int, **kwargs):
        """Validate against a live replica's limits (the fleet is
        homogeneous by contract: every replica serves the same model
        with the same engine config)."""
        if self._closed:
            raise UnavailableError("fleet is closed")
        if self._dead is not None:
            raise UnavailableError(f"fleet loop died: {self._dead!r}")
        reps = self.manager.routable() or self.manager.replicas(_LIVE)
        if not reps:
            raise UnavailableError("fleet has no live replicas")
        return reps[0].engine.make_request(prompt, max_new_tokens,
                                           **kwargs)

    def _affinity_key(self, req: Request) -> Optional[str]:
        """The routing-affinity key: an explicit session always wins;
        with `prefix_affinity` on, a sessionless request pins by a hash
        of its tenant + leading prompt tokens (the same prefix the radix
        cache indexes), so warm prefixes land where their blocks live."""
        if req.session:
            return req.session
        if not self._prefix_affinity:
            return None
        import hashlib
        import numpy as np
        head = np.asarray(req.prompt[:self._prefix_tokens], np.int32)
        h = hashlib.blake2b((req.tenant or "").encode() + b"\0"
                            + head.tobytes(), digest_size=8)
        return "px:" + h.hexdigest()

    def set_share_groups(self, groups: Dict[str, str]):
        """Broadcast the gateway's tenant -> KV share-group mapping to
        every replica engine that supports a prefix cache."""
        for rep in self.manager.replicas(_LIVE):
            fn = getattr(rep.engine, "set_share_groups", None)
            if fn is not None:
                fn(groups)

    def try_admit(self, req: Request, resp: Response) -> bool:
        """Place the request NOW on the best replica (affinity, then
        least-loaded) — the gateway's admission path; must run on the
        driving thread."""
        akey = self._affinity_key(req)
        for rep in self._route_order(akey):
            if rep.engine.try_admit(req, resp):
                self._note_affinity(akey, rep.id)
                return True
        return False

    def preempt_slot(self, key) -> PreemptedRun:
        rid, slot = key
        rep = self.manager.get(rid)
        if rep is None or rep.state not in _LIVE:
            raise InvalidArgumentError(f"replica {rid} is not live")
        try:
            return rep.engine.preempt_slot(slot)
        except WorkerDiedError as e:
            # the worker turned out dead/wedged mid-preempt: crash
            # semantics fail the victim over, and the caller (the
            # gateway's preemption scan) sees the replica-not-live error
            # it already tolerates
            self.manager._on_crash(rep, e)
            raise InvalidArgumentError(
                f"replica {rid} died during preempt: {e}")

    def restore_run(self, paused: PreemptedRun) -> bool:
        """Resume a preempted run on ANY replica with capacity — the
        gateway's restore path, now fleet-wide (the snapshot format is
        replica-portable by construction)."""
        for rep in self.manager._targets():
            if rep.engine.scheduler.free_slot_count() <= 0:
                continue
            try:
                check_compatible(encode_run(paused), rep.engine)
            except RunTransferError:
                continue
            try:
                if rep.engine.restore_run(paused):
                    return True
            except RunTransferError:
                continue
            except WorkerDiedError as e:
                self.manager._on_crash(rep, e)
                continue
        return False

    def step(self) -> bool:
        if self._closed:
            return False
        return self.manager.tick()

    def has_work(self) -> bool:
        return (any(r.engine.has_work()
                    for r in self.manager.replicas(_LIVE))
                or bool(self.manager._parked))

    def _abort_all(self, make_exc):
        self.manager.abort_all(make_exc)

    # -- submission (caller threads) ----------------------------------
    def submit(self, prompt, max_new_tokens: int, block: bool = False,
               timeout: Optional[float] = None, **kwargs) -> Response:
        """Route one request: session-affine when `session=` was given
        and its replica is still healthy, least-loaded otherwise.  Raises
        the same typed errors `ServingEngine.submit` raises; every
        accepted request's Response reaches a terminal state even if its
        replica later dies (failover / resubmit / typed error)."""
        req, resp = self.make_request(prompt, max_new_tokens, **kwargs)
        last_exc = None
        akey = self._affinity_key(req)
        for rep in self._route_order(akey):
            try:
                rep.engine.scheduler.submit(req, resp, block=block,
                                            timeout=timeout)
            except QueueFullError as e:
                last_exc = e
                continue
            self._note_affinity(akey, rep.id)
            self._work.set()
            return resp
        raise last_exc or UnavailableError(
            "no routable replica accepted the request")

    def _route_order(self, session: Optional[str]) -> List[Replica]:
        reps = self.manager._targets()
        if not (self._affinity_enabled and session):
            return reps
        with self._lock:
            rid = self._affinity.get(session)
        if rid is not None:
            for i, rep in enumerate(reps):
                if rep.id == rid:
                    if i:
                        reps.insert(0, reps.pop(i))
                    return reps
            # the pinned replica is gone/fenced: re-home below
            with self._lock:
                self._affinity.pop(session, None)
        return reps

    def _note_affinity(self, session: Optional[str], rid: int):
        if self._affinity_enabled and session:
            with self._lock:
                # dict order is insertion order: delete-then-insert makes
                # this an LRU touch, and overflow evicts the oldest entry
                self._affinity.pop(session, None)
                self._affinity[session] = rid
                while len(self._affinity) > self._max_sessions:
                    self._affinity.pop(next(iter(self._affinity)))

    # -- driving -------------------------------------------------------
    def run_until_drained(self, timeout: Optional[float] = None):
        t0 = time.monotonic()
        while self.has_work():
            self.step()
            if timeout is not None and time.monotonic() - t0 > timeout:
                raise TimeoutError(f"fleet did not drain in {timeout}s")

    def start(self):
        """Background fleet loop.  Not for use under a gateway — the
        gateway's loop drives `step()` itself."""
        if self._thread is not None:
            return
        if self._closed:
            raise UnavailableError("fleet is closed")
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    did = self.step()
                except BaseException as e:  # noqa: BLE001 — no hangs
                    self._dead = e
                    self._abort_all(lambda req: UnavailableError(
                        f"request {req.id} aborted: fleet loop died: "
                        f"{e!r}"))
                    return
                if not did:
                    self._work.wait(0.002)
                    self._work.clear()

        self._thread = threading.Thread(target=loop, name="serving-fleet",
                                        daemon=True)
        self._thread.start()

    def close(self):
        """Close every replica; every outstanding request reaches a
        terminal state.  Idempotent and safe under concurrent
        double-close (same contract as the engine/gateway)."""
        self._closed = True
        self._stop.set()
        self._work.set()
        with self._close_lock:
            if self._thread is not None:
                self._thread.join(timeout=5.0)
                self._thread = None
            self.manager.close_all()
        with self._lock:
            self._affinity.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- introspection -------------------------------------------------
    def health(self) -> Dict:
        """Per-replica health + fleet aggregates — the gateway's
        /healthz fleet block.  `all_routable_stale` is the
        subprocess-deployment alarm: every replica the router would
        still send traffic to has a heartbeat older than the wedge
        threshold (normal fencing would have caught one stale replica —
        ALL stale means the driving loop itself stopped), and the
        gateway answers 503 on it."""
        reps = self.manager.replicas()
        stale = self.manager.stale_routable()
        routable = self.manager.routable()
        # verified = serving a weight set the canary gate passed (or the
        # boot-time weights, which predate any refresh).  No refresher
        # attached -> every routable replica is verified by definition.
        if self._refresher is None:
            verified = len(routable)
        else:
            verified = sum(
                1 for r in routable
                if self._refresher.sha_ok(
                    getattr(r.engine, "weights_sha", None)))
        out = {
            "replicas": {str(r.id): r.snapshot() for r in reps},
            "routable": len(routable),
            "routable_verified": verified,
            "total": len(reps),
            "workers": sum(1 for r in reps
                           if isinstance(r, SubprocessReplica)),
            "remote_workers": sum(1 for r in reps
                                  if isinstance(r, RemoteReplica)),
            "warm": self.warm,
            "heartbeat_timeout_s": self.manager.heartbeat_timeout_s,
            "stale_routable": stale,
            "all_routable_stale": bool(routable)
            and len(stale) == len(routable),
            **self.manager.counters(),
        }
        if self._refresher is not None:
            out["refresh"] = self._refresher.status()
        return out

    def post_warmup_compiles(self) -> int:
        """Worst replica's post-warmup compile count (the fleet contract
        is 0 on every replica); -1 if any live replica never warmed."""
        vals = [r.engine.post_warmup_compiles()
                for r in self.manager.replicas(_LIVE)]
        return max(vals) if vals else -1

    def metrics(self) -> Dict:
        live = self.manager.replicas(_LIVE)
        totals = {"requests_completed": 0, "requests_errored": 0,
                  "tokens_out": 0}
        per = {}
        for rep in self.manager.replicas():
            try:
                m = rep.engine.metrics()
            except Exception:
                m = {}
            if rep.state in _LIVE:
                for k in totals:
                    totals[k] += m.get(k) or 0
            per[str(rep.id)] = {"state": rep.state,
                                "occupancy": m.get("slot_occupancy"),
                                "queue_depth": m.get("queue_depth"),
                                "completed": m.get("requests_completed"),
                                "errored": m.get("requests_errored")}
        return {
            **totals,
            "replicas": per,
            "routable": len(self.manager.routable()),
            "live": len(live),
            "sessions": len(self._affinity),
            "prefix_affinity": self._prefix_affinity,
            "max_slots": self.max_slots,
            "warm": self.warm,
            "post_warmup_compiles": (self.post_warmup_compiles()
                                     if self.warm else None),
            **{f"fleet_{k}": v for k, v in self.manager.counters().items()},
        }
