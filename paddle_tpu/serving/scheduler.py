"""Request admission + slot bookkeeping for the serving engine.

The scheduler is pure host-side state: a bounded FIFO admission queue with
backpressure (submit blocks or rejects once `max_queue_depth` requests are
waiting) and a free-list of KV-cache slots.  The engine drives it: each
engine step first sweeps deadlines/cancellations, then admits as many
queued requests as there are free slots (each admission is one bucketed
prefill), then runs one decode step over every occupied slot.

Deadlines use `utils.retry.Deadline` — the same wall-clock-budget object
RetryPolicy enforces — counted from submission, so queue wait burns budget
exactly like a retry loop's backoff does.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Optional, Tuple

from ..core.errors import ResourceExhaustedError, ExecutionTimeoutError
from ..utils.monitor import stat_add
from .request import Request, Response, RequestCancelled

__all__ = ["RequestScheduler", "QueueFullError", "DeadlineExceededError"]

_obs_handles = None


def _obs():
    """(slot_occupancy_gauge, queue_depth_gauge, queue_full_counter) —
    cached observability handles (registry.reset() zeroes in place)."""
    global _obs_handles
    if _obs_handles is None:
        from ..observability import metrics as _m
        _obs_handles = (
            _m.gauge("serving_slot_occupancy",
                     "KV-cache slots currently decoding"),
            _m.gauge("serving_queue_depth",
                     "requests waiting for admission"),
            _m.counter("serving_queue_full_total",
                       "submissions rejected at max_queue_depth"))
    return _obs_handles


class QueueFullError(ResourceExhaustedError):
    """Admission queue at max_queue_depth: the request was rejected.  The
    backpressure signal — callers shed load or retry with backoff."""
    code = "ResourceExhausted"


class DeadlineExceededError(ExecutionTimeoutError):
    """The request's wall-clock deadline passed before it finished."""
    code = "ExecutionTimeout"


class RequestScheduler:
    """Admission queue + slot free-list.  Thread-safe: `submit` is called
    from caller threads, everything else from the engine loop."""

    def __init__(self, max_slots: int, max_queue_depth: int = 64):
        self.max_slots = int(max_slots)
        self.max_queue_depth = int(max_queue_depth)
        self._pending: "deque[Tuple[Request, Response]]" = deque()
        self._free = list(range(self.max_slots - 1, -1, -1))
        self._active = {}  # slot -> (Request, Response)
        self._lock = threading.Lock()
        self._space = threading.Condition(self._lock)

    # -- caller side --------------------------------------------------------
    def submit(self, req: Request, resp: Response, block: bool = False,
               timeout: Optional[float] = None):
        """Enqueue.  At max_queue_depth: raises QueueFullError (default) or,
        with block=True, waits up to `timeout` for space."""
        with self._space:
            if len(self._pending) >= self.max_queue_depth and block:
                self._space.wait_for(
                    lambda: len(self._pending) < self.max_queue_depth,
                    timeout=timeout)
            occ_g, depth_g, full_c = _obs()
            if len(self._pending) >= self.max_queue_depth:
                stat_add("STAT_serving_rejects")
                full_c.inc()
                raise QueueFullError(
                    f"serving queue full ({self.max_queue_depth} waiting); "
                    "request rejected")
            self._pending.append((req, resp))
            stat_add("STAT_serving_queue_depth")
            depth_g.set(len(self._pending))

    # -- engine side --------------------------------------------------------
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def active_slots(self):
        with self._lock:
            return dict(self._active)

    def occupancy(self) -> int:
        with self._lock:
            return len(self._active)

    def free_slot_count(self) -> int:
        with self._lock:
            return len(self._free)

    def has_work(self) -> bool:
        with self._lock:
            return bool(self._pending or self._active)

    def next_admission(self, gate=None):
        """Pop the next admissible (request, response, slot), failing
        cancelled/expired queued requests in passing.  None when the queue
        is empty or no slot is free (the popped-but-unadmittable case does
        not exist: a slot is acquired before the pop commits).  `gate`
        (optional, `gate(req) -> bool`) adds a resource check on the HEAD
        request before it pops — the paged engine's block-aware admission:
        a False keeps FIFO order and leaves the head queued
        (backpressure), it does not skip past it."""
        with self._space:
            occ_g, depth_g, _ = _obs()
            while self._pending:
                if not self._free:
                    return None
                req, resp = self._pending[0]
                disposable = (resp.cancelled
                              or (req.deadline is not None
                                  and req.deadline.expired()))
                if not disposable and gate is not None and not gate(req):
                    return None
                self._pending.popleft()
                self._space.notify()
                stat_add("STAT_serving_queue_depth", -1)
                depth_g.set(len(self._pending))
                if resp.cancelled:
                    stat_add("STAT_serving_cancelled")
                    resp._fail(RequestCancelled(
                        f"request {req.id} cancelled before prefill"))
                    continue
                if req.deadline is not None and req.deadline.expired():
                    stat_add("STAT_serving_deadline_expired")
                    resp._fail(DeadlineExceededError(
                        f"request {req.id} deadline "
                        f"({req.deadline.seconds}s) expired while queued"))
                    continue
                slot = self._free.pop()
                self._active[slot] = (req, resp)
                stat_add("STAT_serving_slots_active")
                occ_g.set(len(self._active))
                return req, resp, slot
            return None

    def acquire(self, req: Request, resp: Response) -> Optional[int]:
        """Directly claim a free slot for a request that bypasses the FIFO
        queue (the gateway's admission / preemption-restore path, which
        owns its own priority lanes).  Returns the slot, or None when every
        slot is occupied."""
        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop()
            self._active[slot] = (req, resp)
            stat_add("STAT_serving_slots_active")
            _obs()[0].set(len(self._active))
            return slot

    def release(self, slot: int):
        """Recycle a slot (completion, cancellation, deadline, or fault).
        The KV content is left as-is: the next prefill into this slot
        overwrites the full [0, max_len) range."""
        with self._lock:
            if slot in self._active:
                del self._active[slot]
                self._free.append(slot)
                stat_add("STAT_serving_slots_active", -1)
                _obs()[0].set(len(self._active))

    def drain_pending(self):
        """Remove and return every queued (request, response) — engine
        shutdown/death path; the caller fails the responses."""
        with self._space:
            drained = list(self._pending)
            if drained:
                stat_add("STAT_serving_queue_depth", -len(drained))
            self._pending = deque()
            _obs()[1].set(0)
            self._space.notify_all()
            return drained

    def sweep_pending(self, drop=None):
        """Fail queued requests whose deadline expired or that were
        cancelled, without waiting for a free slot.  `drop` (optional) is
        a ``(pred, make_exc)`` pair: requests with ``pred(req)`` True
        fail with ``make_exc(req)`` — the paged engine's
        can-never-admit check (a queued request whose blocks can never
        exist under the live pool capacity must reach a typed terminal,
        not wait forever).  Returns how many requests `drop` failed
        (pred/make_exc run UNDER the scheduler lock and must not take
        locks that are ever held around scheduler reads — the caller
        applies its own accounting from the return value)."""
        dropped = 0
        with self._space:
            keep = deque()
            for req, resp in self._pending:
                if resp.cancelled:
                    stat_add("STAT_serving_cancelled")
                    resp._fail(RequestCancelled(
                        f"request {req.id} cancelled before prefill"))
                elif req.deadline is not None and req.deadline.expired():
                    stat_add("STAT_serving_deadline_expired")
                    resp._fail(DeadlineExceededError(
                        f"request {req.id} deadline "
                        f"({req.deadline.seconds}s) expired while queued"))
                elif drop is not None and drop[0](req):
                    resp._fail(drop[1](req))
                    dropped += 1
                else:
                    keep.append((req, resp))
                    continue
                stat_add("STAT_serving_queue_depth", -1)
                self._space.notify()
            self._pending = keep
            _obs()[1].set(len(self._pending))
        return dropped
