"""Request / streaming-Response handles for the serving engine.

A `Request` is the immutable description of one decode job (prompt, token
budget, sampling params, deadline); a `Response` is the caller's handle on
its progress — a thread-safe iterator of generated token ids fed by the
engine loop, with TTFT recorded at the first yield and a typed error if the
request is rejected, cancelled, expired, or poisoned.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional

import numpy as np

from ..core.errors import EnforceNotMet
from ..utils.retry import Deadline

__all__ = ["Request", "Response", "RequestCancelled"]


class RequestCancelled(EnforceNotMet):
    """The caller cancelled the request before it completed."""
    code = "Cancelled"


class Request:
    """One decode job.  `greedy` requests ignore the sampling knobs and are
    the ones the engine guarantees bit-identical to a solo
    `generation.generate(decode_strategy='greedy_search')` run."""

    __slots__ = ("id", "prompt", "max_new_tokens", "greedy", "temperature",
                 "top_k", "top_p", "eos_token_id", "seed", "deadline",
                 "poison", "priority", "tenant", "preempts", "resumes",
                 "paused_seconds", "spec", "session", "resubmit",
                 "migrations", "adapter")

    def __init__(self, rid: int, prompt, max_new_tokens: int,
                 greedy: bool = True, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None,
                 seed: Optional[int] = None,
                 deadline: Optional[float] = None,
                 priority: int = 0, tenant: Optional[str] = None,
                 spec: bool = False, session: Optional[str] = None,
                 resubmit: bool = False, adapter: Optional[str] = None):
        self.id = int(rid)
        self.prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens <= 0:
            raise ValueError("max_new_tokens must be positive")
        self.greedy = bool(greedy)
        # None and 1.0 both mean "no tempering" (generation.generate
        # contract); 0.0 must NOT fold into them
        self.temperature = float(1.0 if temperature is None else temperature)
        self.top_k = int(top_k or 0)
        self.top_p = float(1.0 if top_p is None else top_p)
        self.eos_token_id = None if eos_token_id is None else int(eos_token_id)
        self.seed = seed
        # budget counts from SUBMISSION (queue wait included), the same
        # wall-clock semantics utils.retry.RetryPolicy enforces
        self.deadline = Deadline(deadline) if deadline is not None else None
        self.poison = False  # set by the engine under PDTPU_FAULT_NAN_LOGITS
        # speculative decoding: draft proposals verified/committed for this
        # request (engines with a draft model default it on; heterogeneous
        # spec on/off slots share the one verify trace via a dynamic mask)
        self.spec = bool(spec)
        # gateway lane / fairness attribution (0 = best effort; higher
        # priorities may preempt lower ones when a gateway fronts the
        # engine — the bare engine ignores both fields)
        self.priority = int(priority)
        self.tenant = tenant
        # lifecycle counters stamped by engine.preempt_slot/restore_run
        # (kept on the request so bookkeeping dies with it — a long-lived
        # gateway must not accumulate per-request state)
        self.preempts = 0
        self.resumes = 0
        self.paused_seconds = 0.0  # total wall time spent preempted
        # fleet routing (serving/fleet.py): requests sharing a session
        # key stick to one replica while it stays healthy; resubmit=True
        # (greedy-only, validated at make_request) opts the request into
        # re-prefill-from-prompt recovery when its replica crashes and
        # the run snapshot is lost with it.  migrations counts completed
        # cross-replica run transfers (drain/brownout failover).
        self.session = session
        self.resubmit = bool(resubmit)
        self.migrations = 0
        # batched LoRA (paddle_tpu.lora): registry name of the adapter
        # this request decodes under; None = the base model (adapter id
        # 0).  Resolved to a slot index and pinned at admission, unpinned
        # at release — the name (not the index) travels with the request
        # across preempt/restore and replica migration, so a restore on
        # a different replica re-resolves against ITS registry.
        self.adapter = adapter


_TOK, _END, _ERR = 0, 1, 2


class Response:
    """Streaming handle: iterate to receive generated token ids as the
    engine produces them.  Terminal state is exactly one of: finished
    (`finish_reason` in {"eos", "length"}), or errored (`error` set —
    rejection, cancellation, deadline expiry, non-finite logits).
    """

    def __init__(self, request: Request):
        self.request = request
        self._q: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._tokens: List[int] = []
        self._done = threading.Event()
        self.submitted_at = time.monotonic()
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.finish_reason: Optional[str] = None
        self.error: Optional[BaseException] = None
        self.logprob = 0.0
        self._cancel_requested = False

    # -- engine side --------------------------------------------------------
    def _push_token(self, tok: int, logp: float = 0.0):
        now = time.monotonic()
        with self._lock:
            if self.first_token_at is None:
                self.first_token_at = now
            self._tokens.append(int(tok))
            self.logprob += float(logp)
        self._q.put((_TOK, int(tok)))

    def _finish(self, reason: str):
        with self._lock:
            if self._done.is_set():
                return
            self.finished_at = time.monotonic()
            self.finish_reason = reason
            self._done.set()
        self._q.put((_END, reason))

    def _fail(self, exc: BaseException):
        with self._lock:
            if self._done.is_set():
                return
            self.finished_at = time.monotonic()
            self.finish_reason = "error"
            self.error = exc
            self._done.set()
        self._q.put((_ERR, exc))

    # -- caller side --------------------------------------------------------
    def cancel(self):
        """Ask the engine to drop this request: immediately effective for
        queued requests (never prefilled); an active request's slot is
        recycled at the next step boundary."""
        self._cancel_requested = True

    @property
    def cancelled(self) -> bool:
        return self._cancel_requested

    def done(self) -> bool:
        return self._done.is_set()

    @property
    def ttft(self) -> Optional[float]:
        """Seconds from submission to the first streamed token."""
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at

    def __iter__(self):
        while True:
            kind, val = self._q.get()
            if kind == _TOK:
                yield val
            elif kind == _END:
                return
            else:
                raise val

    def tokens(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the request reaches a terminal state, then return
        the full generated token list (raises the request's error if it
        failed)."""
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request.id} not finished after {timeout}s")
        if self.error is not None:
            raise self.error
        with self._lock:
            return list(self._tokens)

    def tokens_so_far(self) -> List[int]:
        with self._lock:
            return list(self._tokens)

    def result(self, timeout: Optional[float] = None):
        """(tokens, info) after completion; raises on failure."""
        toks = self.tokens(timeout)
        return toks, {"finish_reason": self.finish_reason,
                      "logprob": self.logprob, "ttft": self.ttft,
                      "latency": (self.finished_at - self.submitted_at
                                  if self.finished_at else None)}
