"""Paged KV-cache pool: block-granular KV allocation for the serving engine.

The PR-4 engine charges every resident request the full ``max_len`` of KV
HBM (`gen_fixed_cache(max_slots, max_len)` — one row per slot).  At
production scale that cap is the binding constraint: a 32-token request
and a 512-token request pay the same HBM, so the number of resident slots
is ``pool_bytes / max_len_bytes`` no matter what the traffic looks like.

vLLM's PagedAttention observation (Kwon et al., 2023) applied to this
engine: hold ONE device-resident block pool per layer —
``[num_blocks, block_size, heads, head_dim]`` — and give each slot an
indirection table of block ids covering exactly the rows it has actually
written.  Long and short requests then share HBM, and the resident-slot
count is bounded by *aggregate* live tokens, not ``slots * max_len``.

Split of responsibilities:

- **PagedKVPool** (here, host side): the block allocator — free-list,
  slot -> block-table indirection, alloc/append/free, capacity
  accounting (including the ``PDTPU_FAULT_KV_EXHAUST`` forced-exhaustion
  cap), and construction of the device pools from any model speaking the
  ``gen_fixed_cache`` protocol.  Pure host bookkeeping: nothing here is
  ever traced.
- **ops/paged_attention.py** (device side): the gather/scatter/scrub
  primitives the compiled serving programs use against the pool, plus
  the standalone paged-attention op (jnp gather fallback on CPU, pallas
  block-table kernel for TPU).
- **serving/engine.py**: `ServingEngine(kv="paged", block_size=...)`
  wires both into the unchanged engine contracts (compile bound,
  bit-identical streams, preempt/restore).

Scrub-on-recycle
----------------
Freed blocks return to the free-list and are re-served with a hard
no-stale-KV guarantee enforced INSIDE the compiled programs (zero extra
programs, zero idle HBM passes): a prefill overwrites every block it
claims end-to-end (prompt KV + zero padding to the block boundary), and
the decode/verify programs zero a block in full the moment a slot's
write position first enters it (``offset == 0``), before writing the new
row.  A block is only ever readable through a slot's table, tables only
cover rows the slot wrote, and the first write into a re-served block
erases all of it — so no request can observe another tenant's KV, and
the device state of a re-served block provably contains none
(tests/test_dist_serving.py::test_recycled_block_is_scrubbed).

Exhaustion is backpressure, not a crash: admission checks `free_blocks`
before claiming a slot, `ensure` returning False mid-decode triggers
preemption of the newest low-priority run (engine policy), and the typed
`KVPoolExhaustedError` is the terminal state for runs that can no longer
fit at all.  ``PDTPU_FAULT_KV_EXHAUST=N`` caps the live capacity to N
blocks to force every one of those paths on CPU.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ..core.errors import InvalidArgumentError, ResourceExhaustedError
from ..utils import faults

__all__ = ["PagedKVPool", "KVPoolExhaustedError"]


class KVPoolExhaustedError(ResourceExhaustedError):
    """The paged KV block pool cannot hold this run: every block is in
    use (or the pool is capped by PDTPU_FAULT_KV_EXHAUST) and no
    lower-priority victim can be preempted to make room.  The request is
    terminal — resubmit when the pool drains, or raise num_blocks."""
    code = "ResourceExhausted"


_obs_handles = None


def _obs():
    """(blocks_used_gauge, blocks_free_gauge) — cached handles
    (registry.reset() zeroes values in place)."""
    global _obs_handles
    if _obs_handles is None:
        from ..observability import metrics as _m
        _obs_handles = (
            _m.gauge("serving_kv_blocks_used",
                     "paged KV pool blocks currently allocated"),
            _m.gauge("serving_kv_blocks_free",
                     "paged KV pool blocks free (after any fault cap)"))
    return _obs_handles


class PagedKVPool:
    """Host-side block allocator over a device-resident block pool.

    ``build_pools(model, ...)`` constructs the per-layer device pools —
    each KV leaf of ``model.gen_fixed_cache(1, block_size)`` becomes a
    ``(num_blocks, block_size, *leaf.shape[2:])`` zero pool — and the
    allocator hands out block ids: ``alloc``/``ensure`` grow a slot's
    table to cover a row count, ``free`` recycles the slot's blocks,
    ``table_array`` renders the table as the fixed-shape
    ``(max_blocks_per_slot,)`` int32 input the compiled programs take
    (unallocated entries hold the ``num_blocks`` sentinel: reads clip to
    masked rows, writes drop).

    All mutation happens on the engine loop thread; the lock only guards
    the metric snapshots other threads read."""

    def __init__(self, num_blocks: int, block_size: int, pool_len: int):
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.pool_len = int(pool_len)
        if self.block_size < 1:
            raise InvalidArgumentError(
                f"block_size must be >= 1, got {self.block_size}")
        if self.num_blocks < 1:
            raise InvalidArgumentError(
                f"num_blocks must be >= 1, got {self.num_blocks}")
        # max blocks one slot can ever hold (its table's static width)
        self.max_blocks_per_slot = -(-self.pool_len // self.block_size)
        self._lock = threading.Lock()
        # LIFO free-list: the most recently freed block is re-served
        # first (deterministic recycling — the scrub proof relies on it)
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))
        self._tables: Dict[int, List[int]] = {}
        # debug/test aid: the most recent block ids handed out, in order —
        # the scrub-on-recycle proof reads which blocks were RE-served
        self.served_log: "deque[int]" = deque(maxlen=512)
        # bumped on every table mutation (growth or free): the engine
        # caches its device-side (tables, active) batch inputs against it
        # so unchanged ticks re-upload nothing
        self.version = 0

    # -- capacity ------------------------------------------------------------
    def capacity(self) -> int:
        """Usable blocks RIGHT NOW: num_blocks, unless
        PDTPU_FAULT_KV_EXHAUST caps it lower (consulted live)."""
        cap = faults.kv_exhaust_cap()
        return self.num_blocks if cap is None else min(self.num_blocks, cap)

    def used_blocks(self) -> int:
        with self._lock:
            return sum(len(t) for t in self._tables.values())

    def free_blocks(self) -> int:
        return max(0, self.capacity() - self.used_blocks())

    def blocks_for(self, rows: int) -> int:
        """Blocks needed to hold `rows` KV rows."""
        return -(-max(0, int(rows)) // self.block_size)

    def can_ever_fit(self, rows: int) -> bool:
        """Whether a run holding `rows` rows could occupy the pool even
        ALONE (under the live capacity) — False means the run can never
        resume and must fail typed instead of parking forever."""
        return self.blocks_for(rows) <= min(self.capacity(),
                                            self.max_blocks_per_slot)

    # -- alloc/free ----------------------------------------------------------
    def ensure(self, slot: int, rows: int) -> bool:
        """Grow slot's table to cover `rows` rows (clamped to the
        per-slot maximum).  Returns False — nothing allocated — when the
        free-list (after the fault cap) cannot supply the growth."""
        rows = min(int(rows), self.pool_len)
        with self._lock:
            table = self._tables.setdefault(slot, [])
            need = min(self.blocks_for(rows),
                       self.max_blocks_per_slot) - len(table)
            if need <= 0:
                return True
            used = sum(len(t) for t in self._tables.values())
            if used + need > self.capacity() or need > len(self._free):
                return False
            for _ in range(need):
                b = self._free.pop()
                table.append(b)
                self.served_log.append(b)
            self.version += 1
        self._note_gauges()
        return True

    def alloc(self, slot: int, rows: int) -> bool:
        """Fresh allocation for a slot that must not already hold blocks
        (admission).  Same return contract as ensure."""
        with self._lock:
            if self._tables.get(slot):
                raise InvalidArgumentError(
                    f"slot {slot} already holds "
                    f"{len(self._tables[slot])} blocks")
        return self.ensure(slot, rows)

    def free(self, slot: int) -> int:
        """Recycle every block the slot holds; returns how many.  The
        block CONTENT is scrubbed at re-serve time inside the compiled
        programs (module docstring) — free itself is pure bookkeeping."""
        with self._lock:
            table = self._tables.pop(slot, [])
            self._free.extend(table)
            n = len(table)
            if n:
                self.version += 1
        if n:
            self._note_gauges()
        return n

    # -- views ---------------------------------------------------------------
    def rows_capacity(self, slot: int) -> int:
        with self._lock:
            return len(self._tables.get(slot, ())) * self.block_size

    def block_ids(self, slot: int) -> List[int]:
        with self._lock:
            return list(self._tables.get(slot, ()))

    def table_array(self, slot: int) -> np.ndarray:
        """(max_blocks_per_slot,) int32 program input; unallocated tail
        entries hold the `num_blocks` sentinel (reads clip into masked
        rows, writes drop)."""
        out = np.full((self.max_blocks_per_slot,), self.num_blocks,
                      np.int32)
        with self._lock:
            t = self._tables.get(slot, ())
            out[:len(t)] = t
        return out

    def sentinel_table(self) -> np.ndarray:
        """An all-sentinel table: every write through it is dropped —
        what engine warmup uses so precompiling writes nothing."""
        return np.full((self.max_blocks_per_slot,), self.num_blocks,
                       np.int32)

    def stats(self) -> Dict:
        used = self.used_blocks()
        return {"num_blocks": self.num_blocks,
                "block_size": self.block_size,
                "capacity": self.capacity(),
                "used_blocks": used,
                "free_blocks": self.free_blocks(),
                "max_blocks_per_slot": self.max_blocks_per_slot}

    def _note_gauges(self):
        used_g, free_g = _obs()
        used_g.set(self.used_blocks())
        free_g.set(self.free_blocks())

    # -- device pool construction -------------------------------------------
    @staticmethod
    def leaf_shapes(model, dtype=None):
        """Per-layer (k, v) leaf shapes/dtypes from one block's worth of
        the model's own fixed-cache protocol."""
        template = model.gen_fixed_cache(1, 1, dtype)
        return [((tuple(k.shape[2:]), k.dtype), (tuple(v.shape[2:]), v.dtype))
                for k, v in template]

    def build_pools(self, model, dtype=None, put=None):
        """The device-resident block pool: for each model KV leaf of
        shape (B, T, *rest), one zero pool of shape
        (num_blocks, block_size, *rest).  `put` (optional) places each
        leaf — the mesh engine passes a heads-sharded device_put."""
        import jax.numpy as jnp
        pools = []
        for (ks, kdt), (vs, vdt) in self.leaf_shapes(model, dtype):
            k = jnp.zeros((self.num_blocks, self.block_size) + ks, kdt)
            v = jnp.zeros((self.num_blocks, self.block_size) + vs, vdt)
            if put is not None:
                k, v = put(k), put(v)
            pools.append((k, v))
        return pools

    def pool_bytes(self, pools) -> int:
        return int(sum(k.size * k.dtype.itemsize + v.size * v.dtype.itemsize
                       for k, v in pools))
