"""Paged KV-cache pool: block-granular KV allocation for the serving engine.

The PR-4 engine charges every resident request the full ``max_len`` of KV
HBM (`gen_fixed_cache(max_slots, max_len)` — one row per slot).  At
production scale that cap is the binding constraint: a 32-token request
and a 512-token request pay the same HBM, so the number of resident slots
is ``pool_bytes / max_len_bytes`` no matter what the traffic looks like.

vLLM's PagedAttention observation (Kwon et al., 2023) applied to this
engine: hold ONE device-resident block pool per layer —
``[num_blocks, block_size, heads, head_dim]`` — and give each slot an
indirection table of block ids covering exactly the rows it has actually
written.  Long and short requests then share HBM, and the resident-slot
count is bounded by *aggregate* live tokens, not ``slots * max_len``.

Split of responsibilities:

- **PagedKVPool** (here, host side): the block allocator — free-list,
  slot -> block-table indirection, alloc/append/free, capacity
  accounting (including the ``PDTPU_FAULT_KV_EXHAUST`` forced-exhaustion
  cap), and construction of the device pools from any model speaking the
  ``gen_fixed_cache`` protocol.  Pure host bookkeeping: nothing here is
  ever traced.
- **ops/paged_attention.py** (device side): the gather/scatter/scrub
  primitives the compiled serving programs use against the pool, plus
  the standalone paged-attention op (jnp gather fallback on CPU, pallas
  block-table kernel for TPU).
- **serving/engine.py**: `ServingEngine(kv="paged", block_size=...)`
  wires both into the unchanged engine contracts (compile bound,
  bit-identical streams, preempt/restore).

Scrub-on-recycle
----------------
Freed blocks return to the free-list and are re-served with a hard
no-stale-KV guarantee enforced INSIDE the compiled programs (zero extra
programs, zero idle HBM passes): a prefill overwrites every block it
claims end-to-end (prompt KV + zero padding to the block boundary), and
the decode/verify programs zero a block in full the moment a slot's
write position first enters it (``offset == 0``), before writing the new
row.  A block is only ever readable through a slot's table, tables only
cover rows the slot wrote, and the first write into a re-served block
erases all of it — so no request can observe another tenant's KV, and
the device state of a re-served block provably contains none
(tests/test_dist_serving.py::test_recycled_block_is_scrubbed).

Exhaustion is backpressure, not a crash: admission checks `free_blocks`
before claiming a slot, `ensure` returning False mid-decode triggers
preemption of the newest low-priority run (engine policy), and the typed
`KVPoolExhaustedError` is the terminal state for runs that can no longer
fit at all.  ``PDTPU_FAULT_KV_EXHAUST=N`` caps the live capacity to N
blocks to force every one of those paths on CPU.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ..core.errors import InvalidArgumentError, ResourceExhaustedError
from ..utils import faults

__all__ = ["PagedKVPool", "KVPoolExhaustedError"]


class KVPoolExhaustedError(ResourceExhaustedError):
    """The paged KV block pool cannot hold this run: every block is in
    use (or the pool is capped by PDTPU_FAULT_KV_EXHAUST) and no
    lower-priority victim can be preempted to make room.  The request is
    terminal — resubmit when the pool drains, or raise num_blocks."""
    code = "ResourceExhausted"


_obs_handles: Dict[str, tuple] = {}


def _obs(pool: str):
    """(blocks_used_gauge, blocks_free_gauge) bound to this pool's
    `pool=` label — cached handles (registry.reset() zeroes values in
    place).  Labeling keeps two allocators in one process (a target and
    a draft pool, or two fleet replicas) from overwriting each other in
    /metrics."""
    handles = _obs_handles.get(pool)
    if handles is None:
        from ..observability import metrics as _m
        used = _m.gauge("serving_kv_blocks_used",
                        "paged KV pool blocks currently allocated",
                        labelnames=("pool",))
        free = _m.gauge("serving_kv_blocks_free",
                        "paged KV pool blocks free (after any fault cap)",
                        labelnames=("pool",))
        handles = _obs_handles[pool] = (used.labels(pool=pool),
                                        free.labels(pool=pool))
    return handles


class PagedKVPool:
    """Host-side block allocator over a device-resident block pool.

    ``build_pools(model, ...)`` constructs the per-layer device pools —
    each KV leaf of ``model.gen_fixed_cache(1, block_size)`` becomes a
    ``(num_blocks, block_size, *leaf.shape[2:])`` zero pool — and the
    allocator hands out block ids: ``alloc``/``ensure`` grow a slot's
    table to cover a row count, ``free`` recycles the slot's blocks,
    ``table_array`` renders the table as the fixed-shape
    ``(max_blocks_per_slot,)`` int32 input the compiled programs take
    (unallocated entries hold the ``num_blocks`` sentinel: reads clip to
    masked rows, writes drop).

    All mutation happens on the engine loop thread; the lock only guards
    the metric snapshots other threads read."""

    def __init__(self, num_blocks: int, block_size: int, pool_len: int,
                 name: str = "target"):
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.pool_len = int(pool_len)
        self.name = str(name)
        if self.block_size < 1:
            raise InvalidArgumentError(
                f"block_size must be >= 1, got {self.block_size}")
        if self.num_blocks < 1:
            raise InvalidArgumentError(
                f"num_blocks must be >= 1, got {self.num_blocks}")
        # max blocks one slot can ever hold (its table's static width)
        self.max_blocks_per_slot = -(-self.pool_len // self.block_size)
        self._lock = threading.Lock()
        # LIFO free-list: the most recently freed block is re-served
        # first (deterministic recycling — the scrub proof relies on it)
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))
        self._tables: Dict[int, List[int]] = {}
        # block id -> number of slot tables referencing it.  Without a
        # prefix cache every block has at most one reference and the
        # accounting reduces to the PR-8 free-list; with one, shared
        # prefix blocks carry ref > 1 and `free` only recycles a block
        # when its LAST reference drops.
        self._refs: Dict[int, int] = {}
        self._live = 0          # distinct blocks with ref > 0
        # blocks owned by the prefix cache: at ref 0 they stay RESIDENT
        # (evictable, not free-listed) until the cache evicts them
        self._cached: set = set()
        # prefix-cache hooks (engine loop thread only): reclaim(n) asks
        # the cache to evict >= n evictable blocks back to the free
        # list; unref(ids) tells it these cached blocks just hit ref 0
        self._on_reclaim = None
        self._on_cached_unref = None
        # debug/test aid: the most recent block ids handed out, in order —
        # the scrub-on-recycle proof reads which blocks were RE-served
        self.served_log: "deque[int]" = deque(maxlen=512)
        # bumped on every table mutation (growth or free): the engine
        # caches its device-side (tables, active) batch inputs against it
        # so unchanged ticks re-upload nothing
        self.version = 0

    def set_cache_hooks(self, reclaim, unref):
        """Attach a prefix cache (serving/prefix_cache.py)."""
        self._on_reclaim = reclaim
        self._on_cached_unref = unref

    # -- capacity ------------------------------------------------------------
    def capacity(self) -> int:
        """Usable blocks RIGHT NOW: num_blocks, unless
        PDTPU_FAULT_KV_EXHAUST caps it lower (consulted live)."""
        cap = faults.kv_exhaust_cap()
        return self.num_blocks if cap is None else min(self.num_blocks, cap)

    def used_blocks(self) -> int:
        """Distinct blocks with at least one table reference.  Cached
        refcount-0 blocks are NOT used: they are resident but evictable,
        so they count as free for admission (block-aware gate)."""
        with self._lock:
            return self._live

    def free_blocks(self) -> int:
        return max(0, self.capacity() - self.used_blocks())

    def block_ref(self, block: int) -> int:
        """Live reference count of one block (0 = unreferenced)."""
        with self._lock:
            return self._refs.get(block, 0)

    def cached_blocks(self) -> int:
        """Blocks currently owned by the prefix cache (any refcount)."""
        with self._lock:
            return len(self._cached)

    def blocks_for(self, rows: int) -> int:
        """Blocks needed to hold `rows` KV rows."""
        return -(-max(0, int(rows)) // self.block_size)

    def can_ever_fit(self, rows: int) -> bool:
        """Whether a run holding `rows` rows could occupy the pool even
        ALONE (under the live capacity) — False means the run can never
        resume and must fail typed instead of parking forever."""
        return self.blocks_for(rows) <= min(self.capacity(),
                                            self.max_blocks_per_slot)

    # -- alloc/free ----------------------------------------------------------
    def ensure(self, slot: int, rows: int) -> bool:
        """Grow slot's table to cover `rows` rows (clamped to the
        per-slot maximum).  Returns False — nothing allocated — when the
        capacity (after the fault cap) cannot supply the growth.  When
        the free list is short but a prefix cache holds evictable
        refcount-0 blocks, the cache is asked to evict (LRU order) —
        cached-but-unreferenced blocks are reclaimable capacity."""
        rows = min(int(rows), self.pool_len)
        with self._lock:
            table = self._tables.setdefault(slot, [])
            need = min(self.blocks_for(rows),
                       self.max_blocks_per_slot) - len(table)
            if need <= 0:
                return True
            if self._live + need > self.capacity():
                return False
        if need > len(self._free):
            self._reclaim(need - len(self._free))
        with self._lock:
            if need > len(self._free):
                return False
            for _ in range(need):
                b = self._free.pop()
                table.append(b)
                self._refs[b] = 1
                self._live += 1
                self.served_log.append(b)
            self.version += 1
        self._note_gauges()
        return True

    def _reclaim(self, shortfall: int):
        """Ask the prefix cache (if attached) to evict at least
        `shortfall` evictable blocks back to the free list.  Engine loop
        thread only; called outside the lock (the cache calls back into
        `release_cached`)."""
        if self._on_reclaim is not None and shortfall > 0:
            self._on_reclaim(shortfall)

    def alloc(self, slot: int, rows: int) -> bool:
        """Fresh allocation for a slot that must not already hold blocks
        (admission).  Same return contract as ensure."""
        with self._lock:
            if self._tables.get(slot):
                raise InvalidArgumentError(
                    f"slot {slot} already holds "
                    f"{len(self._tables[slot])} blocks")
        return self.ensure(slot, rows)

    # -- prefix-cache sharing ------------------------------------------------
    def adopt(self, slot: int, block_ids: List[int]) -> bool:
        """Map already-resident (cached) blocks into an EMPTY slot's
        table, bumping their refcounts — the warm-prefix admission path.
        Returns False (nothing mapped) when reviving the refcount-0
        blocks among them would exceed the live capacity cap."""
        with self._lock:
            if self._tables.get(slot):
                raise InvalidArgumentError(
                    f"slot {slot} already holds "
                    f"{len(self._tables[slot])} blocks")
            revive = sum(1 for b in block_ids if self._refs.get(b, 0) == 0)
            if self._live + revive > self.capacity():
                return False
            table = self._tables[slot] = []
            for b in block_ids:
                r = self._refs.get(b, 0)
                if r == 0:
                    self._live += 1
                self._refs[b] = r + 1
                table.append(b)
            if table:
                self.version += 1
        if block_ids:
            self._note_gauges()
        return True

    def cow_last(self, slot: int):
        """Copy-on-write divergence: replace the LAST block of the
        slot's table (a shared cached block about to be written) with a
        fresh private block.  Returns (src, dst) block ids — the caller
        must copy the device content src -> dst BEFORE any program
        writes through the table — or None when no block is available.
        Engine loop thread only: src's content stays intact until a
        later allocation re-serves it, so the copy is race-free."""
        with self._lock:
            table = self._tables.get(slot)
            if not table:
                raise InvalidArgumentError(f"slot {slot} holds no blocks")
            src = table[-1]
            short = self.capacity() < self._live + 1
        if short:
            return None
        if not self._free:
            self._reclaim(1)
        with self._lock:
            if not self._free:
                return None
            dst = self._free.pop()
            self._refs[dst] = 1
            self._live += 1
            self.served_log.append(dst)
            unref = []
            r = self._refs.get(src, 1) - 1
            if r > 0:
                self._refs[src] = r
            else:
                self._refs.pop(src, None)
                self._live -= 1
                if src in self._cached:
                    unref.append(src)
                else:
                    self._free.append(src)
            table[-1] = dst
            self.version += 1
        if unref and self._on_cached_unref is not None:
            self._on_cached_unref(unref)
        self._note_gauges()
        return src, dst

    def register_cached(self, block: int):
        """The prefix cache takes ownership of a block: at ref 0 it will
        stay resident (evictable) instead of returning to the free
        list."""
        with self._lock:
            self._cached.add(block)

    def release_cached(self, block_ids: List[int]):
        """The prefix cache evicted these blocks: recycle any that are
        unreferenced back to the free list (LIFO, so the scrub proof
        sees them re-served first)."""
        with self._lock:
            for b in block_ids:
                self._cached.discard(b)
                if self._refs.get(b, 0) == 0:
                    self._free.append(b)
        self._note_gauges()

    def free(self, slot: int) -> int:
        """Drop the slot's reference on every block it holds; returns
        how many table entries were released.  A block whose LAST
        reference drops is recycled to the free list — unless the
        prefix cache owns it, in which case it stays device-resident
        (evictable) and the cache is notified.  Shared blocks other
        slots still reference are never double-freed.  Block CONTENT is
        scrubbed at re-serve time inside the compiled programs (module
        docstring) — free itself is pure bookkeeping."""
        with self._lock:
            table = self._tables.pop(slot, [])
            n = len(table)
            unref = []
            for b in table:
                r = self._refs.get(b, 1) - 1
                if r > 0:
                    self._refs[b] = r
                    continue
                self._refs.pop(b, None)
                self._live -= 1
                if b in self._cached:
                    unref.append(b)
                else:
                    self._free.append(b)
            if n:
                self.version += 1
        if unref and self._on_cached_unref is not None:
            self._on_cached_unref(unref)
        if n:
            self._note_gauges()
        return n

    # -- views ---------------------------------------------------------------
    def rows_capacity(self, slot: int) -> int:
        with self._lock:
            return len(self._tables.get(slot, ())) * self.block_size

    def block_ids(self, slot: int) -> List[int]:
        with self._lock:
            return list(self._tables.get(slot, ()))

    def table_array(self, slot: int) -> np.ndarray:
        """(max_blocks_per_slot,) int32 program input; unallocated tail
        entries hold the `num_blocks` sentinel (reads clip into masked
        rows, writes drop)."""
        out = np.full((self.max_blocks_per_slot,), self.num_blocks,
                      np.int32)
        with self._lock:
            t = self._tables.get(slot, ())
            out[:len(t)] = t
        return out

    def sentinel_table(self) -> np.ndarray:
        """An all-sentinel table: every write through it is dropped —
        what engine warmup uses so precompiling writes nothing."""
        return np.full((self.max_blocks_per_slot,), self.num_blocks,
                       np.int32)

    def stats(self) -> Dict:
        used = self.used_blocks()
        with self._lock:
            shared = sum(1 for r in self._refs.values() if r > 1)
            cached = len(self._cached)
        return {"pool": self.name,
                "num_blocks": self.num_blocks,
                "block_size": self.block_size,
                "capacity": self.capacity(),
                "used_blocks": used,
                "free_blocks": self.free_blocks(),
                "shared_blocks": shared,
                "cached_blocks": cached,
                "max_blocks_per_slot": self.max_blocks_per_slot}

    def _note_gauges(self):
        used_g, free_g = _obs(self.name)
        used_g.set(self.used_blocks())
        free_g.set(self.free_blocks())

    # -- device pool construction -------------------------------------------
    @staticmethod
    def leaf_shapes(model, dtype=None):
        """Per-layer (k, v) leaf shapes/dtypes from one block's worth of
        the model's own fixed-cache protocol."""
        template = model.gen_fixed_cache(1, 1, dtype)
        return [((tuple(k.shape[2:]), k.dtype), (tuple(v.shape[2:]), v.dtype))
                for k, v in template]

    def build_pools(self, model, dtype=None, put=None):
        """The device-resident block pool: for each model KV leaf of
        shape (B, T, *rest), one zero pool of shape
        (num_blocks, block_size, *rest).  `put` (optional) places each
        leaf — the mesh engine passes a heads-sharded device_put."""
        import jax.numpy as jnp
        pools = []
        for (ks, kdt), (vs, vdt) in self.leaf_shapes(model, dtype):
            k = jnp.zeros((self.num_blocks, self.block_size) + ks, kdt)
            v = jnp.zeros((self.num_blocks, self.block_size) + vs, vdt)
            if put is not None:
                k, v = put(k), put(v)
            pools.append((k, v))
        return pools

    def pool_bytes(self, pools) -> int:
        return int(sum(k.size * k.dtype.itemsize + v.size * v.dtype.itemsize
                       for k, v in pools))
