"""Prefix-aware KV reuse: a radix index over cached paged-KV blocks.

At scale most prompts share long prefixes — system prompts, few-shot
templates, multi-turn history — yet the PR-8 paged engine recomputes and
re-stores KV for every one of those tokens on every admission.  The
block-table indirection is exactly the substrate vLLM's PagedAttention
(Kwon et al., 2023) and SGLang's RadixAttention (Zheng et al., 2023) use
to turn the pool from a per-request scratchpad into a shared cache:

- **Refcounted blocks** (kv_pool.py): a block freed by one slot stays
  device-resident while any other slot references it, and — once this
  cache owns it — while it remains cache-resident at refcount 0, until
  LRU eviction reclaims it for the free list.
- **Radix index** (here): a trie over FULL prompt blocks.  Each node is
  one block's worth of token ids, keyed by (share key, parent node,
  exact token bytes) with a rolling blake2b digest chained from the
  parent for content identity.  `match` walks the longest resident
  prefix; `insert` registers a freshly prefilled prompt's full blocks.
  Only full blocks enter the index: a cached block is immutable while
  resident (decode writes land at positions >= prompt_len, past every
  full prompt block), so a chain can be mapped into any later slot.
- **Share policy**: the share key partitions the index — tenant-private
  by default, opt-in groups via `TenantConfig(kv_share_group=...)`.  A
  block cached under one key is INVISIBLE to every other key: cross-
  tenant reuse is impossible by construction, extending the PR-8
  scrub contract to cached blocks (an evicted block returns to the
  free list and is scrubbed at re-serve time inside the compiled
  programs, so recycling across tenants stays leak-free too).
- **LRU eviction over refcount-0 leaves only**: referenced blocks and
  interior nodes with resident children are never evicted, so a
  resident chain is always reachable root-first and parents outlive
  children.  ``PDTPU_FAULT_PREFIX_EVICT=N`` caps the number of
  resident refcount-0 cached blocks (consulted live) to force
  eviction/COW churn on CPU without filling a real pool.
- **Copy-on-write** (engine policy, `kv_pool.cow_last`): when a prompt
  is fully block-aligned-cached, its last token's row must be
  recomputed inside a shared block — the engine allocates a private
  copy first so shared blocks are never written.

Pure host bookkeeping on the engine loop thread; nothing here is ever
traced.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils import faults

__all__ = ["PrefixCache"]

_obs_handles = None


def _obs():
    """(hits, misses, evictions, cow_copies) counter handles — cached
    (registry.reset() zeroes values in place)."""
    global _obs_handles
    if _obs_handles is None:
        from ..observability import metrics as _m
        _obs_handles = (
            _m.counter("prefix_cache_hits_total",
                       "prompt blocks served from the prefix cache"),
            _m.counter("prefix_cache_misses_total",
                       "prompt blocks prefilled cold (no cached prefix)"),
            _m.counter("prefix_cache_evictions_total",
                       "cached blocks LRU-evicted back to the free list"),
            _m.counter("prefix_cache_cow_copies_total",
                       "copy-on-write private copies of shared blocks"))
    return _obs_handles


class _Node:
    """One full block of token ids resident in the cache."""

    __slots__ = ("id", "parent", "key", "block", "digest", "children")

    def __init__(self, node_id: int, parent: int, key, block: int,
                 digest: bytes):
        self.id = node_id
        self.parent = parent      # parent node id (0 = share-key root)
        self.key = key            # index key, kept for O(1) removal
        self.block = block        # pool block id holding this KV
        self.digest = digest      # rolling content hash along the chain
        self.children = 0


class PrefixCache:
    """Host-side radix index over a ``PagedKVPool``'s cached blocks.

    The engine drives it at three points: ``match`` at admission (and
    from the admission gate, with ``record=False``), ``insert`` after a
    successful prefill, and the pool hooks fire on release/allocation
    pressure.  All mutation happens on the engine loop thread."""

    def __init__(self, pool):
        self.pool = pool
        self.block_size = pool.block_size
        self._nodes: Dict[int, _Node] = {}
        self._index: Dict[Tuple[str, int, bytes], int] = {}
        self._by_block: Dict[int, int] = {}      # block id -> node id
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        self._next_id = 1
        # host-side tallies (cheap to read; the registry counters mirror
        # them for /metrics)
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.cow_copies = 0
        pool.set_cache_hooks(reclaim=self._reclaim, unref=self._on_unref)

    # -- lookup --------------------------------------------------------------
    def match(self, share_key: str, tokens: np.ndarray,
              record: bool = False) -> List[int]:
        """Longest resident prefix of `tokens` under `share_key`, as the
        chain of pool block ids (root-first; each covers one FULL
        block).  Touches the walked chain's LRU position.  With
        `record`, tallies block-level hits and misses (the admission
        path sets it; the admission gate re-matches without counting)."""
        bs = self.block_size
        chain: List[int] = []
        parent = 0
        nb = len(tokens) // bs
        for i in range(nb):
            key = (share_key, parent,
                   np.asarray(tokens[i * bs:(i + 1) * bs],
                              np.int32).tobytes())
            nid = self._index.get(key)
            if nid is None:
                break
            self._lru.move_to_end(nid)
            chain.append(self._nodes[nid].block)
            parent = nid
        if record:
            h, m = len(chain), nb - len(chain)
            self.hits += h
            self.misses += m
            hits_c, miss_c, _, _ = _obs()
            if h:
                hits_c.inc(h)
            if m:
                miss_c.inc(m)
        return chain

    # -- insertion -----------------------------------------------------------
    def insert(self, share_key: str, tokens: np.ndarray,
               block_ids: List[int]):
        """Register a freshly prefilled prompt's FULL blocks.
        `block_ids` is the owning slot's table prefix (one id per full
        block of `tokens`).  Existing nodes win — a duplicate block
        (two slots racing the same cold prefix) stays slot-private and
        recycles normally on free."""
        bs = self.block_size
        parent = 0
        digest = b""
        for i in range(len(tokens) // bs):
            if i >= len(block_ids):
                break
            raw = np.asarray(tokens[i * bs:(i + 1) * bs], np.int32).tobytes()
            key = (share_key, parent, raw)
            digest = hashlib.blake2b(digest + raw, digest_size=16).digest()
            nid = self._index.get(key)
            if nid is None:
                nid = self._next_id
                self._next_id += 1
                node = _Node(nid, parent, key, int(block_ids[i]), digest)
                self._nodes[nid] = node
                self._index[key] = nid
                self._by_block[node.block] = nid
                if parent:
                    self._nodes[parent].children += 1
                self.pool.register_cached(node.block)
            if nid in self._lru:
                self._lru.move_to_end(nid)
            else:
                self._lru[nid] = None
            parent = nid

    def note_cow(self):
        self.cow_copies += 1
        _obs()[3].inc()

    # -- eviction ------------------------------------------------------------
    def _evictable(self, node: _Node) -> bool:
        return node.children == 0 and self.pool.block_ref(node.block) == 0

    def evict(self, n: int) -> List[int]:
        """Evict up to `n` blocks, oldest evictable leaves first
        (evicting a leaf can make its parent evictable, so chains drain
        child-before-parent).  Returns the freed block ids after handing
        them back to the pool's free list."""
        freed: List[int] = []
        while len(freed) < n:
            victim = None
            for nid in self._lru:                 # oldest first
                if self._evictable(self._nodes[nid]):
                    victim = nid
                    break
            if victim is None:
                break
            freed.append(self._remove(victim))
        if freed:
            self.evictions += len(freed)
            _obs()[2].inc(len(freed))
            self.pool.release_cached(freed)
        return freed

    def _remove(self, nid: int) -> int:
        node = self._nodes.pop(nid)
        del self._index[node.key]
        self._lru.pop(nid, None)
        self._by_block.pop(node.block, None)
        if node.parent:
            parent = self._nodes.get(node.parent)
            if parent is not None:
                parent.children -= 1
        return node.block

    # -- pool hooks ----------------------------------------------------------
    def _reclaim(self, shortfall: int) -> int:
        """Pool allocation pressure: free at least `shortfall` blocks if
        evictable ones exist."""
        return len(self.evict(shortfall))

    def _on_unref(self, block_ids: List[int]):
        """Cached blocks just dropped to refcount 0 (still resident).
        Enforce the live PDTPU_FAULT_PREFIX_EVICT cap."""
        self.enforce_cap()

    def enforce_cap(self):
        cap = faults.prefix_evict_cap()
        if cap is None:
            return
        while True:
            resident0 = sum(1 for node in self._nodes.values()
                            if self.pool.block_ref(node.block) == 0)
            if resident0 <= cap or not self.evict(resident0 - cap):
                break

    # -- views ---------------------------------------------------------------
    def resident_nodes(self) -> int:
        return len(self._nodes)

    def block_owner(self, block: int) -> Optional[int]:
        """Node id owning a block, or None (tests/debug)."""
        return self._by_block.get(block)

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def stats(self) -> Dict:
        return {"nodes": len(self._nodes),
                "resident_blocks": self.pool.cached_blocks(),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "cow_copies": self.cow_copies,
                "hit_rate": self.hit_rate()}
