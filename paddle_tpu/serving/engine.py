"""ServingEngine: continuous batching over a slot-based KV-cache pool.

Exactly two compiled program families serve every request mix:

- **bucketed prefill** (one trace per prompt-length bucket): the prompt,
  right-padded to the bucket, runs through `model.forward_fixed` against a
  bucket-sized scratch cache; the resulting KV is written into the assigned
  slot of the engine-lifetime pool via `dynamic_update_slice`, overwriting
  the slot's FULL [0, max_len) range (stale KV from the slot's previous
  occupant can never leak).  The first generated token is sampled inside
  the same program from the prompt's last-position logits.
- **one decode step** (a single trace, ever): `model.forward_fixed` is
  vmapped over the slot axis so every slot advances one token per call with
  its OWN write position, and every sampling knob — temperature, top-k,
  top-p, greedy flag, RNG key — is a per-slot dynamic input
  (`generation.process_logits_dynamic`), so heterogeneous requests share
  the trace.  Requests join and leave the resident batch between
  iterations; nobody owns a compilation.

Compilation count is therefore bounded by len(prefill_buckets) + 1 per
engine, regardless of how many (prompt_len, max_new, sampling-param)
combinations the traffic mixes — asserted by `compile_counts()`.

**Speculative decoding** (``draft_model=``): the decode program is
replaced by ONE verify program per engine that (a) runs ``spec_tokens``
sequential draft-model steps proposing K tokens per slot (the draft owns
its own slot pool, written with the same protocol), (b) scores
``[last_committed, d_1..d_K]`` — K+1 positions — in ONE batched target
forward, and (c) commits the longest accepted prefix plus one corrected
token entirely in-program (`generation.speculative`: greedy equality
accept, or distribution-preserving rejection sampling for sampling
slots), so a tick advances 1..K+1 tokens per slot with a single target
dispatch.  Per-bucket prefill additionally prefills the draft pool inside
the same program.  The program bound is UNCHANGED: len(prefill_buckets)
prefill programs (each covering target + draft) + 1 verify program —
spec on/off per request, greedy/sampling, and every sampling-param combo
share the single verify trace via dynamic per-slot inputs.  Greedy
speculative streams stay bit-identical to solo `generate` (acceptance is
argmax equality against the same logits rows the solo loop argmaxes);
spec-off slots inside a speculative engine reproduce the plain decode
step token-for-token (same key folds, same distributions).

Greedy requests are bit-identical to a solo
`generation.generate(decode_strategy='greedy_search')` run of the same
prompt: prefill logits at the prompt's last position are unaffected by
right-padding (causal mask), and decode attends exactly the
[0, pos] prefix of the slot, the same masked-buffer attention the solo
loop runs.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.errors import FatalError, InvalidArgumentError, UnavailableError
from ..generation import process_logits_dynamic
from ..utils import faults
from ..utils.monitor import stat_add
from .request import Request, Response, RequestCancelled
from .scheduler import RequestScheduler, DeadlineExceededError

__all__ = ["ServingEngine", "NonFiniteLogitsError", "PreemptedRun"]


class NonFiniteLogitsError(FatalError):
    """Decode produced NaN/Inf logits for this request's slot; the request
    is errored individually and its slot recycled."""
    code = "Fatal"


def _default_buckets(max_len: int):
    """Powers of two from 16 up to max_len (prompt lengths round up)."""
    buckets, b = [], 16
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_len)
    return tuple(buckets)


class _SlotRun:
    """Host-side per-slot decode state."""
    __slots__ = ("req", "resp", "pos", "produced", "last_token",
                 "last_token_at", "key")

    def __init__(self, req: Request, resp: Response, pos: int,
                 first_token: int, key: np.ndarray):
        self.req = req
        self.resp = resp
        self.pos = pos              # kv length so far (write offset)
        self.produced = 1           # first token came from prefill
        self.last_token = first_token
        self.last_token_at = time.monotonic()
        self.key = key


class PreemptedRun:
    """Host snapshot of a preempted in-flight decode — everything needed
    to resume the stream, bit-identical, in ANY free slot later.

    The same snapshot/publish split `distributed.checkpoint` uses: the
    live KV rows are copied device->host NOW (so the pool stays free to be
    donated to the next compiled call), and "publish" is the later
    `restore_run` writing them back.  `kv_rows` holds per-layer
    ``(k_rows, v_rows)`` numpy arrays of shape ``(pos, ...)``; sampling
    state (RNG key, write position, produced count, last token) rides
    along so decode step `pos` folds the same key it would have folded
    uninterrupted."""

    __slots__ = ("req", "resp", "pos", "produced", "last_token", "key",
                 "kv_rows", "draft_kv_rows", "preempted_at")

    def __init__(self, run: _SlotRun, kv_rows, draft_kv_rows=None):
        self.req = run.req
        self.resp = run.resp
        self.pos = run.pos
        self.produced = run.produced
        self.last_token = run.last_token
        self.key = run.key
        self.kv_rows = kv_rows
        # speculative engines snapshot the draft pool rows too: resuming
        # with a coherent draft context preserves the accept rate (output
        # correctness never depends on draft KV — rejected proposals are
        # free — but garbage draft context would decay a resumed stream
        # to target-only throughput)
        self.draft_kv_rows = draft_kv_rows
        self.preempted_at = time.monotonic()


class ServingEngine:
    """Continuous-batching engine over a model implementing the
    `gen_fixed_cache` / `forward_fixed` protocol (see the serving package
    docstring and models/gpt.py:190,201)."""

    def __init__(self, model, max_slots: int = 8, max_len: int = 256,
                 prefill_buckets=None, max_queue_depth: int = 64,
                 pad_token_id: int = 0, dtype=None, profile: bool = False,
                 decode_chunk: int = 4, draft_model=None,
                 spec_tokens: int = 4):
        from ..generation import _model_fns
        self.model = model
        self.max_slots = int(max_slots)
        self.max_len = int(max_len)
        self.pad_token_id = int(pad_token_id)
        self.buckets = tuple(sorted(set(
            int(b) for b in (prefill_buckets or _default_buckets(max_len)))))
        if self.buckets[-1] > self.max_len:
            raise InvalidArgumentError(
                f"prefill bucket {self.buckets[-1]} exceeds max_len "
                f"{self.max_len}")
        self._dtype = dtype
        self._profile = bool(profile)
        # tokens decoded per compiled decode call (an internal lax.scan):
        # amortizes the per-call host+dispatch cost across chunk tokens per
        # slot.  Tokens stream in bursts of `chunk`; admission, deadline
        # and cancel sweeps run between calls.  A slot finishing mid-chunk
        # wastes its tail iterations (its post-finish tokens are discarded
        # on the host and its KV garbage is overwritten by the slot's next
        # prefill) — with budgets >> chunk the waste is marginal and the
        # dispatch amortization dominates on every backend.
        self.decode_chunk = max(1, int(decode_chunk))
        self.scheduler = RequestScheduler(self.max_slots, max_queue_depth)
        self._state, self._apply = _model_fns(model)
        self.draft_model = draft_model
        self.spec_tokens = int(spec_tokens)
        if draft_model is not None:
            if self.spec_tokens < 1:
                raise InvalidArgumentError(
                    f"spec_tokens must be >= 1, got {self.spec_tokens}")
            if self.spec_tokens >= self.max_len:
                raise InvalidArgumentError(
                    f"spec_tokens {self.spec_tokens} must be < max_len "
                    f"{self.max_len}")
        # pool length: speculative engines get spec_tokens rows of
        # HEADROOM beyond max_len — a verify tick writes K+1 rows at
        # pos..pos+K even when only one commits, and pos legitimately
        # reaches plen+max_new-2 <= max_len-2; without the headroom the
        # final ticks of a full-budget request would have
        # dynamic_update_slice CLAMP the write start and silently
        # overwrite committed KV (breaking greedy parity).  Request
        # validation stays at plen+max_new <= max_len.
        self._pool_len = self.max_len + (
            self.spec_tokens if draft_model is not None else 0)
        # THE pool: one gen_fixed_cache(max_slots, pool_len) allocation,
        # reused for the engine's lifetime
        self._pools = model.gen_fixed_cache(self.max_slots, self._pool_len,
                                            dtype)
        self._slots: Dict[int, _SlotRun] = {}
        # device-resident decode batch state; rebuilt from host _SlotRun
        # state only when membership changes (admission / slot release)
        self._dev_tokens = None
        self._dev_pos = None
        self._dev_params = None
        self._batch_dirty = True
        self._rid = 0
        self._submit_lock = threading.Lock()
        # nan_logits fault: presence decided NOW (trace time) — the clean
        # decode program carries zero fault branches
        self._poison_target = faults.nan_logits_request()
        self._key_width = len(np.asarray(jax.random.PRNGKey(0)))
        # the pool is DONATED to every prefill/decode call and replaced by
        # the returned buffers: XLA updates the slots in place instead of
        # copying max_slots * max_len of KV per call (measured 166x on a
        # CPU pool-passthrough update; the same aliasing TPU donation does)
        self._donate = (1,)
        self._compiles = {"decode": 0, "prefill": {b: 0 for b in self.buckets}}
        self._decode_calls = 0  # slow_decode fault stride counter
        # speculative decoding: a draft model swaps the decode program for
        # the single verify program and adds a draft slot pool + draft
        # prefill folded into the per-bucket prefill programs — the
        # compiled-program bound stays len(buckets) + 1
        if draft_model is not None:
            self._dstate, self._dapply = _model_fns(draft_model)
            self._draft_pools = draft_model.gen_fixed_cache(
                self.max_slots, self._pool_len, dtype)
            # draft_diverge fault: presence decided NOW (trace time); the
            # per-tick flag is a dynamic input
            self._diverge_every = faults.draft_diverge_every()
            self._spec_ticks = 0
            from ..observability import metrics as _obs_m2
            self._h_accept = _obs_m2.histogram(
                "serving_spec_accept_rate",
                "accepted draft proposals / spec_tokens, per slot per tick")
            self._spec_proposed = 0
            self._spec_accepted = 0
            self._decode_fn = self._build_verify()
        else:
            self._decode_fn = self._build_decode()
        self._prefill_fns = {b: self._build_prefill(b)
                             for b in self.buckets}
        # observability: latency histograms shared with the unified
        # report / Prometheus endpoint (handles cached; registry.reset()
        # zeroes values in place)
        from ..observability import metrics as _obs_m
        self._h_ttft = _obs_m.histogram(
            "serving_ttft_seconds", "submit -> first streamed token")
        self._h_itl = _obs_m.histogram(
            "serving_inter_token_seconds",
            "gap between consecutive tokens of one request")
        # metrics accumulators
        self._m_lock = threading.Lock()
        self._ttfts: List[float] = []
        self._itl_sum = 0.0
        self._itl_n = 0
        self._tokens_out = 0
        self._completed = 0
        self._errored = 0
        self._started_at = time.monotonic()
        # background loop
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._work = threading.Event()
        self._closed = False
        self._dead: Optional[BaseException] = None

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------
    def _build_prefill(self, bucket: int):
        """One per-bucket prefill program.  On a speculative engine the
        SAME program additionally prefills the draft pool (one draft
        forward over the same padded ids, slot row written with the same
        full-range overwrite) — the first token still comes from the
        target's last-prompt-position logits, so greedy parity is
        identical with and without a draft."""
        apply_fixed = self._apply
        model, draft = self.model, self.draft_model
        pool_len, dtype = self._pool_len, self._dtype
        dapply = self._dapply if draft is not None else None

        def write_slot(pools, kv, slot):
            new_pools = []
            for (kp, vp), (kc, vc) in zip(pools, kv):
                # full-range overwrite: bucket KV + zeros to pool_len, so
                # a recycled slot keeps no stale KV from its previous
                # tenant
                krow = jnp.zeros((1, pool_len) + kp.shape[2:], kp.dtype)
                vrow = jnp.zeros((1, pool_len) + vp.shape[2:], vp.dtype)
                krow = jax.lax.dynamic_update_slice(
                    krow, kc.astype(kp.dtype), (0, 0, 0, 0))
                vrow = jax.lax.dynamic_update_slice(
                    vrow, vc.astype(vp.dtype), (0, 0, 0, 0))
                new_pools.append((
                    jax.lax.dynamic_update_slice(kp, krow, (slot, 0, 0, 0)),
                    jax.lax.dynamic_update_slice(vp, vrow, (slot, 0, 0, 0))))
            return new_pools

        def first_token(logits, prompt_len, key, temp, top_k, top_p,
                        greedy):
            # right-padding never touches the prompt's last-position
            # logits (causal mask), so this matches the solo generate
            # prefill
            last = jax.lax.dynamic_index_in_dim(
                logits[0].astype(jnp.float32), prompt_len - 1, axis=0,
                keepdims=False)
            finite = jnp.isfinite(last).all()
            proc = process_logits_dynamic(
                last[None], temp[None], top_k[None], top_p[None],
                greedy[None])[0]
            # the first token's key is folded at (prompt_len - 1); decode
            # step j folds at prompt_len + j — counters never collide
            sampled = jax.random.categorical(
                jax.random.fold_in(key, prompt_len - 1), proc)
            tok = jnp.where(greedy, jnp.argmax(proc, axis=-1),
                            sampled).astype(jnp.int32)
            logp = jax.nn.log_softmax(proc)[tok]
            return tok, logp, finite

        def count_trace():
            self._compiles["prefill"][bucket] += 1  # trace-count (host)
            stat_add("STAT_serving_compiles")

        if draft is None:
            def prefill(state, pools, ids, slot, prompt_len, key, temp,
                        top_k, top_p, greedy):
                count_trace()
                scratch = model.gen_fixed_cache(1, bucket, dtype)
                logits, kv = apply_fixed(state, ids, scratch, 0)
                new_pools = write_slot(pools, kv, slot)
                tok, logp, finite = first_token(
                    logits, prompt_len, key, temp, top_k, top_p, greedy)
                return tok, logp, finite, new_pools

            name, donate = f"serving_prefill_b{bucket}", self._donate
        else:
            def prefill(state, dstate, pools, dpools, ids, slot,
                        prompt_len, key, temp, top_k, top_p, greedy):
                count_trace()
                scratch = model.gen_fixed_cache(1, bucket, dtype)
                logits, kv = apply_fixed(state, ids, scratch, 0)
                new_pools = write_slot(pools, kv, slot)
                dscratch = draft.gen_fixed_cache(1, bucket, dtype)
                _, dkv = dapply(dstate, ids, dscratch, 0)
                new_dpools = write_slot(dpools, dkv, slot)
                tok, logp, finite = first_token(
                    logits, prompt_len, key, temp, top_k, top_p, greedy)
                return tok, logp, finite, new_pools, new_dpools

            name, donate = f"serving_prefill_spec_b{bucket}", (2, 3)

        from ..observability import track
        return track(name, jax.jit(prefill, donate_argnums=donate))

    def _build_decode(self):
        apply_fixed = self._apply
        poison_armed = self._poison_target is not None

        chunk = self.decode_chunk

        def decode(state, pools, tokens, pos, keys, temp, top_k, top_p,
                   greedy, poison):
            self._compiles["decode"] += 1  # trace-count (host side effect)
            stat_add("STAT_serving_compiles")

            def one(carry, _):
                tokens, pos, pools = carry

                def row(tok, caches, p):
                    c = [(k[None], v[None]) for (k, v) in caches]
                    logits, new = apply_fixed(state, tok[None, None], c, p)
                    return (logits[0, -1].astype(jnp.float32),
                            [(k[0], v[0]) for (k, v) in new])

                last, pools = jax.vmap(row)(tokens, pools, pos)
                if poison_armed:
                    last = faults.poison_logits(last, poison)
                finite = jnp.isfinite(last).all(axis=-1)

                # all-greedy fast path: the full dynamic sampling pipeline
                # (two (S, V) sorts + threefry draw) costs real time per
                # iteration; a pure-greedy batch — the common serving mix —
                # skips it at runtime via lax.cond, INSIDE the single
                # decode trace (no extra program, identical tokens: with
                # greedy all-True process_logits_dynamic returns the raw
                # logits, so both branches argmax the same array)
                def mixed(last):
                    proc = process_logits_dynamic(last, temp, top_k, top_p,
                                                  greedy)
                    folded = jax.vmap(jax.random.fold_in)(keys, pos)
                    sampled = jax.vmap(jax.random.categorical)(folded, proc)
                    tok = jnp.where(greedy, jnp.argmax(proc, axis=-1),
                                    sampled).astype(jnp.int32)
                    logp = jnp.take_along_axis(
                        jax.nn.log_softmax(proc, axis=-1), tok[:, None],
                        axis=-1)[:, 0]
                    return tok, logp

                def all_greedy(last):
                    tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
                    logp = jnp.take_along_axis(
                        jax.nn.log_softmax(last, axis=-1), tok[:, None],
                        axis=-1)[:, 0]
                    return tok, logp

                tok, logp = jax.lax.cond(jnp.all(greedy), all_greedy,
                                         mixed, last)
                return (tok, pos + 1, pools), (tok, logp, finite)

            # chunked decode: `chunk` iterations per compiled call, the
            # per-call host+dispatch cost amortized across chunk * slots
            # tokens.  The final (tokens, pos) carry is exactly the next
            # call's input while batch membership is unchanged: the engine
            # feeds the device arrays straight back, so a steady-state
            # decode call uploads nothing.
            (tokens, pos, pools), (toks, logps, finites) = jax.lax.scan(
                one, (tokens, pos, pools), None, length=chunk)
            return toks, logps, finites, tokens, pos, pools

        from ..observability import track
        return track("serving_decode",
                     jax.jit(decode, donate_argnums=self._donate))

    # ------------------------------------------------------------------
    # speculative verify program (draft_model engines)
    # ------------------------------------------------------------------
    def _build_verify(self):
        """THE speculative tick: K sequential draft proposals, one batched
        target forward over [last_committed, d_1..d_K] (K+1 positions),
        in-program accept/reject + commit (generation.speculative).  One
        trace, ever: sampling params, spec on/off, poison and diverge are
        all dynamic per-slot/per-tick inputs."""
        from ..generation.speculative import (commit_speculative_greedy,
                                              commit_speculative_sampled,
                                              draft_proposal_key)
        apply_fixed, dapply = self._apply, self._dapply
        poison_armed = self._poison_target is not None
        diverge_armed = self._diverge_every is not None
        k_spec = self.spec_tokens
        pad = self.pad_token_id

        def verify(state, dstate, pools, dpools, tokens, pos, keys, temp,
                   top_k, top_p, greedy, spec_on, poison, diverge):
            self._compiles["decode"] += 1  # trace-count (host side effect)
            stat_add("STAT_serving_compiles")

            def drow(tok, caches, p):
                c = [(kb[None], vb[None]) for (kb, vb) in caches]
                logits, new = dapply(dstate, tok[None, None], c, p)
                return (logits[0, -1].astype(jnp.float32),
                        [(kb[0], vb[0]) for (kb, vb) in new])

            def dstep(carry, i):
                cur, dp = carry
                dlast, dp = jax.vmap(drow)(cur, dp, pos + i)
                if diverge_armed:
                    dlast = faults.poison_draft_logits(dlast, diverge)
                dfin = jnp.isfinite(dlast).all(axis=-1)

                # all-greedy fast path, same rationale as the plain decode
                # step: a pure-greedy batch skips the per-proposal sort
                # pipeline + threefry inside the one shared trace
                def mixed(dlast):
                    proc = process_logits_dynamic(dlast, temp, top_k,
                                                  top_p, greedy)
                    kd = jax.vmap(
                        lambda kk, pp: draft_proposal_key(kk, pp, i))(
                            keys, pos)
                    sampled = jax.vmap(jax.random.categorical)(kd, proc)
                    prop = jnp.where(greedy, jnp.argmax(proc, axis=-1),
                                     sampled).astype(jnp.int32)
                    return prop, jax.nn.softmax(proc, axis=-1)

                def all_greedy(dlast):
                    return (jnp.argmax(dlast, axis=-1).astype(jnp.int32),
                            jax.nn.softmax(dlast, axis=-1))

                prop, q = jax.lax.cond(jnp.all(greedy), all_greedy, mixed,
                                       dlast)
                return (prop, dp), (prop, q, dfin)

            # K+1 draft steps, not K: step K feeds the LAST proposal d_K
            # at pos+K so a fully-accepted tick leaves the draft pool
            # dense (d_K commits when everything accepts; without this
            # row every all-accept tick would punch a permanent zero-KV
            # hole the draft attends over forever, decaying the accept
            # rate cumulatively — worst exactly when the draft is good).
            # Step K's proposal/q outputs are discarded; on a rejection
            # its KV row is beyond the committed prefix and the next
            # tick overwrites it before any query can attend it.
            (_, dpools), (props, qs, dfins) = jax.lax.scan(
                dstep, (tokens, dpools), jnp.arange(k_spec + 1))
            props = props[:k_spec].T             # (S, K)
            qs = jnp.swapaxes(qs[:k_spec], 0, 1)  # (S, K, V)
            dfin = dfins.all(axis=0)             # (S,)

            # target scores all K proposals + the bonus position in ONE
            # forward of K+1 tokens per slot
            ids = jnp.concatenate([tokens[:, None], props], axis=1)

            def trow(row_ids, caches, p):
                c = [(kb[None], vb[None]) for (kb, vb) in caches]
                logits, new = apply_fixed(state, row_ids[None], c, p)
                return (logits[0].astype(jnp.float32),
                        [(kb[0], vb[0]) for (kb, vb) in new])

            tlog, pools = jax.vmap(trow)(ids, pools, pos)  # (S, K+1, V)
            if poison_armed:
                factor = jnp.where(poison, jnp.float32(float("nan")),
                                   jnp.float32(1.0))
                tlog = tlog * factor[:, None, None]
            # draft non-finiteness only matters for slots actually
            # speculating — a spec-off slot must never die for garbage in
            # a pool it does not consume
            finite = (jnp.isfinite(tlog).all(axis=(1, 2))
                      & (dfin | ~spec_on))

            def proc_all(t):
                flat = t.reshape(-1, t.shape[-1])

                def rep(a):
                    return jnp.repeat(a, k_spec + 1, axis=0)
                return process_logits_dynamic(
                    flat, rep(temp), rep(top_k), rep(top_p),
                    rep(greedy)).reshape(t.shape)

            plog = jax.lax.cond(jnp.all(greedy), lambda t: t, proc_all,
                                tlog)
            ops = (props, qs, plog, keys, pos, greedy, spec_on)
            out, count, accepted, last, logps = jax.lax.cond(
                jnp.all(greedy),
                lambda o: commit_speculative_greedy(*o, pad),
                lambda o: commit_speculative_sampled(*o, pad), ops)
            return (out, logps, finite, count, accepted, last, pos + count,
                    pools, dpools)

        from ..observability import track
        return track("serving_verify",
                     jax.jit(verify, donate_argnums=(2, 3)))

    # ------------------------------------------------------------------
    # submission
    # ------------------------------------------------------------------
    def make_request(self, prompt, max_new_tokens: int,
                     decode_strategy: str = "greedy_search", temperature=1.0,
                     top_k=0, top_p=1.0, eos_token_id: Optional[int] = None,
                     seed: Optional[int] = None,
                     deadline: Optional[float] = None, priority: int = 0,
                     tenant: Optional[str] = None,
                     spec: Optional[bool] = None):
        """Validate + build one (Request, Response) pair WITHOUT enqueuing
        it — the gateway's admission layer owns its own lanes and hands
        requests to `try_admit` directly.  Raises InvalidArgumentError for
        a prompt/budget the engine can never serve."""
        if self._closed:
            raise UnavailableError("serving engine is closed")
        if self._dead is not None:
            raise UnavailableError(
                f"serving engine loop died: {self._dead!r}")
        if decode_strategy not in ("greedy_search", "sampling"):
            raise InvalidArgumentError(
                f"serving supports 'greedy_search' or 'sampling', got "
                f"{decode_strategy!r} (beam search holds k hypotheses per "
                "slot — use generation.generate)")
        # spec=None -> the engine default: speculate whenever a draft
        # model is configured.  Explicit spec=True on a draftless engine
        # is a caller error, not a silent downgrade.
        if spec is None:
            spec = self.draft_model is not None
        elif spec and self.draft_model is None:
            raise InvalidArgumentError(
                "spec=True requires the engine to be built with a "
                "draft_model (speculative decoding)")
        with self._submit_lock:
            rid = self._rid
            self._rid += 1
        req = Request(rid, prompt, max_new_tokens,
                      greedy=decode_strategy == "greedy_search",
                      temperature=temperature, top_k=top_k, top_p=top_p,
                      eos_token_id=eos_token_id,
                      seed=seed if seed is not None else rid,
                      deadline=deadline, priority=priority, tenant=tenant,
                      spec=bool(spec))
        plen = req.prompt.shape[0]
        if plen > self.buckets[-1]:
            stat_add("STAT_serving_rejects")
            raise InvalidArgumentError(
                f"prompt length {plen} exceeds the largest prefill bucket "
                f"{self.buckets[-1]} (engine max_len={self.max_len})")
        if plen + req.max_new_tokens > self.max_len:
            stat_add("STAT_serving_rejects")
            raise InvalidArgumentError(
                f"prompt ({plen}) + max_new_tokens ({req.max_new_tokens}) "
                f"exceeds the engine's max_len {self.max_len}")
        if self._poison_target is not None and rid == self._poison_target:
            req.poison = True
        resp = Response(req)
        stat_add("STAT_serving_requests")
        return req, resp

    def submit(self, prompt, max_new_tokens: int,
               decode_strategy: str = "greedy_search", temperature=1.0,
               top_k=0, top_p=1.0, eos_token_id: Optional[int] = None,
               seed: Optional[int] = None, deadline: Optional[float] = None,
               block: bool = False, timeout: Optional[float] = None,
               spec: Optional[bool] = None) -> Response:
        """Enqueue one request; returns its streaming Response.

        Raises InvalidArgumentError for a prompt/budget the engine can
        never serve (prompt longer than the largest prefill bucket, or
        prompt + max_new_tokens past max_len), QueueFullError at
        max_queue_depth (backpressure).
        """
        req, resp = self.make_request(
            prompt, max_new_tokens, decode_strategy=decode_strategy,
            temperature=temperature, top_k=top_k, top_p=top_p,
            eos_token_id=eos_token_id, seed=seed, deadline=deadline,
            spec=spec)
        self.scheduler.submit(req, resp, block=block, timeout=timeout)
        self._work.set()
        return resp

    # ------------------------------------------------------------------
    # the engine loop
    # ------------------------------------------------------------------
    def step(self) -> bool:
        """One engine iteration: sweep deadlines/cancels, admit waiting
        requests into free slots (one bucketed prefill each), then advance
        every occupied slot one token with the single decode program.
        Returns whether any work was done."""
        did = False
        self._sweep()
        self.scheduler.sweep_pending()
        while True:
            adm = self.scheduler.next_admission()
            if adm is None:
                break
            self._admit(*adm)
            did = True
        if self._slots:
            self._decode_step()
            did = True
        return did

    def _sweep(self):
        for slot in list(self._slots):
            run = self._slots[slot]
            if run.resp.cancelled:
                stat_add("STAT_serving_cancelled")
                run.resp._fail(RequestCancelled(
                    f"request {run.req.id} cancelled mid-decode"))
                self._release(slot)
            elif run.req.deadline is not None and run.req.deadline.expired():
                stat_add("STAT_serving_deadline_expired")
                run.resp._fail(DeadlineExceededError(
                    f"request {run.req.id} deadline "
                    f"({run.req.deadline.seconds}s) expired mid-decode"))
                self._release(slot)

    def _release(self, slot: int):
        self._slots.pop(slot, None)
        self.scheduler.release(slot)
        self._batch_dirty = True

    def _bucket_for(self, plen: int) -> int:
        for b in self.buckets:
            if b >= plen:
                return b
        raise InvalidArgumentError(f"no bucket fits prompt length {plen}")

    def _request_key(self, req: Request) -> np.ndarray:
        # any well-mixed bits work as a raw PRNG key; host-only derivation
        # keeps submit()/admission free of device round-trips
        rs = np.random.RandomState(np.uint32(req.seed))
        return rs.randint(0, 2 ** 32, size=self._key_width, dtype=np.uint64
                          ).astype(np.uint32)

    def _admit(self, req: Request, resp: Response, slot: int):
        span = self._span("serving_prefill")
        try:
            plen = req.prompt.shape[0]
            bucket = self._bucket_for(plen)
            ids = np.full((1, bucket), self.pad_token_id, np.int32)
            ids[0, :plen] = req.prompt
            key = self._request_key(req)
            if self.draft_model is not None:
                (tok, logp, finite, self._pools,
                 self._draft_pools) = self._prefill_fns[bucket](
                    self._state, self._dstate, self._pools,
                    self._draft_pools, jnp.asarray(ids), jnp.int32(slot),
                    jnp.int32(plen), jnp.asarray(key),
                    jnp.float32(req.temperature), jnp.int32(req.top_k),
                    jnp.float32(req.top_p), jnp.asarray(req.greedy))
            else:
                tok, logp, finite, self._pools = self._prefill_fns[bucket](
                    self._state, self._pools, jnp.asarray(ids),
                    jnp.int32(slot), jnp.int32(plen), jnp.asarray(key),
                    jnp.float32(req.temperature), jnp.int32(req.top_k),
                    jnp.float32(req.top_p), jnp.asarray(req.greedy))
            stat_add("STAT_serving_prefills")
            if not bool(finite):
                self._fail_slot(slot, resp, "prefill")
                return
            tok = int(tok)
            run = _SlotRun(req, resp, pos=plen, first_token=tok, key=key)
            self._slots[slot] = run
            self._batch_dirty = True
            self._emit(run, tok, float(logp))
            stat_add("STAT_serving_tokens")
            self._maybe_finish(slot, run, tok)
        finally:
            if span is not None:
                span.__exit__(None, None, None)

    # ------------------------------------------------------------------
    # gateway admission: direct placement, preemption, restore
    # ------------------------------------------------------------------
    def try_admit(self, req: Request, resp: Response) -> bool:
        """Place the request into a free slot NOW (one bucketed prefill),
        bypassing the FIFO queue — the gateway's admission path, which
        keeps its own priority lanes and only hands a request over once a
        slot is actually available.  Returns False when every slot is
        occupied.  Must be called from the thread driving step() (the
        engine loop is single-threaded by design)."""
        slot = self.scheduler.acquire(req, resp)
        if slot is None:
            return False
        self._admit(req, resp, slot)
        return True

    def preempt_slot(self, slot: int) -> PreemptedRun:
        """Evict the run occupying `slot`, snapshotting its live KV rows +
        sampling state to host, and free the slot.  The response stream
        stays OPEN (paused); `restore_run` later continues it bit-identical
        to an uninterrupted run.

        Zero new compiled programs: the snapshot is a plain
        `jax.device_get` of the pool (host copy, same donation-safe move
        the async checkpointer's snapshot phase makes) and the row slices
        are numpy.  Known cost: the transfer is O(pool), not O(victim
        rows) — free on CPU (aliased memory), two full-pool copies per
        preempt/restore pair on an accelerator (four on a speculative
        engine, whose draft pool rides along); a device-side row
        gather/scatter would shrink it at the price of extra compiled
        programs — and slicing `[slot, :pos]` before the device_get
        would compile one tiny gather per distinct pos, which is worse.
        Must be called between engine steps from the driving thread."""
        run = self._slots.get(slot)
        if run is None:
            raise InvalidArgumentError(f"slot {slot} holds no active run")
        host = jax.device_get(self._pools)
        kv_rows = [(np.array(k[slot, :run.pos]), np.array(v[slot, :run.pos]))
                   for k, v in host]
        draft_rows = None
        if self.draft_model is not None:
            dhost = jax.device_get(self._draft_pools)
            draft_rows = [(np.array(k[slot, :run.pos]),
                           np.array(v[slot, :run.pos])) for k, v in dhost]
        paused = PreemptedRun(run, kv_rows, draft_rows)
        run.req.preempts += 1
        self._slots.pop(slot, None)
        self.scheduler.release(slot)
        self._batch_dirty = True
        stat_add("STAT_serving_preemptions")
        return paused

    def restore_run(self, paused: PreemptedRun) -> bool:
        """Resume a preempted run into any free slot: the saved KV rows are
        written back into the pool (host-side copy + upload — no compiled
        program) and decode continues from the saved position with the
        saved RNG key, so the remaining stream is bit-identical to a run
        that was never preempted.  Returns False when no slot is free."""
        slot = self.scheduler.acquire(paused.req, paused.resp)
        if slot is None:
            return False
        def write_rows(pools, rows):
            new_pools = []
            for (hk, hv), (rk, rv) in zip(jax.device_get(pools), rows):
                # device_get may alias backend memory on CPU: copy before
                # the in-place row write, then re-upload (rows beyond
                # `pos` may hold garbage from the slot's idle decode
                # passes — the model protocol guarantees positions > pos
                # never influence output, and decode overwrites them as
                # it advances)
                hk = np.array(hk)
                hv = np.array(hv)
                hk[slot, :paused.pos] = rk
                hv[slot, :paused.pos] = rv
                new_pools.append((jnp.asarray(hk), jnp.asarray(hv)))
            return new_pools

        self._pools = write_rows(self._pools, paused.kv_rows)
        if self.draft_model is not None and paused.draft_kv_rows is not None:
            self._draft_pools = write_rows(self._draft_pools,
                                           paused.draft_kv_rows)
        run = _SlotRun(paused.req, paused.resp, pos=paused.pos,
                       first_token=paused.last_token, key=paused.key)
        run.produced = paused.produced
        paused.req.resumes += 1
        paused.req.paused_seconds += time.monotonic() - paused.preempted_at
        self._slots[slot] = run
        self._batch_dirty = True
        stat_add("STAT_serving_resumes")
        return True

    def _rebuild_batch(self):
        s = self.max_slots
        tokens = np.zeros((s,), np.int32)
        pos = np.zeros((s,), np.int32)
        keys = np.zeros((s, self._key_width), np.uint32)
        temp = np.ones((s,), np.float32)
        top_k = np.zeros((s,), np.int32)
        top_p = np.ones((s,), np.float32)
        greedy = np.ones((s,), bool)
        poison = np.zeros((s,), bool)
        spec_on = np.zeros((s,), bool)
        for slot, run in self._slots.items():
            tokens[slot] = run.last_token
            pos[slot] = run.pos
            keys[slot] = run.key
            temp[slot] = run.req.temperature
            top_k[slot] = run.req.top_k
            top_p[slot] = run.req.top_p
            greedy[slot] = run.req.greedy
            poison[slot] = run.req.poison
            spec_on[slot] = run.req.spec
        self._dev_tokens = jnp.asarray(tokens)
        self._dev_pos = jnp.asarray(pos)
        self._dev_params = tuple(jnp.asarray(a) for a in (
            keys, temp, top_k, top_p, greedy, poison, spec_on))
        self._batch_dirty = False

    def _decode_step(self):
        if self.draft_model is not None:
            self._spec_step()
            return
        span = self._span("serving_decode")
        try:
            if self._batch_dirty:
                self._rebuild_batch()
            # PDTPU_FAULT_SLOW_DECODE: host-side latency injection, read
            # live per call — overload/SLO-miss paths become testable on
            # CPU without a big model
            faults.maybe_slow_decode(self._decode_calls)
            self._decode_calls += 1
            keys, temp, top_k, top_p, greedy, poison, _ = self._dev_params
            toks, logps, finites, ntok, npos, self._pools = self._decode_fn(
                self._state, self._pools, self._dev_tokens, self._dev_pos,
                keys, temp, top_k, top_p, greedy, poison)
            self._dev_tokens, self._dev_pos = ntok, npos
            # one device->host pull for the whole (chunk, slots) burst
            toks, logps, finites = jax.device_get((toks, logps, finites))
            stat_add("STAT_serving_decode_steps")
            emitted = 0
            for slot in list(self._slots):
                run = self._slots[slot]
                for j in range(toks.shape[0]):
                    # deadline enforcement on the decode tick itself, not
                    # only at the next sweep: a budget that expired while
                    # the chunk was computing stops the stream here — no
                    # post-expiry tokens are delivered, the slot recycles
                    # now (regression: deadline shorter than one chunk)
                    if (run.req.deadline is not None
                            and run.req.deadline.expired()):
                        stat_add("STAT_serving_deadline_expired")
                        run.resp._fail(DeadlineExceededError(
                            f"request {run.req.id} deadline "
                            f"({run.req.deadline.seconds}s) expired "
                            "mid-decode"))
                        self._release(slot)
                        break
                    if not finites[j, slot]:
                        self._fail_slot(slot, run.resp, "decode")
                        break
                    t = int(toks[j, slot])
                    run.pos += 1
                    run.produced += 1
                    run.last_token = t
                    self._emit(run, t, float(logps[j, slot]))
                    emitted += 1
                    self._maybe_finish(slot, run, t)
                    if slot not in self._slots:
                        # finished mid-chunk: the tail iterations of this
                        # slot are discarded (their KV garbage dies with
                        # the slot's next prefill)
                        break
            if emitted:
                stat_add("STAT_serving_tokens", emitted)
        finally:
            if span is not None:
                span.__exit__(None, None, None)

    def _spec_step(self):
        """One speculative tick: K draft proposals + one batched target
        verify, committing 1..K+1 tokens per slot.  Host side mirrors the
        chunked decode step — including the PR-6 deadline rule: a tick can
        commit up to K+1 tokens, and a deadline that expired while the
        tick was computing stops the stream BEFORE the next commit — no
        post-expiry token is ever delivered."""
        span = self._span("serving_verify")
        try:
            if self._batch_dirty:
                self._rebuild_batch()
            tick_no = self._decode_calls  # lifetime stride counter: the
            # diverge fault keys off it, NOT _spec_ticks, which is a
            # metrics-window counter reset_metrics() zeroes
            faults.maybe_slow_decode(tick_no)
            self._decode_calls += 1
            keys, temp, top_k, top_p, greedy, poison, spec_on = \
                self._dev_params
            diverge = bool(self._diverge_every is not None
                           and tick_no % self._diverge_every == 0)
            self._spec_ticks += 1
            (toks, logps, finites, counts, accepts, last, npos,
             self._pools, self._draft_pools) = self._decode_fn(
                self._state, self._dstate, self._pools, self._draft_pools,
                self._dev_tokens, self._dev_pos, keys, temp, top_k, top_p,
                greedy, spec_on, poison, jnp.asarray(diverge))
            self._dev_tokens, self._dev_pos = last, npos
            # one device->host pull for the whole (slots, K+1) tick
            toks, logps, finites, counts, accepts = jax.device_get(
                (toks, logps, finites, counts, accepts))
            stat_add("STAT_serving_decode_steps")
            stat_add("STAT_spec_ticks")
            k_spec = self.spec_tokens
            emitted = proposed = accepted_n = 0
            for slot in list(self._slots):
                run = self._slots[slot]
                if not finites[slot]:
                    self._fail_slot(slot, run.resp, "verify")
                    continue
                if run.req.spec:
                    proposed += k_spec
                    accepted_n += int(accepts[slot])
                    self._h_accept.observe(int(accepts[slot]) / k_spec)
                for j in range(int(counts[slot])):
                    # deadline enforcement on the tick itself (PR-6 rule):
                    # a speculative tick may hold K+1 ready tokens, but a
                    # budget that expired mid-tick delivers none of the
                    # remainder — the slot recycles now (regression:
                    # deadline shorter than one speculative tick)
                    if (run.req.deadline is not None
                            and run.req.deadline.expired()):
                        stat_add("STAT_serving_deadline_expired")
                        run.resp._fail(DeadlineExceededError(
                            f"request {run.req.id} deadline "
                            f"({run.req.deadline.seconds}s) expired "
                            "mid-decode"))
                        self._release(slot)
                        break
                    t = int(toks[slot, j])
                    run.pos += 1
                    run.produced += 1
                    run.last_token = t
                    self._emit(run, t, float(logps[slot, j]))
                    emitted += 1
                    self._maybe_finish(slot, run, t)
                    if slot not in self._slots:
                        # finished mid-tick: the tail commits are
                        # discarded (their KV garbage dies with the
                        # slot's next prefill)
                        break
            if emitted:
                stat_add("STAT_serving_tokens", emitted)
            if proposed:
                stat_add("STAT_spec_proposed", proposed)
                stat_add("STAT_spec_accepted", accepted_n)
                with self._m_lock:
                    self._spec_proposed += proposed
                    self._spec_accepted += accepted_n
        finally:
            if span is not None:
                span.__exit__(None, None, None)

    def _fail_slot(self, slot: int, resp: Response, phase: str):
        stat_add("STAT_serving_nonfinite")
        with self._m_lock:
            self._errored += 1
        resp._fail(NonFiniteLogitsError(
            f"request {resp.request.id}: non-finite logits during {phase}; "
            "slot recycled, engine keeps serving"))
        self._release(slot)

    def _emit(self, run: _SlotRun, tok: int, logp: float):
        now = time.monotonic()
        first = run.resp.first_token_at is None
        run.resp._push_token(tok, logp)
        with self._m_lock:
            self._tokens_out += 1
            if first:
                self._ttfts.append(run.resp.ttft)
            else:
                self._itl_sum += now - run.last_token_at
                self._itl_n += 1
        if first:
            self._h_ttft.observe(run.resp.ttft)
        else:
            self._h_itl.observe(now - run.last_token_at)
        run.last_token_at = now

    def _maybe_finish(self, slot: int, run: _SlotRun, tok: int):
        eos = run.req.eos_token_id
        if eos is not None and tok == eos:
            reason = "eos"
        elif run.produced >= run.req.max_new_tokens:
            reason = "length"
        else:
            return
        with self._m_lock:
            self._completed += 1
        run.resp._finish(reason)
        self._release(slot)

    def _span(self, name: str):
        if not self._profile:
            return None
        from ..utils.profiler import RecordEvent
        return RecordEvent(name).__enter__()

    # ------------------------------------------------------------------
    # driving
    # ------------------------------------------------------------------
    def has_work(self) -> bool:
        return bool(self._slots) or self.scheduler.has_work()

    def run_until_drained(self, timeout: Optional[float] = None):
        """Drive the loop in the caller's thread until queue and slots are
        empty (tests / batch jobs).  Not for use while start() is live."""
        t0 = time.monotonic()
        while self.has_work():
            self.step()
            if timeout is not None and time.monotonic() - t0 > timeout:
                raise TimeoutError("serving engine did not drain in "
                                   f"{timeout}s")

    def _abort_all(self, make_exc):
        """Fail every in-flight and queued request (engine death/close):
        a consumer blocked in Response.__iter__ / tokens() must get an
        error, never hang."""
        for slot in list(self._slots):
            run = self._slots.pop(slot)
            self.scheduler.release(slot)
            run.resp._fail(make_exc(run.req))
        for req, resp in self.scheduler.drain_pending():
            resp._fail(make_exc(req))
        self._batch_dirty = True

    def start(self):
        """Background engine loop (streaming servers / the probe)."""
        if self._thread is not None:
            return
        if self._closed:
            raise UnavailableError("serving engine is closed")
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    did = self.step()
                except BaseException as e:  # noqa: BLE001 — must not hang
                    # the loop thread dying silently would leave every
                    # consumer blocked forever: record the cause, fail all
                    # outstanding requests, refuse new ones
                    self._dead = e
                    self._abort_all(lambda req: UnavailableError(
                        f"request {req.id} aborted: serving engine loop "
                        f"died: {e!r}"))
                    return
                if not did:
                    self._work.wait(0.002)
                    self._work.clear()

        self._thread = threading.Thread(target=loop, name="serving-engine",
                                        daemon=True)
        self._thread.start()

    def close(self):
        """Stop the loop and fail any still-outstanding requests (a
        Response consumer must never be left blocked on a closed
        engine)."""
        self._closed = True
        self._stop.set()
        self._work.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._abort_all(lambda req: RequestCancelled(
            f"request {req.id} aborted: serving engine closed"))

    def warmup(self):
        """Compile every program the engine will ever run (one prefill per
        bucket + the decode/verify step) so no request pays a trace.  Runs
        dummy data through slot 0; safe any time no request is in
        flight."""
        s = self.max_slots
        zero_key = jnp.asarray(np.zeros(self._key_width, np.uint32))
        for b in self.buckets:
            ids = np.full((1, b), self.pad_token_id, np.int32)
            if self.draft_model is not None:
                (_, _, _, self._pools,
                 self._draft_pools) = self._prefill_fns[b](
                    self._state, self._dstate, self._pools,
                    self._draft_pools, jnp.asarray(ids), jnp.int32(0),
                    jnp.int32(1), zero_key, jnp.float32(1.0), jnp.int32(0),
                    jnp.float32(1.0), jnp.asarray(True))
            else:
                _, _, _, self._pools = self._prefill_fns[b](
                    self._state, self._pools, jnp.asarray(ids),
                    jnp.int32(0), jnp.int32(1), zero_key, jnp.float32(1.0),
                    jnp.int32(0), jnp.float32(1.0), jnp.asarray(True))
        if self.draft_model is not None:
            (_, _, _, _, _, _, _, self._pools,
             self._draft_pools) = self._decode_fn(
                self._state, self._dstate, self._pools, self._draft_pools,
                jnp.zeros((s,), jnp.int32), jnp.zeros((s,), jnp.int32),
                jnp.zeros((s, self._key_width), jnp.uint32),
                jnp.ones((s,), jnp.float32), jnp.zeros((s,), jnp.int32),
                jnp.ones((s,), jnp.float32), jnp.ones((s,), bool),
                jnp.ones((s,), bool), jnp.zeros((s,), bool),
                jnp.asarray(False))
        else:
            _, _, _, _, _, self._pools = self._decode_fn(
                self._state, self._pools, jnp.zeros((s,), jnp.int32),
                jnp.zeros((s,), jnp.int32),
                jnp.zeros((s, self._key_width), jnp.uint32),
                jnp.ones((s,), jnp.float32), jnp.zeros((s,), jnp.int32),
                jnp.ones((s,), jnp.float32), jnp.ones((s,), bool),
                jnp.zeros((s,), bool))

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------
    def compile_counts(self) -> Dict:
        """Traced-program counts: the ≤ len(buckets) + 1 guarantee.  For
        speculative engines the same bound holds — "decode" counts the one
        verify program (draft proposal scan + batched target verify +
        in-program commit) and each per-bucket prefill program covers
        target AND draft prefill, so spec on/off × greedy/sampling traffic
        never adds a program."""
        return {"decode": self._compiles["decode"],
                "prefill": dict(self._compiles["prefill"]),
                "total": (self._compiles["decode"]
                          + sum(self._compiles["prefill"].values())),
                "bound": len(self.buckets) + 1}

    def metrics(self) -> Dict:
        """Serving metrics snapshot (also published as STAT_serving_*
        monitor counters and, under enable_profile, in the profiler
        report)."""
        with self._m_lock:
            ttfts = sorted(self._ttfts)
            p50 = ttfts[len(ttfts) // 2] if ttfts else None
            itl = self._itl_sum / self._itl_n if self._itl_n else None
            elapsed = time.monotonic() - self._started_at
            return {
                "requests_completed": self._completed,
                "requests_errored": self._errored,
                "tokens_out": self._tokens_out,
                "tokens_per_sec": (self._tokens_out / elapsed
                                   if elapsed > 0 else 0.0),
                "ttft_p50_ms": None if p50 is None else p50 * 1e3,
                "inter_token_ms": None if itl is None else itl * 1e3,
                "queue_depth": self.scheduler.queue_depth(),
                "slot_occupancy": self.scheduler.occupancy(),
                "max_slots": self.max_slots,
                "compile_counts": self.compile_counts(),
                "spec": self._spec_metrics(),
            }

    def _spec_metrics(self):
        if self.draft_model is None:
            return {"enabled": False}
        return {
            "enabled": True,
            "spec_tokens": self.spec_tokens,
            "ticks": self._spec_ticks,
            "proposed": self._spec_proposed,
            "accepted": self._spec_accepted,
            "accept_rate": (self._spec_accepted / self._spec_proposed
                            if self._spec_proposed else None),
        }

    def reset_metrics(self):
        with self._m_lock:
            self._ttfts = []
            self._itl_sum = 0.0
            self._itl_n = 0
            self._tokens_out = 0
            self._completed = 0
            self._errored = 0
            self._started_at = time.monotonic()
            if self.draft_model is not None:
                self._spec_ticks = 0
                self._spec_proposed = 0
                self._spec_accepted = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
