"""ServingEngine: continuous batching over a slot-based KV-cache pool.

Exactly two compiled program families serve every request mix:

- **bucketed prefill** (one trace per prompt-length bucket): the prompt,
  right-padded to the bucket, runs through `model.forward_fixed` against a
  bucket-sized scratch cache; the resulting KV is written into the assigned
  slot of the engine-lifetime pool via `dynamic_update_slice`, overwriting
  the slot's FULL [0, max_len) range (stale KV from the slot's previous
  occupant can never leak).  The first generated token is sampled inside
  the same program from the prompt's last-position logits.
- **one decode step** (a single trace, ever): `model.forward_fixed` is
  vmapped over the slot axis so every slot advances one token per call with
  its OWN write position, and every sampling knob — temperature, top-k,
  top-p, greedy flag, RNG key — is a per-slot dynamic input
  (`generation.process_logits_dynamic`), so heterogeneous requests share
  the trace.  Requests join and leave the resident batch between
  iterations; nobody owns a compilation.

Compilation count is therefore bounded by len(prefill_buckets) + 1 per
engine, regardless of how many (prompt_len, max_new, sampling-param)
combinations the traffic mixes — asserted by `compile_counts()`.

**Speculative decoding** (``draft_model=``): the decode program is
replaced by ONE verify program per engine that (a) runs ``spec_tokens``
sequential draft-model steps proposing K tokens per slot (the draft owns
its own slot pool, written with the same protocol), (b) scores
``[last_committed, d_1..d_K]`` — K+1 positions — in ONE batched target
forward, and (c) commits the longest accepted prefix plus one corrected
token entirely in-program (`generation.speculative`: greedy equality
accept, or distribution-preserving rejection sampling for sampling
slots), so a tick advances 1..K+1 tokens per slot with a single target
dispatch.  Per-bucket prefill additionally prefills the draft pool inside
the same program.  The program bound is UNCHANGED: len(prefill_buckets)
prefill programs (each covering target + draft) + 1 verify program —
spec on/off per request, greedy/sampling, and every sampling-param combo
share the single verify trace via dynamic per-slot inputs.  Greedy
speculative streams stay bit-identical to solo `generate` (acceptance is
argmax equality against the same logits rows the solo loop argmaxes);
spec-off slots inside a speculative engine reproduce the plain decode
step token-for-token (same key folds, same distributions).

**Paged KV pool** (``kv="paged"``): the slot-row pool is replaced by ONE
block pool per layer (``[num_blocks, block_size, heads, head_dim]`` —
serving/kv_pool.py) with a host-side allocator and per-slot block-table
indirection.  The compiled programs change shape but not count or
semantics: prefill writes the prompt's blocks through the slot's table
(full-block overwrite — no stale KV survives re-serving), the
decode/verify step gathers each slot's table into the contiguous view
ONCE per call (the batched form of
`ops.paged_attention.gather_block_rows` — on CPU this reconstruction
keeps every float op identical to the fixed engine, so streams stay
bit-identical to solo generate) and scatters the tick's freshly written
rows back in one pass, zeroing any block it enters (scrub-on-recycle).
Honest cost note: the gathered view is a TRANSIENT per-call working set
of up to fixed-pool size, so on an accelerator the density win is in
the PERSISTENT pool only until the pallas block-table kernel
(`ops.paged_attention.paged_attention`, which reads O(live blocks) and
never materializes the view) replaces the gather inside the decode
program — the ROADMAP's named next step on a live slot.  Block exhaustion
is backpressure: admission waits for free blocks, mid-decode shortfall
preempts the newest lowest-priority run into a host snapshot (the PR-6
preempt machinery) and resumes it when the pool drains, and a run that
can no longer fit at all fails with the typed `KVPoolExhaustedError`.
``PDTPU_FAULT_KV_EXHAUST=N`` caps the live pool to force every path.

**Tensor parallelism** (``mesh=``): the whole engine runs SPMD over a
`jax.sharding.Mesh` — params laid out by `parallel.sharding.param_specs`
(column-parallel qkv/ffn_in, row-parallel proj/ffn_out, vocab-sharded
embeddings), the KV pool sharded over heads on the ``tp`` axis, and the
same prefill/decode/verify programs compiled ONCE under the mesh (XLA
GSPMD inserts the collectives).  The 8-virtual-device CPU mesh makes the
whole thing tier-1 testable: streams match the single-device engine
token-for-token for the same seeds.

Greedy requests are bit-identical to a solo
`generation.generate(decode_strategy='greedy_search')` run of the same
prompt: prefill logits at the prompt's last position are unaffected by
right-padding (causal mask), and decode attends exactly the
[0, pos] prefix of the slot, the same masked-buffer attention the solo
loop runs.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.errors import FatalError, InvalidArgumentError, UnavailableError
from ..generation import process_logits_dynamic
from ..utils import faults
from ..utils.monitor import stat_add
from .kv_pool import KVPoolExhaustedError, PagedKVPool
from .request import Request, Response, RequestCancelled
from .scheduler import RequestScheduler, DeadlineExceededError

__all__ = ["ServingEngine", "NonFiniteLogitsError", "PreemptedRun",
           "KVPoolExhaustedError"]


class NonFiniteLogitsError(FatalError):
    """Decode produced NaN/Inf logits for this request's slot; the request
    is errored individually and its slot recycled."""
    code = "Fatal"


def _first_token_at(logits, idx, fold_pos, key, temp, top_k, top_p,
                    greedy):
    """Sample the first generated token from the logits row at `idx`,
    folding the key at the ABSOLUTE position `fold_pos` — the general
    form behind `_first_token`.  The cached-prefix prefill computes only
    the prompt's uncached suffix, so its last-position logits sit at the
    RELATIVE index (prompt_len - 1 - cached_len) while the key must
    still fold at the absolute (prompt_len - 1) for stream parity with
    the cold path."""
    last = jax.lax.dynamic_index_in_dim(
        logits[0].astype(jnp.float32), idx, axis=0, keepdims=False)
    finite = jnp.isfinite(last).all()
    proc = process_logits_dynamic(
        last[None], temp[None], top_k[None], top_p[None], greedy[None])[0]
    sampled = jax.random.categorical(
        jax.random.fold_in(key, fold_pos), proc)
    tok = jnp.where(greedy, jnp.argmax(proc, axis=-1),
                    sampled).astype(jnp.int32)
    logp = jax.nn.log_softmax(proc)[tok]
    return tok, logp, finite


def _first_token(logits, prompt_len, key, temp, top_k, top_p, greedy):
    """Sample the first generated token from the prompt's last-position
    logits (shared by the fixed and paged prefill programs).  Right
    padding never touches that position (causal mask), so this matches
    the solo generate prefill; the key is folded at (prompt_len - 1) and
    decode step j folds at prompt_len + j — counters never collide."""
    return _first_token_at(logits, prompt_len - 1, prompt_len - 1, key,
                           temp, top_k, top_p, greedy)


def _sample_step(last, keys, pos, temp, top_k, top_p, greedy):
    """One per-slot sampling decision over (S, V) logits — shared by the
    fixed and paged decode steps so the bit-identical-stream contract has
    a single implementation site.  All-greedy fast path: the full dynamic
    sampling pipeline (two (S, V) sorts + threefry draw) costs real time
    per iteration; a pure-greedy batch — the common serving mix — skips
    it at runtime via lax.cond, INSIDE the single decode trace (no extra
    program, identical tokens: with greedy all-True
    process_logits_dynamic returns the raw logits, so both branches
    argmax the same array)."""
    def mixed(last):
        proc = process_logits_dynamic(last, temp, top_k, top_p, greedy)
        folded = jax.vmap(jax.random.fold_in)(keys, pos)
        sampled = jax.vmap(jax.random.categorical)(folded, proc)
        tok = jnp.where(greedy, jnp.argmax(proc, axis=-1),
                        sampled).astype(jnp.int32)
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(proc, axis=-1), tok[:, None],
            axis=-1)[:, 0]
        return tok, logp

    def all_greedy(last):
        tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(last, axis=-1), tok[:, None],
            axis=-1)[:, 0]
        return tok, logp

    return jax.lax.cond(jnp.all(greedy), all_greedy, mixed, last)


def _draft_propose(dlast, keys, pos, temp, top_k, top_p, greedy, i):
    """One per-slot draft proposal from (S, V) draft logits — shared by
    the fixed and paged verify steps (same single-site rationale and
    all-greedy fast path as _sample_step).  Returns (proposal, q)."""
    from ..generation.speculative import draft_proposal_key

    def mixed(dlast):
        proc = process_logits_dynamic(dlast, temp, top_k, top_p, greedy)
        kd = jax.vmap(lambda kk, pp: draft_proposal_key(kk, pp, i))(
            keys, pos)
        sampled = jax.vmap(jax.random.categorical)(kd, proc)
        prop = jnp.where(greedy, jnp.argmax(proc, axis=-1),
                         sampled).astype(jnp.int32)
        return prop, jax.nn.softmax(proc, axis=-1)

    def all_greedy(dlast):
        return (jnp.argmax(dlast, axis=-1).astype(jnp.int32),
                jax.nn.softmax(dlast, axis=-1))

    return jax.lax.cond(jnp.all(greedy), all_greedy, mixed, dlast)


def _extract_rows(ctx, start, n):
    """Per-slot (n,) row windows from gathered (S, T, ...) KV views —
    the write-back side of the paged decode/verify builders."""
    return [
        (jax.vmap(lambda c, p: jax.lax.dynamic_slice_in_dim(
            c, p, n))(kc, start),
         jax.vmap(lambda c, p: jax.lax.dynamic_slice_in_dim(
             c, p, n))(vc, start))
        for (kc, vc) in ctx]


def _gather_ctx(pool, tables):
    """Batched `ops.paged_attention.gather_block_rows` (ONE
    implementation site for the clip/sentinel contract): (S, nb_max)
    block tables over a (num_blocks, block_size, ...) pool -> every
    slot's contiguous (T, ...) KV view — the SAME length the fixed
    engine's slot row would have (to the block boundary), so the paged
    attention pays nothing extra.  Shared by the paged decode and verify
    builders."""
    from ..ops.paged_attention import gather_block_rows
    return jax.vmap(gather_block_rows, in_axes=(None, 0))(pool, tables)


def _window_start(pos, n_rows, total_rows):
    """Write-back window start for extracting `n_rows` rows from a
    (*, total_rows, ...) gathered view: `pos` clamped so the window
    never runs off the end.  A clamped window re-writes up to
    (pos - start) rows BELOW pos with the values the gather read for
    them — idempotent by construction — instead of paying a permanently
    longer view just to keep dynamic_slice from clamping."""
    return jnp.maximum(0, jnp.minimum(pos, total_rows - n_rows))


class _CachedPlan:
    """Host-side warm-admission plan (see `_cached_plan`)."""

    __slots__ = ("chain", "matched", "cow", "cached_len", "bucket",
                 "new_live")

    def __init__(self, chain, matched, cow, cached_len, bucket, new_live):
        self.chain = chain            # cached block ids to adopt
        self.matched = matched        # rows covered by the chain
        self.cow = cow                # last chain block needs a COW copy
        self.cached_len = cached_len  # dynamic prefill input
        self.bucket = bucket          # SUFFIX bucket (plen - cached_len)
        self.new_live = new_live      # fresh live blocks this admit costs


def _paged_row_writer(block_size, sentinel, pool_len):
    """Builds the traced write-back for paged decode/verify: scatter
    `n_rows` freshly produced KV rows per slot (positions pos..pos+n-1)
    through the block tables, zeroing every block a slot ENTERS (write
    offset 0) before the rows land — the scrub-on-recycle guarantee.
    Inactive slots and rows past pool_len route through the sentinel id
    and are dropped."""
    from ..ops.paged_attention import scatter_block_rows, scrub_blocks

    def write(pools, tables, pos, rows_list, active, n_rows):
        pvals = pos[:, None] + jnp.arange(n_rows)[None, :]      # (S, R)
        bidx = jnp.clip(pvals // block_size, 0, tables.shape[1] - 1)
        blk = jnp.take_along_axis(tables, bidx, axis=1)
        off = (pvals % block_size).reshape(-1)
        ok = active[:, None] & (pvals < pool_len)
        blk_w = jnp.where(ok, blk, sentinel).reshape(-1)
        # a block's first row IS the entering position, so every already
        # committed row of the entering slot lives in earlier blocks —
        # zeroing here can only erase recycled/stale speculative rows
        scrub = jnp.where(ok & (pvals % block_size == 0), blk,
                          sentinel).reshape(-1)
        new_pools = []
        for (kp, vp), (kr, vr) in zip(pools, rows_list):
            kr = kr.reshape((-1,) + kr.shape[2:])               # (S*R, ...)
            vr = vr.reshape((-1,) + vr.shape[2:])
            kp = scrub_blocks(kp, scrub)
            vp = scrub_blocks(vp, scrub)
            new_pools.append((scatter_block_rows(kp, blk_w, off, kr),
                              scatter_block_rows(vp, blk_w, off, vr)))
        return new_pools

    return write


def _default_buckets(max_len: int):
    """Powers of two from 16 up to max_len (prompt lengths round up)."""
    buckets, b = [], 16
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_len)
    return tuple(buckets)


class _SlotRun:
    """Host-side per-slot decode state."""
    __slots__ = ("req", "resp", "pos", "produced", "last_token",
                 "last_token_at", "key", "aid")

    def __init__(self, req: Request, resp: Response, pos: int,
                 first_token: int, key: np.ndarray, aid: int = 0):
        self.req = req
        self.resp = resp
        self.pos = pos              # kv length so far (write offset)
        self.produced = 1           # first token came from prefill
        self.last_token = first_token
        self.last_token_at = time.monotonic()
        self.key = key
        self.aid = aid              # pinned adapter slot id (0 = base)


class PreemptedRun:
    """Host snapshot of a preempted in-flight decode — everything needed
    to resume the stream, bit-identical, in ANY free slot later.

    The same snapshot/publish split `distributed.checkpoint` uses: the
    live KV rows are copied device->host NOW (so the pool stays free to be
    donated to the next compiled call), and "publish" is the later
    `restore_run` writing them back.  `kv_rows` holds per-layer
    ``(k_rows, v_rows)`` numpy arrays of shape ``(pos, ...)``; sampling
    state (RNG key, write position, produced count, last token) rides
    along so decode step `pos` folds the same key it would have folded
    uninterrupted."""

    __slots__ = ("req", "resp", "pos", "produced", "last_token", "key",
                 "kv_rows", "draft_kv_rows", "preempted_at",
                 "source_config_hash")

    def __init__(self, run: _SlotRun, kv_rows, draft_kv_rows=None):
        self.req = run.req
        self.resp = run.resp
        self.pos = run.pos
        self.produced = run.produced
        self.last_token = run.last_token
        self.key = run.key
        self.kv_rows = kv_rows
        # speculative engines snapshot the draft pool rows too: resuming
        # with a coherent draft context preserves the accept rate (output
        # correctness never depends on draft KV — rejected proposals are
        # free — but garbage draft context would decay a resumed stream
        # to target-only throughput)
        self.draft_kv_rows = draft_kv_rows
        self.preempted_at = time.monotonic()
        # the source engine's transfer-identity digest
        # (transfer.engine_config_hash), stamped by preempt_slot so the
        # hash survives every manager-side re-encode hop of a migration
        # — a cross-manifest restore must be refused typed no matter how
        # many times the snapshot was decoded and re-encoded in between
        self.source_config_hash: Optional[str] = None

    @classmethod
    def from_state(cls, req, resp, pos: int, produced: int,
                   last_token: int, key, kv_rows, draft_kv_rows=None):
        """Build a PreemptedRun from raw snapshot state instead of a live
        _SlotRun — the run-transfer codec's decode side
        (serving/transfer.py): a snapshot that crossed a replica (or, via
        its byte form, a process) boundary restores through the SAME
        `restore_run` contract a locally preempted run uses."""
        paused = cls.__new__(cls)
        paused.req = req
        paused.resp = resp
        paused.pos = int(pos)
        paused.produced = int(produced)
        paused.last_token = int(last_token)
        paused.key = np.asarray(key)
        paused.kv_rows = kv_rows
        paused.draft_kv_rows = draft_kv_rows
        paused.preempted_at = time.monotonic()
        paused.source_config_hash = None
        return paused


class ServingEngine:
    """Continuous-batching engine over a model implementing the
    `gen_fixed_cache` / `forward_fixed` protocol (see the serving package
    docstring and models/gpt.py:190,201)."""

    def __init__(self, model, max_slots: int = 8, max_len: int = 256,
                 prefill_buckets=None, max_queue_depth: int = 64,
                 pad_token_id: int = 0, dtype=None, profile: bool = False,
                 decode_chunk: int = 4, draft_model=None,
                 spec_tokens: int = 4, kv: str = "fixed",
                 block_size: int = 16, num_blocks: Optional[int] = None,
                 mesh=None, program_set=None, prefix_cache: bool = False,
                 share_policy=None, lora=None):
        from ..generation import _model_fns
        self.model = model
        self.max_slots = int(max_slots)
        self.max_len = int(max_len)
        self.pad_token_id = int(pad_token_id)
        self.buckets = tuple(sorted(set(
            int(b) for b in (prefill_buckets or _default_buckets(max_len)))))
        if self.buckets[-1] > self.max_len:
            raise InvalidArgumentError(
                f"prefill bucket {self.buckets[-1]} exceeds max_len "
                f"{self.max_len}")
        self._dtype = dtype
        self._profile = bool(profile)
        # tokens decoded per compiled decode call (an internal lax.scan):
        # amortizes the per-call host+dispatch cost across chunk tokens per
        # slot.  Tokens stream in bursts of `chunk`; admission, deadline
        # and cancel sweeps run between calls.  A slot finishing mid-chunk
        # wastes its tail iterations (its post-finish tokens are discarded
        # on the host and its KV garbage is overwritten by the slot's next
        # prefill) — with budgets >> chunk the waste is marginal and the
        # dispatch amortization dominates on every backend.
        self.decode_chunk = max(1, int(decode_chunk))
        self.scheduler = RequestScheduler(self.max_slots, max_queue_depth)
        # batched LoRA adapters (paddle_tpu.lora): per-slot adapter ids
        # are DYNAMIC inputs to the same program family and the factor
        # stacks ride as ordinary program arguments, so heterogeneous
        # adapters batch in one tick at the unchanged compile bound.
        # The hooks are armed BEFORE _model_fns so every traced program
        # sees them; they add no state keys (swap_weights / refresh /
        # transfer are untouched).
        self.lora = lora
        self._lora_reg = None
        self._lora_keys: Tuple[str, ...] = ()
        if lora is not None:
            if draft_model is not None:
                raise InvalidArgumentError(
                    "lora=LoRAConfig(...) and draft_model= (speculative "
                    "decoding) cannot be combined on one engine yet: the "
                    "verify program's draft proposals would need their "
                    "own per-slot adapter gathers.  Drop draft_model= on "
                    "this engine (adapters usually matter more than spec "
                    "speedup for multi-tenant traffic), or route "
                    "speculative traffic to a separate non-LoRA engine "
                    "until spec-decode composition lands.")
            if prefix_cache:
                raise InvalidArgumentError(
                    "lora=LoRAConfig(...) and prefix_cache=True cannot "
                    "be combined on one engine yet: cached KV blocks are "
                    "computed under ONE adapter's projections, so a warm "
                    "hit served to a different adapter would be silently "
                    "wrong.  Drop prefix_cache=True on this engine, or "
                    "serve prefix-heavy base-model traffic from a "
                    "separate non-LoRA engine until per-adapter cache "
                    "partitioning lands.")
            from ..lora.layers import attach_serving_lora
            from ..lora.registry import AdapterRegistry
            from ..lora.train import base_weights_hash
            shapes = attach_serving_lora(model, lora.targets)
            base_sha = (base_weights_hash(model)
                        if lora.check_base_hash and lora.base_sha is None
                        else None)
            self._lora_reg = AdapterRegistry(lora, shapes,
                                             base_sha=base_sha)
            self._lora_keys = self._lora_reg.keys
        self._state, self._apply = _model_fns(model)
        self.draft_model = draft_model
        self.spec_tokens = int(spec_tokens)
        if draft_model is not None:
            if self.spec_tokens < 1:
                raise InvalidArgumentError(
                    f"spec_tokens must be >= 1, got {self.spec_tokens}")
            if self.spec_tokens >= self.max_len:
                raise InvalidArgumentError(
                    f"spec_tokens {self.spec_tokens} must be < max_len "
                    f"{self.max_len}")
        # pool length: speculative engines get spec_tokens rows of
        # HEADROOM beyond max_len — a verify tick writes K+1 rows at
        # pos..pos+K even when only one commits, and pos legitimately
        # reaches plen+max_new-2 <= max_len-2; without the headroom the
        # final ticks of a full-budget request would have
        # dynamic_update_slice CLAMP the write start and silently
        # overwrite committed KV (breaking greedy parity).  Request
        # validation stays at plen+max_new <= max_len.
        self._pool_len = self.max_len + (
            self.spec_tokens if draft_model is not None else 0)
        # tensor parallelism: lay the params out over the mesh BEFORE any
        # program traces — prefill/decode/verify then compile once under
        # the mesh and XLA GSPMD owns the collectives
        self.mesh = mesh
        self._kv_put = None
        if mesh is not None:
            self._init_mesh(mesh)
            self._state = self._shard_state(self._state)
        if kv not in ("fixed", "paged"):
            raise InvalidArgumentError(
                f"kv must be 'fixed' or 'paged', got {kv!r}")
        self.kv = kv
        # prefix-aware KV reuse (serving/prefix_cache.py): opt-in so the
        # plain paged engine keeps its exact PR-8 allocation behavior
        if prefix_cache and kv != "paged":
            raise InvalidArgumentError(
                f"prefix_cache=True cannot be combined with kv={kv!r}: "
                "prefix reuse shares immutable KV BLOCKS between "
                "requests, and only the paged layout has blocks to "
                "share.  Pass kv='paged' on this engine, or drop "
                "prefix_cache=True to keep the fixed layout.")
        if prefix_cache and draft_model is not None:
            raise InvalidArgumentError(
                "prefix_cache=True and draft_model= (speculative "
                "decoding) cannot be combined on one engine yet: the "
                "draft pool shares the target's block tables, but the "
                "cached-prefill half of the draft path is "
                "unimplemented, so a warm hit would leave the draft KV "
                "incoherent.  Drop one of the two knobs on this engine "
                "— keep prefix_cache=True for prompt-template traffic, "
                "or keep draft_model= for long-decode traffic — or "
                "split the traffic across two engines until the "
                "composition lands.")
        self.prefix_cache = None
        self._share_policy = share_policy
        self._share_groups: Dict[str, str] = {}
        self._cow_fn = None
        self.block_size = int(block_size)
        if kv == "paged" and self.block_size < 1:
            raise InvalidArgumentError(
                f"block_size must be >= 1, got {self.block_size}")
        # rows one compiled tick may write per slot (capacity ensured
        # host-side before each paged call)
        self._rows_per_tick = (self.spec_tokens + 1
                               if draft_model is not None
                               else self.decode_chunk)
        if kv == "paged":
            if num_blocks is None:
                # default capacity parity with the fixed pool: paged is
                # opt-in HBM shaping, not a silent budget cut
                num_blocks = self.max_slots * (
                    -(-self._pool_len // self.block_size))
            # THE pool: one [num_blocks, block_size, heads, dim] block
            # pool per layer + the host-side allocator (kv_pool.py)
            self.kv_pool = PagedKVPool(int(num_blocks), self.block_size,
                                       self._pool_len)
            self._pools = self.kv_pool.build_pools(model, dtype,
                                                   put=self._kv_put)
            # OOM preemption state: runs parked when the block pool runs
            # dry mid-decode, resumed as it drains (bounded — overflow is
            # the typed KVPoolExhaustedError path)
            self._oom_paused: List[PreemptedRun] = []
            self._max_oom_paused = max(2, 2 * self.max_slots)
            self._paged_cache = None  # (allocator version, tables, active)
            self._oom_preempts = 0
            self._oom_failed = 0
            if prefix_cache:
                from .prefix_cache import PrefixCache
                self.prefix_cache = PrefixCache(self.kv_pool)

                # copy-on-write device copy: ONE jitted block copy
                # (src/dst are dynamic scalars — a single compile),
                # precompiled at warmup against the sentinel dst so the
                # zero-post-warmup-compiles contract holds under COW
                def _cow(pools, src, dst):
                    return [(kp.at[dst].set(kp[src], mode="drop"),
                             vp.at[dst].set(vp[src], mode="drop"))
                            for kp, vp in pools]

                self._cow_fn = jax.jit(_cow, donate_argnums=(0,))
        else:
            self.kv_pool = None
            # THE pool: one gen_fixed_cache(max_slots, pool_len)
            # allocation, reused for the engine's lifetime
            self._pools = model.gen_fixed_cache(self.max_slots,
                                                self._pool_len, dtype)
            if self._kv_put is not None:
                self._pools = [(self._kv_put(k), self._kv_put(v))
                               for k, v in self._pools]
        self._assert_kv_sharded(self._pools, "KV pool")
        self._warm = False
        self._slots: Dict[int, _SlotRun] = {}
        # device-resident decode batch state; rebuilt from host _SlotRun
        # state only when membership changes (admission / slot release)
        self._dev_tokens = None
        self._dev_pos = None
        self._dev_params = None
        self._batch_dirty = True
        self._rid = 0
        self._submit_lock = threading.Lock()
        # nan_logits fault: presence decided NOW (trace time) — the clean
        # decode program carries zero fault branches
        self._poison_target = faults.nan_logits_request()
        self._key_width = len(np.asarray(jax.random.PRNGKey(0)))
        # the pool is DONATED to every prefill/decode call and replaced by
        # the returned buffers: XLA updates the slots in place instead of
        # copying max_slots * max_len of KV per call (measured 166x on a
        # CPU pool-passthrough update; the same aliasing TPU donation does)
        self._donate = (1,)
        self._compiles = {"decode": 0, "prefill": {b: 0 for b in self.buckets}}
        self._decode_calls = 0  # slow_decode fault stride counter
        # speculative decoding: a draft model swaps the decode program for
        # the single verify program and adds a draft slot pool + draft
        # prefill folded into the per-bucket prefill programs — the
        # compiled-program bound stays len(buckets) + 1
        if draft_model is not None:
            self._dstate, self._dapply = _model_fns(draft_model)
            if mesh is not None:
                self._dstate = self._shard_state(self._dstate)
            if self.kv == "paged":
                # the draft pool pages too, SHARING the target's block
                # tables (one allocator): a slot's draft KV lives at the
                # same block ids in the draft leaf arrays
                self._draft_pools = self.kv_pool.build_pools(
                    draft_model, dtype, put=self._kv_put)
            else:
                self._draft_pools = draft_model.gen_fixed_cache(
                    self.max_slots, self._pool_len, dtype)
                if self._kv_put is not None:
                    self._draft_pools = [(self._kv_put(k), self._kv_put(v))
                                         for k, v in self._draft_pools]
            self._assert_kv_sharded(self._draft_pools, "draft KV pool")
            # draft_diverge fault: presence decided NOW (trace time); the
            # per-tick flag is a dynamic input
            self._diverge_every = faults.draft_diverge_every()
            self._spec_ticks = 0
            from ..observability import metrics as _obs_m2
            self._h_accept = _obs_m2.histogram(
                "serving_spec_accept_rate",
                "accepted draft proposals / spec_tokens, per slot per tick")
            self._spec_proposed = 0
            self._spec_accepted = 0
            self._decode_fn = (self._build_verify_paged()
                               if self.kv == "paged"
                               else self._build_verify())
        else:
            self._decode_fn = (self._build_decode_paged()
                               if self.kv == "paged"
                               else self._build_decode())
        if self.kv == "paged":
            # with a prefix cache every bucket's prefill is the cached
            # variant (cached_len=0 IS the cold path) — the program
            # family stays one prefill per bucket, bound unchanged
            build = (self._build_prefill_cached if self.prefix_cache
                     is not None else self._build_prefill_paged)
            self._prefill_fns = {b: build(b) for b in self.buckets}
        else:
            self._prefill_fns = {b: self._build_prefill(b)
                                 for b in self.buckets}
        # AOT program set (paddle_tpu.programs.program_set): swap the
        # freshly built — but never yet traced — program family for
        # deserialized ones.  'exe' programs are already-compiled native
        # executables (zero trace + zero compile at warmup); 'stablehlo'
        # ones compile their portable module on first call.  A manifest
        # mismatch or corrupt artifact raises ProgramSetError here —
        # the predictor layer catches it and falls back to tracing.
        self.program_set_info = None
        self._warm_marks = None
        if program_set is not None:
            from ..programs.program_set import load_program_set
            loaded = load_program_set(program_set, self)
            self._decode_fn = loaded["decode"]
            for b in self.buckets:
                self._prefill_fns[b] = loaded[f"prefill_b{b}"]
            self.program_set_info = {
                "path": program_set if isinstance(program_set, str)
                else None,
                "kinds": {k: v.kind for k, v in loaded.items()}}
        # observability: latency histograms shared with the unified
        # report / Prometheus endpoint (handles cached; registry.reset()
        # zeroes values in place)
        from ..observability import metrics as _obs_m
        self._h_ttft = _obs_m.histogram(
            "serving_ttft_seconds", "submit -> first streamed token")
        self._h_itl = _obs_m.histogram(
            "serving_inter_token_seconds",
            "gap between consecutive tokens of one request")
        # metrics accumulators
        self._m_lock = threading.Lock()
        self._ttfts: List[float] = []
        self._itl_sum = 0.0
        self._itl_n = 0
        self._tokens_out = 0
        self._completed = 0
        self._errored = 0
        self._started_at = time.monotonic()
        # background loop
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._work = threading.Event()
        self._closed = False
        # close() is idempotent and safe under concurrent double-close:
        # the fleet's replica manager fences and closes aggressively
        # (drain completion, crash handling, rollout teardown and the
        # user's own close can race), so exactly ONE caller runs the
        # join + abort sequence and everyone else returns once it's done
        self._close_lock = threading.Lock()
        self._dead: Optional[BaseException] = None
        # continuous weight refresh (serving/refresh.py): which published
        # artifact this engine currently serves (None = constructor
        # weights) and how many swaps it has absorbed.  Both are
        # host-side bookkeeping only — the compiled programs take the
        # state dict as a per-call argument, so swapping never retraces.
        self.weights_sha: Optional[str] = None
        self.refresh_epoch = 0

    # ------------------------------------------------------------------
    # continuous weight refresh
    # ------------------------------------------------------------------
    def swap_weights(self, state: Dict, weights_sha: Optional[str] = None):
        """Rebind the served weights to `state` with ZERO recompiles.

        Every compiled prefill/decode/verify program takes the state
        dict as an explicit call argument (never a closed-over
        constant), so a shape/dtype-stable swap reuses the loaded
        program set untouched — the next engine step simply passes the
        new arrays.  The caller (fleet flip choreography) guarantees the
        engine is idle or between steps on the driving thread; any
        in-progress compiled call keeps the OLD dict it was handed.

        Validates the exact key set + per-leaf shape/dtype against the
        current state and raises InvalidArgumentError on any mismatch —
        a wrong-architecture publish must never half-apply.  Under a
        mesh every leaf is re-placed with the incumbent leaf's sharding.
        A prefix cache is flushed: cached KV embeds the old weights'
        activations and would break new-weights bit-identity.
        """
        old = self._state
        missing = set(old) - set(state)
        unexpected = set(state) - set(old)
        if missing or unexpected:
            raise InvalidArgumentError(
                f"swap_weights state-dict key mismatch: missing "
                f"{sorted(missing)[:4]}, unexpected "
                f"{sorted(unexpected)[:4]}")
        for k, cur in old.items():
            new = state[k]
            if tuple(np.shape(new)) != tuple(np.shape(cur)):
                raise InvalidArgumentError(
                    f"swap_weights shape mismatch for {k!r}: "
                    f"{tuple(np.shape(new))} != {tuple(np.shape(cur))}")
        if self.mesh is not None:
            state = {k: jax.device_put(np.asarray(v, dtype=np.asarray(
                old[k]).dtype), old[k].sharding)
                for k, v in state.items()}
        else:
            state = {k: jnp.asarray(np.asarray(v), dtype=jnp.asarray(
                old[k]).dtype) for k, v in state.items()}
        # atomic rebind: one reference assignment — readers see either
        # the complete old dict or the complete new one
        self._state = state
        self.weights_sha = weights_sha
        self.refresh_epoch += 1
        if self.prefix_cache is not None:
            # old-weights KV must never seed a new-weights stream
            self.prefix_cache.evict(self.prefix_cache.resident_nodes())
        if self._lora_reg is not None and self.lora.check_base_hash:
            # loaded adapters SURVIVE the flip (the factor stacks are
            # registry state, not engine state, and the forward hooks
            # live on the layer objects) — but the registry's base pin
            # must follow the weights: a FUTURE register() now checks
            # artifacts against the base actually being served, not the
            # boot-time one
            from ..lora.train import state_hash
            self._lora_reg.base_sha = state_hash(self._state)

    def load_adapter(self, name: str, path: str) -> str:
        """Page a tenant's exported LoRA artifact into the adapter
        registry under `name` — hot: ZERO recompiles (the factor stacks
        are program ARGUMENTS; the slot write reuses the registry's
        pre-traced scatter) and safe while the engine loop is serving
        (no donation, see AdapterRegistry).  Idempotent for identical
        artifact bytes.  Returns the artifact's file sha256 (the fleet's
        re-attach cache key).  Typed failures: AdapterIntegrityError
        (corrupt / wrong base), InvalidArgumentError (rank/target
        mismatch), AdapterExhaustedError (every slot pinned)."""
        if self.lora is None:
            raise InvalidArgumentError(
                "load_adapter requires an engine constructed with "
                "lora=LoRAConfig(...) — this engine serves the base "
                "model only")
        idx = self._lora_reg.register(name, path)
        return self._lora_reg.file_sha(idx)

    # ------------------------------------------------------------------
    # tensor parallelism over the mesh
    # ------------------------------------------------------------------
    def _init_mesh(self, mesh):
        """Resolve the KV-pool placement for `mesh`: KV leaves are
        (*, rows, heads, head_dim)-shaped, so the heads axis (axis 2)
        shards over ``tp`` — each device holds its heads' slice of every
        slot/block, the layout heads-sharded attention consumes with zero
        collectives.  A single leaf whose head count does not divide tp
        stays replicated; if EVERY leaf ends up replicated, __init__
        raises (the no-silent-full-replication guard)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        tp = mesh.shape.get("tp", 1)
        self._mesh_tp = int(tp)

        def place_kv(leaf):
            if leaf.ndim >= 3 and tp > 1 and leaf.shape[2] % tp == 0:
                spec = P(*((None, None, "tp") + (None,) * (leaf.ndim - 3)))
            else:
                spec = P()
            return jax.device_put(leaf, NamedSharding(mesh, spec))

        self._kv_put = place_kv

    def _assert_kv_sharded(self, pools, what: str):
        """The loud no-silent-replication guard: a head count that does
        not divide tp would otherwise replicate the whole pool on every
        device (tp x the HBM) without a word.  Applied to the target AND
        draft pools."""
        if (self._kv_put is not None and self._mesh_tp > 1
                and all(k.sharding.is_fully_replicated
                        and v.sharding.is_fully_replicated
                        for k, v in pools)):
            raise InvalidArgumentError(
                f"tensor-parallel {what} fully replicated: no KV leaf's "
                f"head axis divides tp={self._mesh_tp} — fix the head "
                "count or the mesh (a replicated pool costs tp x the "
                "HBM and defeats the sharding)")

    def _shard_state(self, state):
        """Megatron layout via parallel.sharding.param_specs: column-
        parallel qkv/ffn_in, row-parallel proj/ffn_out, vocab-sharded
        embeddings; anything unmatched (norms, biases of row layers)
        replicates."""
        from jax.sharding import NamedSharding
        from ..parallel.sharding import param_specs
        specs = param_specs(
            {k: tuple(np.shape(v)) for k, v in state.items()},
            self.mesh, tensor_parallel=True)
        return {k: jax.device_put(v, NamedSharding(self.mesh, specs[k]))
                for k, v in state.items()}

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------
    def _lora_ctx(self, lora_args, aid):
        """Trace-time adapter context for program bodies: rebinds the
        positional lora program argument ((A,B) per key + scales) to the
        engine's static key tuple and scopes the (traced) adapter id so
        the forward hooks installed by `attach_serving_lora` see it.
        Entered per vmapped row in decode (aid is the row's scalar) and
        once per prefill (aid is the request's scalar)."""
        from ..lora.layers import adapter_context
        pairs, scales = lora_args
        return adapter_context(dict(zip(self._lora_keys, pairs)),
                               scales, aid)

    def _build_prefill(self, bucket: int):
        """One per-bucket prefill program.  On a speculative engine the
        SAME program additionally prefills the draft pool (one draft
        forward over the same padded ids, slot row written with the same
        full-range overwrite) — the first token still comes from the
        target's last-prompt-position logits, so greedy parity is
        identical with and without a draft.  On a LoRA engine the same
        program takes the factor stacks + a scalar adapter id as EXTRA
        dynamic inputs (adapter id 0 = base model) — still one program
        per bucket."""
        apply_fixed = self._apply
        model, draft = self.model, self.draft_model
        pool_len, dtype = self._pool_len, self._dtype
        dapply = self._dapply if draft is not None else None

        def write_slot(pools, kv, slot):
            new_pools = []
            for (kp, vp), (kc, vc) in zip(pools, kv):
                # full-range overwrite: bucket KV + zeros to pool_len, so
                # a recycled slot keeps no stale KV from its previous
                # tenant
                krow = jnp.zeros((1, pool_len) + kp.shape[2:], kp.dtype)
                vrow = jnp.zeros((1, pool_len) + vp.shape[2:], vp.dtype)
                krow = jax.lax.dynamic_update_slice(
                    krow, kc.astype(kp.dtype), (0, 0, 0, 0))
                vrow = jax.lax.dynamic_update_slice(
                    vrow, vc.astype(vp.dtype), (0, 0, 0, 0))
                new_pools.append((
                    jax.lax.dynamic_update_slice(kp, krow, (slot, 0, 0, 0)),
                    jax.lax.dynamic_update_slice(vp, vrow, (slot, 0, 0, 0))))
            return new_pools

        first_token = _first_token

        def count_trace():
            self._compiles["prefill"][bucket] += 1  # trace-count (host)
            stat_add("STAT_serving_compiles")

        if draft is None and self.lora is not None:
            def prefill(state, pools, lora, ids, slot, prompt_len, aid,
                        key, temp, top_k, top_p, greedy):
                count_trace()
                scratch = model.gen_fixed_cache(1, bucket, dtype)
                with self._lora_ctx(lora, aid):
                    logits, kv = apply_fixed(state, ids, scratch, 0)
                new_pools = write_slot(pools, kv, slot)
                tok, logp, finite = first_token(
                    logits, prompt_len, key, temp, top_k, top_p, greedy)
                return tok, logp, finite, new_pools

            name, donate = f"serving_prefill_b{bucket}", self._donate
        elif draft is None:
            def prefill(state, pools, ids, slot, prompt_len, key, temp,
                        top_k, top_p, greedy):
                count_trace()
                scratch = model.gen_fixed_cache(1, bucket, dtype)
                logits, kv = apply_fixed(state, ids, scratch, 0)
                new_pools = write_slot(pools, kv, slot)
                tok, logp, finite = first_token(
                    logits, prompt_len, key, temp, top_k, top_p, greedy)
                return tok, logp, finite, new_pools

            name, donate = f"serving_prefill_b{bucket}", self._donate
        else:
            def prefill(state, dstate, pools, dpools, ids, slot,
                        prompt_len, key, temp, top_k, top_p, greedy):
                count_trace()
                scratch = model.gen_fixed_cache(1, bucket, dtype)
                logits, kv = apply_fixed(state, ids, scratch, 0)
                new_pools = write_slot(pools, kv, slot)
                dscratch = draft.gen_fixed_cache(1, bucket, dtype)
                _, dkv = dapply(dstate, ids, dscratch, 0)
                new_dpools = write_slot(dpools, dkv, slot)
                tok, logp, finite = first_token(
                    logits, prompt_len, key, temp, top_k, top_p, greedy)
                return tok, logp, finite, new_pools, new_dpools

            name, donate = f"serving_prefill_spec_b{bucket}", (2, 3)

        from ..observability import track
        return track(name, jax.jit(prefill, donate_argnums=donate))

    def _build_decode(self):
        apply_fixed = self._apply
        poison_armed = self._poison_target is not None

        chunk = self.decode_chunk

        if self.lora is not None:
            # LoRA decode: per-slot adapter ids ride next to the sampling
            # params as one more dynamic input and each vmapped row
            # gathers its own factors — heterogeneous adapters batch in
            # ONE tick, one program (the PR-4 dynamic-sampling pattern)
            def decode(state, pools, lora, tokens, pos, aids, keys, temp,
                       top_k, top_p, greedy, poison):
                self._compiles["decode"] += 1  # trace-count (host)
                stat_add("STAT_serving_compiles")

                def one(carry, _):
                    tokens, pos, pools = carry

                    def row(tok, caches, p, aid):
                        c = [(k[None], v[None]) for (k, v) in caches]
                        with self._lora_ctx(lora, aid):
                            logits, new = apply_fixed(state,
                                                      tok[None, None], c, p)
                        return (logits[0, -1].astype(jnp.float32),
                                [(k[0], v[0]) for (k, v) in new])

                    last, pools = jax.vmap(row)(tokens, pools, pos, aids)
                    if poison_armed:
                        last = faults.poison_logits(last, poison)
                    finite = jnp.isfinite(last).all(axis=-1)
                    tok, logp = _sample_step(last, keys, pos, temp, top_k,
                                             top_p, greedy)
                    return (tok, pos + 1, pools), (tok, logp, finite)

                (tokens, pos, pools), (toks, logps, finites) = jax.lax.scan(
                    one, (tokens, pos, pools), None, length=chunk)
                return toks, logps, finites, tokens, pos, pools

            from ..observability import track
            return track("serving_decode",
                         jax.jit(decode, donate_argnums=self._donate))

        def decode(state, pools, tokens, pos, keys, temp, top_k, top_p,
                   greedy, poison):
            self._compiles["decode"] += 1  # trace-count (host side effect)
            stat_add("STAT_serving_compiles")

            def one(carry, _):
                tokens, pos, pools = carry

                def row(tok, caches, p):
                    c = [(k[None], v[None]) for (k, v) in caches]
                    logits, new = apply_fixed(state, tok[None, None], c, p)
                    return (logits[0, -1].astype(jnp.float32),
                            [(k[0], v[0]) for (k, v) in new])

                last, pools = jax.vmap(row)(tokens, pools, pos)
                if poison_armed:
                    last = faults.poison_logits(last, poison)
                finite = jnp.isfinite(last).all(axis=-1)
                tok, logp = _sample_step(last, keys, pos, temp, top_k,
                                         top_p, greedy)
                return (tok, pos + 1, pools), (tok, logp, finite)

            # chunked decode: `chunk` iterations per compiled call, the
            # per-call host+dispatch cost amortized across chunk * slots
            # tokens.  The final (tokens, pos) carry is exactly the next
            # call's input while batch membership is unchanged: the engine
            # feeds the device arrays straight back, so a steady-state
            # decode call uploads nothing.
            (tokens, pos, pools), (toks, logps, finites) = jax.lax.scan(
                one, (tokens, pos, pools), None, length=chunk)
            return toks, logps, finites, tokens, pos, pools

        from ..observability import track
        return track("serving_decode",
                     jax.jit(decode, donate_argnums=self._donate))

    # ------------------------------------------------------------------
    # speculative verify program (draft_model engines)
    # ------------------------------------------------------------------
    def _build_verify(self):
        """THE speculative tick: K sequential draft proposals, one batched
        target forward over [last_committed, d_1..d_K] (K+1 positions),
        in-program accept/reject + commit (generation.speculative).  One
        trace, ever: sampling params, spec on/off, poison and diverge are
        all dynamic per-slot/per-tick inputs."""
        from ..generation.speculative import (commit_speculative_greedy,
                                              commit_speculative_sampled)
        apply_fixed, dapply = self._apply, self._dapply
        poison_armed = self._poison_target is not None
        diverge_armed = self._diverge_every is not None
        k_spec = self.spec_tokens
        pad = self.pad_token_id

        def verify(state, dstate, pools, dpools, tokens, pos, keys, temp,
                   top_k, top_p, greedy, spec_on, poison, diverge):
            self._compiles["decode"] += 1  # trace-count (host side effect)
            stat_add("STAT_serving_compiles")

            def drow(tok, caches, p):
                c = [(kb[None], vb[None]) for (kb, vb) in caches]
                logits, new = dapply(dstate, tok[None, None], c, p)
                return (logits[0, -1].astype(jnp.float32),
                        [(kb[0], vb[0]) for (kb, vb) in new])

            def dstep(carry, i):
                cur, dp = carry
                dlast, dp = jax.vmap(drow)(cur, dp, pos + i)
                if diverge_armed:
                    dlast = faults.poison_draft_logits(dlast, diverge)
                dfin = jnp.isfinite(dlast).all(axis=-1)

                prop, q = _draft_propose(dlast, keys, pos, temp, top_k,
                                         top_p, greedy, i)
                return (prop, dp), (prop, q, dfin)

            # K+1 draft steps, not K: step K feeds the LAST proposal d_K
            # at pos+K so a fully-accepted tick leaves the draft pool
            # dense (d_K commits when everything accepts; without this
            # row every all-accept tick would punch a permanent zero-KV
            # hole the draft attends over forever, decaying the accept
            # rate cumulatively — worst exactly when the draft is good).
            # Step K's proposal/q outputs are discarded; on a rejection
            # its KV row is beyond the committed prefix and the next
            # tick overwrites it before any query can attend it.
            (_, dpools), (props, qs, dfins) = jax.lax.scan(
                dstep, (tokens, dpools), jnp.arange(k_spec + 1))
            props = props[:k_spec].T             # (S, K)
            qs = jnp.swapaxes(qs[:k_spec], 0, 1)  # (S, K, V)
            dfin = dfins.all(axis=0)             # (S,)

            # target scores all K proposals + the bonus position in ONE
            # forward of K+1 tokens per slot
            ids = jnp.concatenate([tokens[:, None], props], axis=1)

            def trow(row_ids, caches, p):
                c = [(kb[None], vb[None]) for (kb, vb) in caches]
                logits, new = apply_fixed(state, row_ids[None], c, p)
                return (logits[0].astype(jnp.float32),
                        [(kb[0], vb[0]) for (kb, vb) in new])

            tlog, pools = jax.vmap(trow)(ids, pools, pos)  # (S, K+1, V)
            if poison_armed:
                factor = jnp.where(poison, jnp.float32(float("nan")),
                                   jnp.float32(1.0))
                tlog = tlog * factor[:, None, None]
            # draft non-finiteness only matters for slots actually
            # speculating — a spec-off slot must never die for garbage in
            # a pool it does not consume
            finite = (jnp.isfinite(tlog).all(axis=(1, 2))
                      & (dfin | ~spec_on))

            def proc_all(t):
                flat = t.reshape(-1, t.shape[-1])

                def rep(a):
                    return jnp.repeat(a, k_spec + 1, axis=0)
                return process_logits_dynamic(
                    flat, rep(temp), rep(top_k), rep(top_p),
                    rep(greedy)).reshape(t.shape)

            plog = jax.lax.cond(jnp.all(greedy), lambda t: t, proc_all,
                                tlog)
            ops = (props, qs, plog, keys, pos, greedy, spec_on)
            out, count, accepted, last, logps = jax.lax.cond(
                jnp.all(greedy),
                lambda o: commit_speculative_greedy(*o, pad),
                lambda o: commit_speculative_sampled(*o, pad), ops)
            return (out, logps, finite, count, accepted, last, pos + count,
                    pools, dpools)

        from ..observability import track
        return track("serving_verify",
                     jax.jit(verify, donate_argnums=(2, 3)))

    # ------------------------------------------------------------------
    # paged programs (kv="paged"): same count, same contracts — blocks
    # gathered/scattered through per-slot tables instead of slot rows
    # ------------------------------------------------------------------
    def _build_prefill_paged(self, bucket: int):
        """Per-bucket prefill against the block pool: the prompt runs
        through the same bucket-sized scratch cache, then every block the
        slot's table covers for the bucket is overwritten END-TO-END
        (prompt KV + zeros to the block boundary) — scrub-on-recycle for
        prompt blocks is the overwrite itself.  Sentinel table entries
        (warmup) drop the write."""
        apply_fixed = self._apply
        model, draft = self.model, self.draft_model
        dtype = self._dtype
        bs = self.block_size
        nb_b = -(-bucket // bs)
        dapply = self._dapply if draft is not None else None

        def write_blocks(pools, kv, table):
            ids = table[:nb_b]
            new_pools = []
            for (kp, vp), (kc, vc) in zip(pools, kv):
                def as_blocks(chunk, pool):
                    rows = chunk[0].astype(pool.dtype)      # (bucket, ...)
                    padn = nb_b * bs - bucket
                    if padn:
                        rows = jnp.concatenate(
                            [rows, jnp.zeros((padn,) + rows.shape[1:],
                                             pool.dtype)])
                    return rows.reshape((nb_b, bs) + rows.shape[1:])
                new_pools.append(
                    (kp.at[ids].set(as_blocks(kc, kp), mode="drop"),
                     vp.at[ids].set(as_blocks(vc, vp), mode="drop")))
            return new_pools

        def count_trace():
            self._compiles["prefill"][bucket] += 1  # trace-count (host)
            stat_add("STAT_serving_compiles")

        if draft is None and self.lora is not None:
            def prefill(state, pools, lora, ids, table, prompt_len, aid,
                        key, temp, top_k, top_p, greedy):
                count_trace()
                scratch = model.gen_fixed_cache(1, bucket, dtype)
                with self._lora_ctx(lora, aid):
                    logits, kv = apply_fixed(state, ids, scratch, 0)
                new_pools = write_blocks(pools, kv, table)
                tok, logp, finite = _first_token(
                    logits, prompt_len, key, temp, top_k, top_p, greedy)
                return tok, logp, finite, new_pools

            name, donate = f"serving_prefill_b{bucket}", (1,)
        elif draft is None:
            def prefill(state, pools, ids, table, prompt_len, key, temp,
                        top_k, top_p, greedy):
                count_trace()
                scratch = model.gen_fixed_cache(1, bucket, dtype)
                logits, kv = apply_fixed(state, ids, scratch, 0)
                new_pools = write_blocks(pools, kv, table)
                tok, logp, finite = _first_token(
                    logits, prompt_len, key, temp, top_k, top_p, greedy)
                return tok, logp, finite, new_pools

            name, donate = f"serving_prefill_b{bucket}", (1,)
        else:
            def prefill(state, dstate, pools, dpools, ids, table,
                        prompt_len, key, temp, top_k, top_p, greedy):
                count_trace()
                scratch = model.gen_fixed_cache(1, bucket, dtype)
                logits, kv = apply_fixed(state, ids, scratch, 0)
                new_pools = write_blocks(pools, kv, table)
                dscratch = draft.gen_fixed_cache(1, bucket, dtype)
                _, dkv = dapply(dstate, ids, dscratch, 0)
                new_dpools = write_blocks(dpools, dkv, table)
                tok, logp, finite = _first_token(
                    logits, prompt_len, key, temp, top_k, top_p, greedy)
                return tok, logp, finite, new_pools, new_dpools

            name, donate = f"serving_prefill_spec_b{bucket}", (2, 3)

        from ..observability import track
        return track(name, jax.jit(prefill, donate_argnums=donate))

    def _build_prefill_cached(self, bucket: int):
        """Per-bucket prefill for prefix-cache engines: the slot's table
        is gathered into its contiguous KV view (exactly like decode —
        the cached prefix blocks already mapped in by admission supply
        rows [0, cached_len)), the prompt's uncached SUFFIX runs through
        the model at the dynamic offset `cached_len` (same traced-scalar
        position the decode/verify programs use), and only the suffix
        rows scatter back through the table.  cached_len=0 IS the cold
        path: the gathered view is all-fresh blocks and the full bucket
        computes — so cold and warm requests share one program per
        bucket and the compile bound stays len(buckets)+1.  Buckets are
        chosen by SUFFIX length, so a warm prefix pays a near-zero
        prefill.  Suffix writes start at cached_len — a block boundary
        for non-COW admissions, so shared blocks are never entered; a
        clamped window near the pool's end re-writes gathered rows
        value-identically, and any block it scrubs lies entirely inside
        the window (fully rewritten), preserving shared content
        bit-exactly."""
        apply_fixed = self._apply
        write_rows = _paged_row_writer(self.block_size,
                                       self.kv_pool.num_blocks,
                                       self._pool_len)
        from ..ops.paged_attention import gather_block_rows

        def count_trace():
            self._compiles["prefill"][bucket] += 1  # trace-count (host)
            stat_add("STAT_serving_compiles")

        def prefill(state, pools, ids, table, prompt_len, cached_len,
                    key, temp, top_k, top_p, greedy):
            count_trace()
            ctx = [(gather_block_rows(kp, table)[None],
                    gather_block_rows(vp, table)[None])
                   for kp, vp in pools]
            logits, kv = apply_fixed(state, ids, ctx, cached_len)
            total = kv[0][0].shape[1]
            start = _window_start(cached_len, bucket, total)
            rows = [
                (jax.lax.dynamic_slice_in_dim(kc[0], start, bucket)[None],
                 jax.lax.dynamic_slice_in_dim(vc[0], start, bucket)[None])
                for kc, vc in kv]
            new_pools = write_rows(pools, table[None], start[None],
                                   rows, jnp.ones((1,), bool), bucket)
            tok, logp, finite = _first_token_at(
                logits, prompt_len - 1 - cached_len, prompt_len - 1, key,
                temp, top_k, top_p, greedy)
            return tok, logp, finite, new_pools

        from ..observability import track
        return track(f"serving_prefill_cached_b{bucket}",
                     jax.jit(prefill, donate_argnums=(1,)))

    def _build_decode_paged(self):
        """THE paged decode step: gather every slot's block table into its
        contiguous KV view ONCE per compiled call (value-identical to the
        fixed slot row — streams stay bit-identical), run the whole
        decode chunk against the gathered view exactly as the fixed step
        runs against its pool rows, then scatter the chunk's freshly
        written rows back through the tables in one pass (entering blocks
        zeroed first).  One gather + one scatter per call amortizes the
        indirection across chunk * slots tokens.  Sampling, the
        all-greedy fast path, chunking and fault branches are the fixed
        decode step verbatim."""
        apply_fixed = self._apply
        poison_armed = self._poison_target is not None
        chunk = self.decode_chunk
        write_rows = _paged_row_writer(self.block_size,
                                       self.kv_pool.num_blocks,
                                       self._pool_len)

        gather_ctx = _gather_ctx

        if self.lora is not None:
            def decode(state, pools, lora, tables, active, tokens, pos,
                       aids, keys, temp, top_k, top_p, greedy, poison):
                self._compiles["decode"] += 1  # trace-count (host)
                stat_add("STAT_serving_compiles")
                ctx = [(gather_ctx(kp, tables), gather_ctx(vp, tables))
                       for (kp, vp) in pools]
                pos0 = pos

                def one(carry, _):
                    tokens, pos, ctx = carry

                    def row(tok, caches, p, aid):
                        c = [(k[None], v[None]) for (k, v) in caches]
                        with self._lora_ctx(lora, aid):
                            logits, new = apply_fixed(state,
                                                      tok[None, None], c, p)
                        return (logits[0, -1].astype(jnp.float32),
                                [(k[0], v[0]) for (k, v) in new])

                    last, ctx = jax.vmap(row)(tokens, ctx, pos, aids)
                    if poison_armed:
                        last = faults.poison_logits(last, poison)
                    finite = jnp.isfinite(last).all(axis=-1)
                    tok, logp = _sample_step(last, keys, pos, temp, top_k,
                                             top_p, greedy)
                    return (tok, pos + 1, ctx), (tok, logp, finite)

                (tokens, pos, ctx), (toks, logps, finites) = jax.lax.scan(
                    one, (tokens, pos0, ctx), None, length=chunk)
                start = _window_start(pos0, chunk, ctx[0][0].shape[1])
                pools = write_rows(pools, tables, start,
                                   _extract_rows(ctx, start, chunk),
                                   active, chunk)
                return toks, logps, finites, tokens, pos, pools

            from ..observability import track
            return track("serving_decode",
                         jax.jit(decode, donate_argnums=(1,)))

        def decode(state, pools, tables, active, tokens, pos, keys, temp,
                   top_k, top_p, greedy, poison):
            self._compiles["decode"] += 1  # trace-count (host side effect)
            stat_add("STAT_serving_compiles")
            ctx = [(gather_ctx(kp, tables), gather_ctx(vp, tables))
                   for (kp, vp) in pools]
            pos0 = pos

            def one(carry, _):
                tokens, pos, ctx = carry

                def row(tok, caches, p):
                    c = [(k[None], v[None]) for (k, v) in caches]
                    logits, new = apply_fixed(state, tok[None, None], c, p)
                    return (logits[0, -1].astype(jnp.float32),
                            [(k[0], v[0]) for (k, v) in new])

                last, ctx = jax.vmap(row)(tokens, ctx, pos)
                if poison_armed:
                    last = faults.poison_logits(last, poison)
                finite = jnp.isfinite(last).all(axis=-1)
                tok, logp = _sample_step(last, keys, pos, temp, top_k,
                                         top_p, greedy)
                return (tok, pos + 1, ctx), (tok, logp, finite)

            (tokens, pos, ctx), (toks, logps, finites) = jax.lax.scan(
                one, (tokens, pos0, ctx), None, length=chunk)
            # one scatter publishes the chunk's written rows back into
            # the block pool; near the end of the view the window clamps
            # and harmlessly re-writes a few already-published rows
            start = _window_start(pos0, chunk, ctx[0][0].shape[1])
            pools = write_rows(pools, tables, start,
                               _extract_rows(ctx, start, chunk), active,
                               chunk)
            return toks, logps, finites, tokens, pos, pools

        from ..observability import track
        return track("serving_decode",
                     jax.jit(decode, donate_argnums=(1,)))

    def _build_verify_paged(self):
        """The speculative tick over the block pool: draft and target
        contexts are gathered from the per-slot tables ONCE per call, the
        draft proposal scan and batched target verify run against the
        gathered views exactly as the fixed verify runs against its pool
        rows, and each side's freshly written rows scatter back in one
        pass — the commit math is the fixed verify verbatim.  The draft
        pool pages with the SAME tables."""
        from ..generation.speculative import (commit_speculative_greedy,
                                              commit_speculative_sampled)
        apply_fixed, dapply = self._apply, self._dapply
        poison_armed = self._poison_target is not None
        diverge_armed = self._diverge_every is not None
        k_spec = self.spec_tokens
        pad = self.pad_token_id
        write_rows = _paged_row_writer(self.block_size,
                                       self.kv_pool.num_blocks,
                                       self._pool_len)

        gather_ctx = _gather_ctx
        extract_rows = _extract_rows

        def verify(state, dstate, pools, dpools, tables, active, tokens,
                   pos, keys, temp, top_k, top_p, greedy, spec_on, poison,
                   diverge):
            self._compiles["decode"] += 1  # trace-count (host side effect)
            stat_add("STAT_serving_compiles")
            dctx = [(gather_ctx(kb, tables), gather_ctx(vb, tables))
                    for (kb, vb) in dpools]

            def dstep(carry, i):
                cur, dp = carry

                def drow(tok, caches, p):
                    c = [(kb[None], vb[None]) for (kb, vb) in caches]
                    logits, new = dapply(dstate, tok[None, None], c, p)
                    return (logits[0, -1].astype(jnp.float32),
                            [(kb[0], vb[0]) for (kb, vb) in new])

                dlast, dp = jax.vmap(drow)(cur, dp, pos + i)
                if diverge_armed:
                    dlast = faults.poison_draft_logits(dlast, diverge)
                dfin = jnp.isfinite(dlast).all(axis=-1)

                prop, q = _draft_propose(dlast, keys, pos, temp, top_k,
                                         top_p, greedy, i)
                return (prop, dp), (prop, q, dfin)

            # K+1 draft steps for the same density reason as the fixed
            # verify: step K feeds d_K at pos+K so an all-accept tick
            # leaves the draft blocks dense
            (_, dctx), (props, qs, dfins) = jax.lax.scan(
                dstep, (tokens, dctx), jnp.arange(k_spec + 1))
            # window clamped at the view's end (re-writes are idempotent)
            start = _window_start(pos, k_spec + 1, dctx[0][0].shape[1])
            dpools = write_rows(dpools, tables, start,
                                extract_rows(dctx, start, k_spec + 1),
                                active, k_spec + 1)
            props = props[:k_spec].T             # (S, K)
            qs = jnp.swapaxes(qs[:k_spec], 0, 1)  # (S, K, V)
            dfin = dfins.all(axis=0)             # (S,)

            ids = jnp.concatenate([tokens[:, None], props], axis=1)
            tctx = [(gather_ctx(kb, tables), gather_ctx(vb, tables))
                    for (kb, vb) in pools]

            def trow(row_ids, caches, p):
                c = [(kb[None], vb[None]) for (kb, vb) in caches]
                logits, new = apply_fixed(state, row_ids[None], c, p)
                return (logits[0].astype(jnp.float32),
                        [(kb[0], vb[0]) for (kb, vb) in new])

            tlog, tctx = jax.vmap(trow)(ids, tctx, pos)  # (S, K+1, V)
            pools = write_rows(pools, tables, start,
                               extract_rows(tctx, start, k_spec + 1),
                               active, k_spec + 1)
            if poison_armed:
                factor = jnp.where(poison, jnp.float32(float("nan")),
                                   jnp.float32(1.0))
                tlog = tlog * factor[:, None, None]
            finite = (jnp.isfinite(tlog).all(axis=(1, 2))
                      & (dfin | ~spec_on))

            def proc_all(t):
                flat = t.reshape(-1, t.shape[-1])

                def rep(a):
                    return jnp.repeat(a, k_spec + 1, axis=0)
                return process_logits_dynamic(
                    flat, rep(temp), rep(top_k), rep(top_p),
                    rep(greedy)).reshape(t.shape)

            plog = jax.lax.cond(jnp.all(greedy), lambda t: t, proc_all,
                                tlog)
            ops = (props, qs, plog, keys, pos, greedy, spec_on)
            out, count, accepted, last, logps = jax.lax.cond(
                jnp.all(greedy),
                lambda o: commit_speculative_greedy(*o, pad),
                lambda o: commit_speculative_sampled(*o, pad), ops)
            return (out, logps, finite, count, accepted, last, pos + count,
                    pools, dpools)

        from ..observability import track
        return track("serving_verify",
                     jax.jit(verify, donate_argnums=(2, 3)))

    # ------------------------------------------------------------------
    # submission
    # ------------------------------------------------------------------
    def make_request(self, prompt, max_new_tokens: int,
                     decode_strategy: str = "greedy_search", temperature=1.0,
                     top_k=0, top_p=1.0, eos_token_id: Optional[int] = None,
                     seed: Optional[int] = None,
                     deadline: Optional[float] = None, priority: int = 0,
                     tenant: Optional[str] = None,
                     spec: Optional[bool] = None,
                     session: Optional[str] = None,
                     resubmit: bool = False,
                     adapter: Optional[str] = None):
        """Validate + build one (Request, Response) pair WITHOUT enqueuing
        it — the gateway's admission layer owns its own lanes and hands
        requests to `try_admit` directly.  Raises InvalidArgumentError for
        a prompt/budget the engine can never serve.

        `session` is the fleet router's affinity key; `resubmit=True`
        (greedy-only) opts into re-prefill-from-prompt recovery when the
        serving replica crashes and the run's KV snapshot dies with it —
        greedy decode is deterministic in the prompt alone, so the
        replayed stream is bit-identical and the fleet forwards only the
        not-yet-delivered suffix.  A sampled resubmit is rejected here,
        typed: a sampled replay is only reproducible through the engine's
        internal per-position key-fold schedule, which is not a contract —
        greedy-only keeps "the delivered prefix never changes" a property
        of the model, not of an implementation detail."""
        if self._closed:
            raise UnavailableError("serving engine is closed")
        if self._dead is not None:
            raise UnavailableError(
                f"serving engine loop died: {self._dead!r}")
        if decode_strategy not in ("greedy_search", "sampling"):
            raise InvalidArgumentError(
                f"serving supports 'greedy_search' or 'sampling', got "
                f"{decode_strategy!r} (beam search holds k hypotheses per "
                "slot — use generation.generate)")
        # spec=None -> the engine default: speculate whenever a draft
        # model is configured.  Explicit spec=True on a draftless engine
        # is a caller error, not a silent downgrade.
        if spec is None:
            spec = self.draft_model is not None
        elif spec and self.draft_model is None:
            raise InvalidArgumentError(
                "spec=True requires the engine to be built with a "
                "draft_model (speculative decoding)")
        if resubmit and decode_strategy != "greedy_search":
            raise InvalidArgumentError(
                "resubmit=True (re-prefill-from-prompt crash recovery) is "
                "greedy-only: a replayed sampled stream is not covered by "
                "any engine contract — drop resubmit or use greedy_search")
        # LoRA: reject unknown adapters NOW, typed — a consumer must
        # never hang on an adapter that was never (or is no longer)
        # loaded.  The slot is pinned later, at admission; if the
        # adapter is evicted while the request queues, admission fails
        # the request with the same typed error.
        if adapter is not None and self.lora is None:
            stat_add("STAT_serving_rejects")
            raise InvalidArgumentError(
                f"adapter={adapter!r} requires the engine to be built "
                "with lora=LoRAConfig(...)")
        if self.lora is not None and adapter is not None:
            try:
                self._lora_reg.resolve(adapter)
            except Exception:
                stat_add("STAT_serving_rejects")
                stat_add("STAT_lora_rejects")
                raise
        with self._submit_lock:
            rid = self._rid
            self._rid += 1
        req = Request(rid, prompt, max_new_tokens,
                      greedy=decode_strategy == "greedy_search",
                      temperature=temperature, top_k=top_k, top_p=top_p,
                      eos_token_id=eos_token_id,
                      seed=seed if seed is not None else rid,
                      deadline=deadline, priority=priority, tenant=tenant,
                      spec=bool(spec), session=session,
                      resubmit=resubmit, adapter=adapter)
        plen = req.prompt.shape[0]
        if plen > self.buckets[-1]:
            stat_add("STAT_serving_rejects")
            raise InvalidArgumentError(
                f"prompt length {plen} exceeds the largest prefill bucket "
                f"{self.buckets[-1]} (engine max_len={self.max_len})")
        if plen + req.max_new_tokens > self.max_len:
            stat_add("STAT_serving_rejects")
            raise InvalidArgumentError(
                f"prompt ({plen}) + max_new_tokens ({req.max_new_tokens}) "
                f"exceeds the engine's max_len {self.max_len}")
        if self.kv == "paged":
            # a request whose full budget can never fit the pool EVEN
            # ALONE is a caller error, not backpressure (with the default
            # num_blocks — fixed-capacity parity — this cannot trip).
            # The static need is the LARGER of the prefill bucket (what
            # admission actually allocates — plen rounds UP to it) and
            # the full row budget, so anything accepted here is
            # admittable by the gate once the pool drains.
            need = self._static_blocks_needed(req)
            if need > self.kv_pool.num_blocks:
                stat_add("STAT_serving_rejects")
                raise InvalidArgumentError(
                    f"request needs {need} KV blocks but the pool holds "
                    f"{self.kv_pool.num_blocks} "
                    f"(block_size={self.block_size}); raise num_blocks or "
                    "shrink the request")
        if self._poison_target is not None and rid == self._poison_target:
            req.poison = True
        resp = Response(req)
        stat_add("STAT_serving_requests")
        return req, resp

    def submit(self, prompt, max_new_tokens: int,
               decode_strategy: str = "greedy_search", temperature=1.0,
               top_k=0, top_p=1.0, eos_token_id: Optional[int] = None,
               seed: Optional[int] = None, deadline: Optional[float] = None,
               block: bool = False, timeout: Optional[float] = None,
               spec: Optional[bool] = None,
               tenant: Optional[str] = None,
               adapter: Optional[str] = None) -> Response:
        """Enqueue one request; returns its streaming Response.

        `tenant` scopes prefix-cache sharing (the gateway sets it from
        its auth context; direct engine callers may pass it for the
        same isolation).  Raises InvalidArgumentError for a
        prompt/budget the engine can never serve (prompt longer than
        the largest prefill bucket, or prompt + max_new_tokens past
        max_len), QueueFullError at max_queue_depth (backpressure).
        """
        req, resp = self.make_request(
            prompt, max_new_tokens, decode_strategy=decode_strategy,
            temperature=temperature, top_k=top_k, top_p=top_p,
            eos_token_id=eos_token_id, seed=seed, deadline=deadline,
            spec=spec, tenant=tenant, adapter=adapter)
        self.scheduler.submit(req, resp, block=block, timeout=timeout)
        self._work.set()
        return resp

    # ------------------------------------------------------------------
    # the engine loop
    # ------------------------------------------------------------------
    def step(self) -> bool:
        """One engine iteration: sweep deadlines/cancels, admit waiting
        requests into free slots (one bucketed prefill each), then advance
        every occupied slot one token with the single decode program.
        Returns whether any work was done."""
        did = False
        self._sweep()
        dropped = self.scheduler.sweep_pending(
            drop=((self._queued_never_fits, self._queued_exhausted_exc)
                  if self.kv == "paged" else None))
        if dropped:
            with self._m_lock:
                self._errored += dropped
        gate = None
        if self.kv == "paged":
            did = self._sweep_oom_paused() or did
            # OOM-parked runs hold progress and arrived earlier: they get
            # first claim on freed slots + blocks, before new admissions
            did = self._restore_oom_paused() or did
            gate = self._admission_gate
        while True:
            adm = self.scheduler.next_admission(gate=gate)
            if adm is None:
                break
            self._admit(*adm)
            did = True
        if self._slots:
            self._decode_step()
            did = True
        return did

    def _static_blocks_needed(self, req: Request) -> int:
        """Blocks the request is GUARANTEED to need: its prefill bucket
        (admission allocates bucket rows, plen rounds up) or the rows
        the runtime will actually BACK (`_rows_needed` — the ensure
        target; chunk/spec tail writes past it drop via the sentinel and
        never allocate), whichever is larger.  Using anything bigger
        here would spuriously reject requests the engine can serve."""
        return max(
            self.kv_pool.blocks_for(self._bucket_for(req.prompt.shape[0])),
            self.kv_pool.blocks_for(self._rows_needed(req)))

    def _queued_never_fits(self, req: Request) -> bool:
        """True when the queued request's prefill bucket cannot fit the
        pool even ALONE under the LIVE capacity (the fault cap) — it can
        never admit, so waiting is a hang, not backpressure; the sweep
        fails it with the typed KVPoolExhaustedError."""
        return (self.kv_pool.blocks_for(
                    self._bucket_for(req.prompt.shape[0]))
                > self.kv_pool.capacity())

    def _queued_exhausted_exc(self, req: Request) -> BaseException:
        # runs INSIDE the scheduler lock (sweep_pending's drop callback):
        # must not take _m_lock — metrics() holds _m_lock while reading
        # scheduler depths, so that order would be an ABBA deadlock; the
        # errored count is applied by step() from sweep's return value
        stat_add("STAT_serving_kv_exhausted")
        return KVPoolExhaustedError(
            f"request {req.id}: prompt bucket needs "
            f"{self.kv_pool.blocks_for(self._bucket_for(req.prompt.shape[0]))} "
            f"KV blocks but only {self.kv_pool.capacity()} are usable "
            "(PDTPU_FAULT_KV_EXHAUST or an undersized pool) — the "
            "request can never admit")

    def _admission_gate(self, req: Request) -> bool:
        """Paged admission is block-aware backpressure: a request stays
        queued until the pool can hold its prompt's bucket (the decode
        growth is handled per tick by ensure/preempt).  Runs parked on
        pool pressure hold FIRST claim on freed capacity — their resume
        blocks are RESERVED, and new work only admits from the surplus
        (work-conserving: a small request may still fill an idle slot,
        but never at the price of starving a parked run).  With a prefix
        cache the gate counts reusable blocks as free-for-this-request:
        a warm prefix only charges the pool for its uncached suffix."""
        reserve = (self.kv_pool.blocks_for(self._oom_paused[0].pos)
                   if self._oom_paused else 0)
        if self.prefix_cache is not None:
            plan = self._cached_plan(req)
            return self.kv_pool.free_blocks() >= plan.new_live + reserve
        bucket = self._bucket_for(req.prompt.shape[0])
        return (self.kv_pool.free_blocks()
                >= self.kv_pool.blocks_for(bucket) + reserve)

    # ------------------------------------------------------------------
    # prefix cache: share policy + admission planning
    # ------------------------------------------------------------------
    def _share_key(self, req: Request) -> str:
        """The cache partition this request may share KV with.  Default:
        tenant-private (anonymous requests form one 'default' group);
        gateway tenancy maps tenants into explicit share groups
        (TenantConfig.kv_share_group); an engine-level `share_policy`
        callable overrides both."""
        if self._share_policy is not None:
            return str(self._share_policy(req))
        tenant = req.tenant if req.tenant is not None else "default"
        return self._share_groups.get(tenant, tenant)

    def set_share_groups(self, groups: Dict[str, str]):
        """Tenant -> share-group mapping (gateway wiring)."""
        self._share_groups = dict(groups)

    def _cached_plan(self, req: Request, record: bool = False):
        """Host-side warm-admission plan: the longest usable cached
        chain, the dynamic `cached_len` the prefill program gets, the
        SUFFIX bucket, and the block cost.  Two invariants are enforced
        here rather than in-program: (1) `cached_len + bucket` never
        exceeds the gathered view width, so the model's write offset
        never clamps (a clamped write would land suffix KV over cached
        rows) — chains trim from the tail until it holds; (2) a fully
        block-aligned cached prompt recomputes its LAST token inside the
        final cached block, which is therefore COW'd to a private copy
        so shared blocks are never written."""
        plen = int(req.prompt.shape[0])
        bs = self.block_size
        view_rows = self.kv_pool.max_blocks_per_slot * bs
        chain = self.prefix_cache.match(self._share_key(req), req.prompt,
                                        record=record)

        def shape(chain):
            matched = len(chain) * bs
            cow = bool(chain) and matched == plen
            cached_len = plen - 1 if cow else matched
            return matched, cow, cached_len, self._bucket_for(
                plen - cached_len)

        matched, cow, cached_len, bucket = shape(chain)
        while chain and cached_len + bucket > view_rows:
            chain = chain[:-1]
            matched, cow, cached_len, bucket = shape(chain)
        total_blocks = min(self.kv_pool.blocks_for(cached_len + bucket),
                           self.kv_pool.max_blocks_per_slot)
        revive = sum(1 for b in chain
                     if self.kv_pool.block_ref(b) == 0)
        new_live = (max(0, total_blocks - len(chain))
                    + (1 if cow else 0) + revive)
        return _CachedPlan(chain, matched, cow, cached_len, bucket,
                           new_live)

    def _sweep(self):
        for slot in list(self._slots):
            run = self._slots[slot]
            if run.resp.cancelled:
                stat_add("STAT_serving_cancelled")
                run.resp._fail(RequestCancelled(
                    f"request {run.req.id} cancelled mid-decode"))
                self._release(slot)
            elif run.req.deadline is not None and run.req.deadline.expired():
                stat_add("STAT_serving_deadline_expired")
                run.resp._fail(DeadlineExceededError(
                    f"request {run.req.id} deadline "
                    f"({run.req.deadline.seconds}s) expired mid-decode"))
                self._release(slot)

    def _release(self, slot: int):
        run = self._slots.pop(slot, None)
        self.scheduler.release(slot)
        if self.kv == "paged":
            # blocks return to the free-list; their content is scrubbed
            # in-program the moment they are re-served (kv_pool docstring)
            self.kv_pool.free(slot)
        if (self._lora_reg is not None and run is not None
                and run.aid):
            # unpin: a ref-0 adapter becomes evictable again
            self._lora_reg.release(run.aid)
        self._batch_dirty = True

    def _bucket_for(self, plen: int) -> int:
        for b in self.buckets:
            if b >= plen:
                return b
        raise InvalidArgumentError(f"no bucket fits prompt length {plen}")

    def _request_key(self, req: Request) -> np.ndarray:
        # any well-mixed bits work as a raw PRNG key; host-only derivation
        # keeps submit()/admission free of device round-trips
        rs = np.random.RandomState(np.uint32(req.seed))
        return rs.randint(0, 2 ** 32, size=self._key_width, dtype=np.uint64
                          ).astype(np.uint32)

    def _admit(self, req: Request, resp: Response, slot: int):
        if self.prefix_cache is not None:
            return self._admit_prefix(req, resp, slot)
        span = self._span("serving_prefill")
        try:
            plen = req.prompt.shape[0]
            bucket = self._bucket_for(plen)
            aid = 0
            if self.lora is not None:
                # resolve + PIN the adapter for the life of the slot (the
                # registry cannot evict a pinned adapter).  The request
                # was validated at make_request, but the adapter may have
                # been evicted while it queued — typed terminal failure,
                # never a hung consumer.
                try:
                    aid = self._lora_reg.acquire(req.adapter)
                except Exception as e:
                    stat_add("STAT_lora_rejects")
                    with self._m_lock:
                        self._errored += 1
                    resp._fail(e)
                    self.scheduler.release(slot)
                    return
            if self.kv == "paged":
                # claim the prompt's blocks; only reachable without them
                # when PDTPU_FAULT_KV_EXHAUST moved the cap between the
                # admission gate and here — typed terminal, never a hang
                if not self.kv_pool.alloc(slot, bucket):
                    stat_add("STAT_serving_kv_exhausted")
                    with self._m_lock:
                        self._errored += 1
                    resp._fail(KVPoolExhaustedError(
                        f"request {req.id}: KV block pool exhausted at "
                        f"admission ({self.kv_pool.free_blocks()} free of "
                        f"{self.kv_pool.capacity()} usable)"))
                    self.scheduler.release(slot)
                    if self._lora_reg is not None and aid:
                        self._lora_reg.release(aid)
                    return
                slot_arg = jnp.asarray(self.kv_pool.table_array(slot))
            else:
                slot_arg = jnp.int32(slot)
            ids = np.full((1, bucket), self.pad_token_id, np.int32)
            ids[0, :plen] = req.prompt
            key = self._request_key(req)
            if self.draft_model is not None:
                (tok, logp, finite, self._pools,
                 self._draft_pools) = self._prefill_fns[bucket](
                    self._state, self._dstate, self._pools,
                    self._draft_pools, jnp.asarray(ids), slot_arg,
                    jnp.int32(plen), jnp.asarray(key),
                    jnp.float32(req.temperature), jnp.int32(req.top_k),
                    jnp.float32(req.top_p), jnp.asarray(req.greedy))
            elif self.lora is not None:
                # the adapter id is an ordinary dynamic input: a new
                # adapter NEVER means a new program
                tok, logp, finite, self._pools = self._prefill_fns[bucket](
                    self._state, self._pools, self._lora_reg.device_args(),
                    jnp.asarray(ids), slot_arg, jnp.int32(plen),
                    jnp.int32(aid), jnp.asarray(key),
                    jnp.float32(req.temperature), jnp.int32(req.top_k),
                    jnp.float32(req.top_p), jnp.asarray(req.greedy))
            else:
                tok, logp, finite, self._pools = self._prefill_fns[bucket](
                    self._state, self._pools, jnp.asarray(ids),
                    slot_arg, jnp.int32(plen), jnp.asarray(key),
                    jnp.float32(req.temperature), jnp.int32(req.top_k),
                    jnp.float32(req.top_p), jnp.asarray(req.greedy))
            stat_add("STAT_serving_prefills")
            if not bool(finite):
                # the run is not in _slots yet — _release won't see the
                # pin, drop it here
                if self._lora_reg is not None and aid:
                    self._lora_reg.release(aid)
                self._fail_slot(slot, resp, "prefill")
                return
            tok = int(tok)
            run = _SlotRun(req, resp, pos=plen, first_token=tok, key=key,
                           aid=aid)
            self._slots[slot] = run
            self._batch_dirty = True
            self._emit(run, tok, float(logp))
            stat_add("STAT_serving_tokens")
            self._maybe_finish(slot, run, tok)
        finally:
            if span is not None:
                span.__exit__(None, None, None)

    def _admit_prefix(self, req: Request, resp: Response, slot: int):
        """Warm-path admission: adopt the longest cached prefix chain
        into the slot's table, COW the final block when the whole prompt
        is cached, and prefill ONLY the uncached suffix (per-slot
        dynamic `cached_len` into the same per-bucket program family —
        `cached_len == 0` IS the cold path, so a miss costs nothing
        extra and the compile bound is unchanged)."""
        span = self._span("serving_prefill")
        try:
            plen = int(req.prompt.shape[0])
            share_key = self._share_key(req)
            plan = self._cached_plan(req, record=True)

            def exhausted(stage):
                stat_add("STAT_serving_kv_exhausted")
                with self._m_lock:
                    self._errored += 1
                resp._fail(KVPoolExhaustedError(
                    f"request {req.id}: KV block pool exhausted at "
                    f"admission/{stage} ({self.kv_pool.free_blocks()} "
                    f"free of {self.kv_pool.capacity()} usable)"))
                self.scheduler.release(slot)

            if plan.chain and not self.kv_pool.adopt(slot, plan.chain):
                return exhausted("adopt")
            if plan.cow:
                pair = self.kv_pool.cow_last(slot)
                if pair is None:
                    self.kv_pool.free(slot)
                    return exhausted("cow")
                src, dst = pair
                # device copy BEFORE any program can write the new block
                self._pools = self._cow_fn(self._pools, jnp.int32(src),
                                           jnp.int32(dst))
                self.prefix_cache.note_cow()
            if not self.kv_pool.ensure(slot, plan.cached_len + plan.bucket):
                self.kv_pool.free(slot)
                return exhausted("suffix")
            slot_arg = jnp.asarray(self.kv_pool.table_array(slot))
            suffix = plen - plan.cached_len
            ids = np.full((1, plan.bucket), self.pad_token_id, np.int32)
            ids[0, :suffix] = req.prompt[plan.cached_len:]
            key = self._request_key(req)
            tok, logp, finite, self._pools = self._prefill_fns[plan.bucket](
                self._state, self._pools, jnp.asarray(ids), slot_arg,
                jnp.int32(plen), jnp.int32(plan.cached_len),
                jnp.asarray(key), jnp.float32(req.temperature),
                jnp.int32(req.top_k), jnp.float32(req.top_p),
                jnp.asarray(req.greedy))
            stat_add("STAT_serving_prefills")
            if not bool(finite):
                self._fail_slot(slot, resp, "prefill")
                return
            self.prefix_cache.insert(
                share_key, req.prompt,
                self.kv_pool.block_ids(slot)[:plen // self.block_size])
            tok = int(tok)
            run = _SlotRun(req, resp, pos=plen, first_token=tok, key=key)
            self._slots[slot] = run
            self._batch_dirty = True
            self._emit(run, tok, float(logp))
            stat_add("STAT_serving_tokens")
            self._maybe_finish(slot, run, tok)
        finally:
            if span is not None:
                span.__exit__(None, None, None)

    # ------------------------------------------------------------------
    # gateway admission: direct placement, preemption, restore
    # ------------------------------------------------------------------
    def try_admit(self, req: Request, resp: Response) -> bool:
        """Place the request into a free slot NOW (one bucketed prefill),
        bypassing the FIFO queue — the gateway's admission path, which
        keeps its own priority lanes and only hands a request over once a
        slot is actually available.  Returns False when every slot is
        occupied (or, paged, when the block pool cannot hold the prompt —
        the gateway retries as the pool drains).  Must be called from the
        thread driving step() (the engine loop is single-threaded by
        design)."""
        if self.kv == "paged" and not self._admission_gate(req):
            return False
        slot = self.scheduler.acquire(req, resp)
        if slot is None:
            return False
        self._admit(req, resp, slot)
        return True

    def preempt_slot(self, slot: int) -> PreemptedRun:
        """Evict the run occupying `slot`, snapshotting its live KV rows +
        sampling state to host, and free the slot.  The response stream
        stays OPEN (paused); `restore_run` later continues it bit-identical
        to an uninterrupted run.

        Zero new compiled programs: the snapshot is a plain
        `jax.device_get` of the pool (host copy, same donation-safe move
        the async checkpointer's snapshot phase makes) and the row slices
        are numpy.  Known cost: the transfer is O(pool), not O(victim
        rows) — free on CPU (aliased memory), two full-pool copies per
        preempt/restore pair on an accelerator (four on a speculative
        engine, whose draft pool rides along); a device-side row
        gather/scatter would shrink it at the price of extra compiled
        programs — and slicing `[slot, :pos]` before the device_get
        would compile one tiny gather per distinct pos, which is worse.
        Must be called between engine steps from the driving thread."""
        run = self._slots.get(slot)
        if run is None:
            raise InvalidArgumentError(f"slot {slot} holds no active run")
        if self.kv == "paged":
            # the snapshot format is IDENTICAL to the fixed engine's —
            # per-layer (pos, ...) row arrays — so PreemptedRun stays
            # pool-layout-agnostic and a run preempted paged restores
            # through the same restore_run contract.  Unlike the fixed
            # path's documented O(pool) device_get, this moves only the
            # slot's OWN blocks: paged OOM backpressure preempts
            # routinely, so the snapshot gathers ids on device first and
            # pulls O(slot blocks) to host (one cached eager gather per
            # distinct block count, bounded by max_blocks_per_slot)
            ids = np.asarray(self.kv_pool.block_ids(slot), np.int32)
            ids_dev = jnp.asarray(ids) if ids.size else None

            def rows_of(leaf):
                if ids_dev is None:
                    return np.zeros((0,) + tuple(leaf.shape[2:]),
                                    leaf.dtype)
                r = np.asarray(jax.device_get(
                    jnp.take(leaf, ids_dev, axis=0)))
                return np.array(r.reshape((-1,) + r.shape[2:])[:run.pos])

            kv_rows = [(rows_of(k), rows_of(v)) for k, v in self._pools]
            draft_rows = None
            if self.draft_model is not None:
                draft_rows = [(rows_of(k), rows_of(v))
                              for k, v in self._draft_pools]
        else:
            host = jax.device_get(self._pools)
            kv_rows = [(np.array(k[slot, :run.pos]),
                        np.array(v[slot, :run.pos]))
                       for k, v in host]
            draft_rows = None
            if self.draft_model is not None:
                dhost = jax.device_get(self._draft_pools)
                draft_rows = [(np.array(k[slot, :run.pos]),
                               np.array(v[slot, :run.pos]))
                              for k, v in dhost]
        paused = PreemptedRun(run, kv_rows, draft_rows)
        from .transfer import engine_config_hash
        paused.source_config_hash = engine_config_hash(self)
        run.req.preempts += 1
        self._slots.pop(slot, None)
        self.scheduler.release(slot)
        if self.kv == "paged":
            self.kv_pool.free(slot)
        if self._lora_reg is not None and run.aid:
            # unpin while parked: the adapter NAME travels with the
            # request; restore re-resolves (and may fail typed if the
            # adapter was evicted meanwhile)
            self._lora_reg.release(run.aid)
        self._batch_dirty = True
        stat_add("STAT_serving_preemptions")
        return paused

    def restore_run(self, paused: PreemptedRun) -> bool:
        """Resume a preempted run into any free slot: the saved KV rows are
        written back into the pool (host-side copy + upload — no compiled
        program) and decode continues from the saved position with the
        saved RNG key, so the remaining stream is bit-identical to a run
        that was never preempted.  Returns False when no slot is free —
        or, paged, when the block pool cannot hold the saved rows yet
        (the caller retries as it drains)."""
        slot = self.scheduler.acquire(paused.req, paused.resp)
        if slot is None:
            return False
        if self.kv == "paged":
            if self.prefix_cache is not None:
                if not self._restore_paged_prefix(slot, paused):
                    self.scheduler.release(slot)
                    return False
                return self._finish_restore(slot, paused)
            if not self.kv_pool.alloc(slot, paused.pos):
                self.scheduler.release(slot)
                return False
            self._pools = self._paged_upload(self._pools, slot,
                                             paused.kv_rows, paused.pos)
            if (self.draft_model is not None
                    and paused.draft_kv_rows is not None):
                self._draft_pools = self._paged_upload(
                    self._draft_pools, slot, paused.draft_kv_rows,
                    paused.pos)
            return self._finish_restore(slot, paused)

        def write_rows(pools, rows):
            new_pools = []
            for (hk, hv), (rk, rv) in zip(jax.device_get(pools), rows):
                # device_get may alias backend memory on CPU: copy before
                # the in-place row write, then re-upload (rows beyond
                # `pos` may hold garbage from the slot's idle decode
                # passes — the model protocol guarantees positions > pos
                # never influence output, and decode overwrites them as
                # it advances)
                hk = np.array(hk)
                hv = np.array(hv)
                hk[slot, :paused.pos] = rk
                hv[slot, :paused.pos] = rv
                nk, nv = jnp.asarray(hk), jnp.asarray(hv)
                if self._kv_put is not None:
                    # mesh engines must re-place the uploaded pool with
                    # its heads sharding — a default-device array here
                    # would silently de-shard the pool and retrace the
                    # decode program on the next call
                    nk, nv = self._kv_put(nk), self._kv_put(nv)
                new_pools.append((nk, nv))
            return new_pools

        self._pools = write_rows(self._pools, paused.kv_rows)
        if self.draft_model is not None and paused.draft_kv_rows is not None:
            self._draft_pools = write_rows(self._draft_pools,
                                           paused.draft_kv_rows)
        return self._finish_restore(slot, paused)

    def _restore_paged_prefix(self, slot: int, paused: PreemptedRun) -> bool:
        """Re-pin a restored run's shared prefix instead of re-uploading
        it: re-match the prompt against the LOCAL cache (the run may
        have migrated from another replica, or its blocks may have been
        evicted while parked), adopt whatever chain is still resident,
        and upload only the snapshot rows past it.  A fully cached
        prompt drops its last chain block — the prefill recomputed that
        block's final row in a private COW copy which was freed with the
        slot, so its snapshot rows upload into a fresh block instead —
        preserving the never-write-shared-blocks invariant.  On failure
        nothing is held (the caller releases the scheduler slot)."""
        req = paused.req
        plen = int(req.prompt.shape[0])
        bs = self.block_size
        chain = self.prefix_cache.match(self._share_key(req), req.prompt)
        if chain and len(chain) * bs >= plen:
            chain = chain[:-1]
        if chain and not self.kv_pool.adopt(slot, chain):
            return False
        if not self.kv_pool.ensure(slot, paused.pos):
            self.kv_pool.free(slot)
            return False
        shared_rows = len(chain) * bs
        self._pools = self._paged_upload(self._pools, slot,
                                         paused.kv_rows, paused.pos,
                                         start_row=shared_rows)
        return True

    def _finish_restore(self, slot: int, paused: PreemptedRun) -> bool:
        """Resume bookkeeping shared by both KV layouts: one copy, so a
        future lifecycle counter cannot diverge between them."""
        aid = 0
        if self.lora is not None:
            # the pin was dropped at preempt; re-resolve by NAME against
            # THIS engine's registry (the run may have migrated).  An
            # adapter evicted/never-loaded here is a typed terminal
            # failure — returning True because the paused run is
            # consumed, not parked for retry.
            try:
                aid = self._lora_reg.acquire(paused.req.adapter)
            except Exception as e:
                stat_add("STAT_lora_rejects")
                with self._m_lock:
                    self._errored += 1
                paused.resp._fail(e)
                self.scheduler.release(slot)
                if self.kv == "paged":
                    self.kv_pool.free(slot)
                self._batch_dirty = True
                return True
        run = _SlotRun(paused.req, paused.resp, pos=paused.pos,
                       first_token=paused.last_token, key=paused.key,
                       aid=aid)
        run.produced = paused.produced
        paused.req.resumes += 1
        paused.req.paused_seconds += time.monotonic() - paused.preempted_at
        self._slots[slot] = run
        self._batch_dirty = True
        stat_add("STAT_serving_resumes")
        return True

    def _paged_upload(self, pools, slot: int, rows, pos: int,
                      start_row: int = 0):
        """Publish snapshot rows into the slot's freshly allocated blocks
        (host build + one eager scatter per leaf; block tails past `pos`
        zero-filled, so the upload is also the scrub).  `start_row`
        (block-aligned) skips leading rows whose blocks were ADOPTED
        from the prefix cache — their device content is already the
        snapshot's, and a shared block must never be written."""
        bs = self.block_size
        skip = start_row // bs
        ids_np = np.asarray(self.kv_pool.block_ids(slot), np.int32)[skip:]
        nb_used = int(ids_np.shape[0])
        if nb_used == 0:
            return pools
        ids = jnp.asarray(ids_np)
        new_pools = []
        for (kp, vp), (rk, rv) in zip(pools, rows):
            def blocks_of(r, pool):
                buf = np.zeros((nb_used * bs,) + tuple(pool.shape[2:]),
                               pool.dtype)
                tail = r[start_row:]
                buf[:tail.shape[0]] = tail
                return jnp.asarray(
                    buf.reshape((nb_used, bs) + tuple(pool.shape[2:])))
            kp = kp.at[ids].set(blocks_of(rk, kp), mode="drop")
            vp = vp.at[ids].set(blocks_of(rv, vp), mode="drop")
            if self._kv_put is not None:
                kp, vp = self._kv_put(kp), self._kv_put(vp)
            new_pools.append((kp, vp))
        return new_pools

    # ------------------------------------------------------------------
    # paged block-pool pressure: ensure-or-preempt, park, resume
    # ------------------------------------------------------------------
    def _ensure_decode_blocks(self):
        """Before a paged tick: grow every active slot's table to cover
        the rows the compiled call may write.  A shortfall preempts the
        newest lowest-priority run (its blocks return to the pool and it
        parks host-side, resuming as the pool drains) — exhaustion is
        backpressure, not a crash.  Runs that can no longer fit at all,
        or overflow the parking budget, fail with the typed
        KVPoolExhaustedError."""
        for slot in sorted(self._slots):
            run = self._slots.get(slot)
            if run is None:
                continue
            target = self._oom_target(run.pos, run.req)
            guard = 0
            while (slot in self._slots
                   and not self.kv_pool.ensure(slot, target)):
                victim = self._pick_oom_victim(slot)
                if victim is None:
                    # nothing below the needy run to evict: park (or
                    # fail) the needy run itself
                    self._oom_evict(slot)
                    break
                self._oom_evict(victim)
                guard += 1
                if guard > self.max_slots + 2:
                    break  # defensive: cannot loop forever

    def _rows_needed(self, req: Request) -> int:
        """Pool rows that must be BACKED for every consumed token of the
        request: the final emitted token's logits come from in-program
        ctx, so backing ends at plen + max_new - 1; chunk-tail writes
        past it route through sentinel table entries and drop (their
        tokens are discarded by the host anyway)."""
        return min(self._pool_len,
                   int(req.prompt.shape[0]) + int(req.max_new_tokens) - 1)

    def _oom_target(self, pos: int, req: Request) -> int:
        """Rows the next tick actually requires for this run."""
        return min(pos + self._rows_per_tick,
                   max(self._rows_needed(req), pos))

    def _pick_oom_victim(self, needy_slot: int):
        """The NEWEST run in the LOWEST priority class at or below the
        needy run's priority (least progress lost, the PR-6 eviction
        intuition), excluding the needy slot itself."""
        needy = self._slots[needy_slot]
        best_slot, best_key = None, None
        for slot, run in self._slots.items():
            if slot == needy_slot:
                continue
            if run.req.priority > needy.req.priority:
                continue
            key = (run.req.priority, -run.req.id)
            if best_key is None or key < best_key:
                best_key, best_slot = key, slot
        return best_slot

    def _oom_evict(self, slot: int):
        run = self._slots.get(slot)
        if run is None:
            return
        if (len(self._oom_paused) >= self._max_oom_paused
                or not self.kv_pool.can_ever_fit(
                    self._oom_target(run.pos, run.req))):
            # parking would never end: the run's next tick cannot fit the
            # pool even ALONE (the fault cap or a tiny pool) — the typed
            # terminal state, not a silent hang
            self._oom_fail(slot, run)
            return
        paused = self.preempt_slot(slot)
        self._oom_paused.append(paused)
        self._oom_preempts += 1
        stat_add("STAT_serving_kv_oom_preempts")

    def _oom_fail(self, slot: int, run: "_SlotRun"):
        stat_add("STAT_serving_kv_exhausted")
        self._oom_failed += 1
        with self._m_lock:
            self._errored += 1
        run.resp._fail(KVPoolExhaustedError(
            f"request {run.req.id}: KV block pool exhausted mid-decode "
            f"({self.kv_pool.used_blocks()} used of "
            f"{self.kv_pool.capacity()} usable blocks) and the run can "
            "no longer be parked or resumed"))
        self._release(slot)

    def _sweep_oom_paused(self) -> bool:
        """Parked runs still honor cancel/deadline, and one that can no
        longer EVER fit (the fault cap shrank the pool under it) fails
        typed instead of waiting forever."""
        keep, changed = [], False
        for p in self._oom_paused:
            if p.resp.cancelled:
                stat_add("STAT_serving_cancelled")
                p.resp._fail(RequestCancelled(
                    f"request {p.req.id} cancelled while parked on KV "
                    "pool pressure"))
                changed = True
            elif p.req.deadline is not None and p.req.deadline.expired():
                stat_add("STAT_serving_deadline_expired")
                p.resp._fail(DeadlineExceededError(
                    f"request {p.req.id} deadline "
                    f"({p.req.deadline.seconds}s) expired while parked "
                    "on KV pool pressure"))
                changed = True
            elif not self.kv_pool.can_ever_fit(
                    self._oom_target(p.pos, p.req)):
                stat_add("STAT_serving_kv_exhausted")
                self._oom_failed += 1
                with self._m_lock:
                    self._errored += 1
                p.resp._fail(KVPoolExhaustedError(
                    f"request {p.req.id}: parked on KV pool pressure and "
                    f"the pool ({self.kv_pool.capacity()} usable blocks) "
                    "can no longer hold it at all"))
                changed = True
            else:
                keep.append(p)
        self._oom_paused = keep
        return changed

    def _restore_oom_paused(self) -> bool:
        did = False
        while self._oom_paused and self.scheduler.free_slot_count() > 0:
            if not self.restore_run(self._oom_paused[0]):
                break
            self._oom_paused.pop(0)
            stat_add("STAT_serving_kv_oom_resumes")
            did = True
        return did

    def _paged_batch(self):
        """(tables, active) dynamic inputs for the paged decode/verify
        call: per-slot block tables (sentinel everywhere a slot is
        unoccupied, so its writes drop) + the occupancy mask.  Cached
        against the allocator's mutation version — tables only change
        when a slot crosses a block boundary or membership churns, so
        steady-state ticks re-upload nothing."""
        ver = self.kv_pool.version
        if self._paged_cache is not None and self._paged_cache[0] == ver:
            return self._paged_cache[1], self._paged_cache[2]
        s = self.max_slots
        sentinel = self.kv_pool.num_blocks
        tables = np.full((s, self.kv_pool.max_blocks_per_slot), sentinel,
                         np.int32)
        active = np.zeros((s,), bool)
        for slot in self._slots:
            tables[slot] = self.kv_pool.table_array(slot)
            active[slot] = True
        self._paged_cache = (ver, jnp.asarray(tables), jnp.asarray(active))
        return self._paged_cache[1], self._paged_cache[2]

    def _rebuild_batch(self):
        s = self.max_slots
        tokens = np.zeros((s,), np.int32)
        pos = np.zeros((s,), np.int32)
        keys = np.zeros((s, self._key_width), np.uint32)
        temp = np.ones((s,), np.float32)
        top_k = np.zeros((s,), np.int32)
        top_p = np.ones((s,), np.float32)
        greedy = np.ones((s,), bool)
        poison = np.zeros((s,), bool)
        spec_on = np.zeros((s,), bool)
        aids = np.zeros((s,), np.int32)  # idle slots decode as adapter 0
        for slot, run in self._slots.items():
            tokens[slot] = run.last_token
            pos[slot] = run.pos
            keys[slot] = run.key
            temp[slot] = run.req.temperature
            top_k[slot] = run.req.top_k
            top_p[slot] = run.req.top_p
            greedy[slot] = run.req.greedy
            poison[slot] = run.req.poison
            spec_on[slot] = run.req.spec
            aids[slot] = run.aid
        self._dev_tokens = jnp.asarray(tokens)
        self._dev_pos = jnp.asarray(pos)
        if self.lora is not None:
            self._dev_aids = jnp.asarray(aids)
        self._dev_params = tuple(jnp.asarray(a) for a in (
            keys, temp, top_k, top_p, greedy, poison, spec_on))
        self._batch_dirty = False

    def _decode_step(self):
        if self.draft_model is not None:
            self._spec_step()
            return
        span = self._span("serving_decode")
        try:
            if self.kv == "paged":
                # grow block tables for this chunk's writes (may preempt
                # or fail runs under pool pressure — membership can
                # change, so this runs before the batch rebuild)
                self._ensure_decode_blocks()
                if not self._slots:
                    return
            if self._batch_dirty:
                self._rebuild_batch()
            # PDTPU_FAULT_SLOW_DECODE: host-side latency injection, read
            # live per call — overload/SLO-miss paths become testable on
            # CPU without a big model
            faults.maybe_slow_decode(self._decode_calls)
            self._decode_calls += 1
            keys, temp, top_k, top_p, greedy, poison, _ = self._dev_params
            if self.kv == "paged":
                tables, active = self._paged_batch()
                if self.lora is not None:
                    (toks, logps, finites, ntok, npos,
                     self._pools) = self._decode_fn(
                        self._state, self._pools,
                        self._lora_reg.device_args(), tables, active,
                        self._dev_tokens, self._dev_pos, self._dev_aids,
                        keys, temp, top_k, top_p, greedy, poison)
                else:
                    (toks, logps, finites, ntok, npos,
                     self._pools) = self._decode_fn(
                        self._state, self._pools, tables, active,
                        self._dev_tokens, self._dev_pos, keys, temp, top_k,
                        top_p, greedy, poison)
            elif self.lora is not None:
                (toks, logps, finites, ntok, npos,
                 self._pools) = self._decode_fn(
                    self._state, self._pools, self._lora_reg.device_args(),
                    self._dev_tokens, self._dev_pos, self._dev_aids, keys,
                    temp, top_k, top_p, greedy, poison)
            else:
                (toks, logps, finites, ntok, npos,
                 self._pools) = self._decode_fn(
                    self._state, self._pools, self._dev_tokens,
                    self._dev_pos, keys, temp, top_k, top_p, greedy,
                    poison)
            self._dev_tokens, self._dev_pos = ntok, npos
            # one device->host pull for the whole (chunk, slots) burst
            toks, logps, finites = jax.device_get((toks, logps, finites))
            stat_add("STAT_serving_decode_steps")
            emitted = 0
            for slot in list(self._slots):
                run = self._slots[slot]
                for j in range(toks.shape[0]):
                    # deadline enforcement on the decode tick itself, not
                    # only at the next sweep: a budget that expired while
                    # the chunk was computing stops the stream here — no
                    # post-expiry tokens are delivered, the slot recycles
                    # now (regression: deadline shorter than one chunk)
                    if (run.req.deadline is not None
                            and run.req.deadline.expired()):
                        stat_add("STAT_serving_deadline_expired")
                        run.resp._fail(DeadlineExceededError(
                            f"request {run.req.id} deadline "
                            f"({run.req.deadline.seconds}s) expired "
                            "mid-decode"))
                        self._release(slot)
                        break
                    if not finites[j, slot]:
                        self._fail_slot(slot, run.resp, "decode")
                        break
                    t = int(toks[j, slot])
                    run.pos += 1
                    run.produced += 1
                    run.last_token = t
                    self._emit(run, t, float(logps[j, slot]))
                    emitted += 1
                    self._maybe_finish(slot, run, t)
                    if slot not in self._slots:
                        # finished mid-chunk: the tail iterations of this
                        # slot are discarded (their KV garbage dies with
                        # the slot's next prefill)
                        break
            if emitted:
                stat_add("STAT_serving_tokens", emitted)
        finally:
            if span is not None:
                span.__exit__(None, None, None)

    def _spec_step(self):
        """One speculative tick: K draft proposals + one batched target
        verify, committing 1..K+1 tokens per slot.  Host side mirrors the
        chunked decode step — including the PR-6 deadline rule: a tick can
        commit up to K+1 tokens, and a deadline that expired while the
        tick was computing stops the stream BEFORE the next commit — no
        post-expiry token is ever delivered."""
        span = self._span("serving_verify")
        try:
            if self.kv == "paged":
                self._ensure_decode_blocks()
                if not self._slots:
                    return
            if self._batch_dirty:
                self._rebuild_batch()
            tick_no = self._decode_calls  # lifetime stride counter: the
            # diverge fault keys off it, NOT _spec_ticks, which is a
            # metrics-window counter reset_metrics() zeroes
            faults.maybe_slow_decode(tick_no)
            self._decode_calls += 1
            keys, temp, top_k, top_p, greedy, poison, spec_on = \
                self._dev_params
            diverge = bool(self._diverge_every is not None
                           and tick_no % self._diverge_every == 0)
            self._spec_ticks += 1
            if self.kv == "paged":
                tables, active = self._paged_batch()
                (toks, logps, finites, counts, accepts, last, npos,
                 self._pools, self._draft_pools) = self._decode_fn(
                    self._state, self._dstate, self._pools,
                    self._draft_pools, tables, active, self._dev_tokens,
                    self._dev_pos, keys, temp, top_k, top_p, greedy,
                    spec_on, poison, jnp.asarray(diverge))
            else:
                (toks, logps, finites, counts, accepts, last, npos,
                 self._pools, self._draft_pools) = self._decode_fn(
                    self._state, self._dstate, self._pools,
                    self._draft_pools, self._dev_tokens, self._dev_pos,
                    keys, temp, top_k, top_p, greedy, spec_on, poison,
                    jnp.asarray(diverge))
            self._dev_tokens, self._dev_pos = last, npos
            # one device->host pull for the whole (slots, K+1) tick
            toks, logps, finites, counts, accepts = jax.device_get(
                (toks, logps, finites, counts, accepts))
            stat_add("STAT_serving_decode_steps")
            stat_add("STAT_spec_ticks")
            k_spec = self.spec_tokens
            emitted = proposed = accepted_n = 0
            for slot in list(self._slots):
                run = self._slots[slot]
                if not finites[slot]:
                    self._fail_slot(slot, run.resp, "verify")
                    continue
                if run.req.spec:
                    proposed += k_spec
                    accepted_n += int(accepts[slot])
                    self._h_accept.observe(int(accepts[slot]) / k_spec)
                for j in range(int(counts[slot])):
                    # deadline enforcement on the tick itself (PR-6 rule):
                    # a speculative tick may hold K+1 ready tokens, but a
                    # budget that expired mid-tick delivers none of the
                    # remainder — the slot recycles now (regression:
                    # deadline shorter than one speculative tick)
                    if (run.req.deadline is not None
                            and run.req.deadline.expired()):
                        stat_add("STAT_serving_deadline_expired")
                        run.resp._fail(DeadlineExceededError(
                            f"request {run.req.id} deadline "
                            f"({run.req.deadline.seconds}s) expired "
                            "mid-decode"))
                        self._release(slot)
                        break
                    t = int(toks[slot, j])
                    run.pos += 1
                    run.produced += 1
                    run.last_token = t
                    self._emit(run, t, float(logps[slot, j]))
                    emitted += 1
                    self._maybe_finish(slot, run, t)
                    if slot not in self._slots:
                        # finished mid-tick: the tail commits are
                        # discarded (their KV garbage dies with the
                        # slot's next prefill)
                        break
            if emitted:
                stat_add("STAT_serving_tokens", emitted)
            if proposed:
                stat_add("STAT_spec_proposed", proposed)
                stat_add("STAT_spec_accepted", accepted_n)
                with self._m_lock:
                    self._spec_proposed += proposed
                    self._spec_accepted += accepted_n
        finally:
            if span is not None:
                span.__exit__(None, None, None)

    def _fail_slot(self, slot: int, resp: Response, phase: str):
        stat_add("STAT_serving_nonfinite")
        with self._m_lock:
            self._errored += 1
        resp._fail(NonFiniteLogitsError(
            f"request {resp.request.id}: non-finite logits during {phase}; "
            "slot recycled, engine keeps serving"))
        self._release(slot)

    def _emit(self, run: _SlotRun, tok: int, logp: float):
        now = time.monotonic()
        first = run.resp.first_token_at is None
        run.resp._push_token(tok, logp)
        with self._m_lock:
            self._tokens_out += 1
            if first:
                self._ttfts.append(run.resp.ttft)
            else:
                self._itl_sum += now - run.last_token_at
                self._itl_n += 1
        if first:
            self._h_ttft.observe(run.resp.ttft)
        else:
            self._h_itl.observe(now - run.last_token_at)
        run.last_token_at = now

    def _maybe_finish(self, slot: int, run: _SlotRun, tok: int):
        eos = run.req.eos_token_id
        if eos is not None and tok == eos:
            reason = "eos"
        elif run.produced >= run.req.max_new_tokens:
            reason = "length"
        else:
            return
        with self._m_lock:
            self._completed += 1
        run.resp._finish(reason)
        self._release(slot)

    def _span(self, name: str):
        if not self._profile:
            return None
        from ..utils.profiler import RecordEvent
        return RecordEvent(name).__enter__()

    # ------------------------------------------------------------------
    # driving
    # ------------------------------------------------------------------
    def has_work(self) -> bool:
        return (bool(self._slots) or self.scheduler.has_work()
                or bool(self.kv == "paged" and self._oom_paused))

    def run_until_drained(self, timeout: Optional[float] = None):
        """Drive the loop in the caller's thread until queue and slots are
        empty (tests / batch jobs).  Not for use while start() is live."""
        t0 = time.monotonic()
        while self.has_work():
            self.step()
            if timeout is not None and time.monotonic() - t0 > timeout:
                raise TimeoutError("serving engine did not drain in "
                                   f"{timeout}s")

    def _abort_all(self, make_exc):
        """Fail every in-flight and queued request (engine death/close):
        a consumer blocked in Response.__iter__ / tokens() must get an
        error, never hang."""
        for slot in list(self._slots):
            run = self._slots.pop(slot)
            self.scheduler.release(slot)
            if self.kv == "paged":
                self.kv_pool.free(slot)
            if self._lora_reg is not None and run.aid:
                self._lora_reg.release(run.aid)
            run.resp._fail(make_exc(run.req))
        for req, resp in self.scheduler.drain_pending():
            resp._fail(make_exc(req))
        if self.kv == "paged":
            paused, self._oom_paused = self._oom_paused, []
            for p in paused:
                p.resp._fail(make_exc(p.req))
        self._batch_dirty = True

    def start(self):
        """Background engine loop (streaming servers / the probe)."""
        if self._thread is not None:
            return
        if self._closed:
            raise UnavailableError("serving engine is closed")
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    did = self.step()
                except BaseException as e:  # noqa: BLE001 — must not hang
                    # the loop thread dying silently would leave every
                    # consumer blocked forever: record the cause, fail all
                    # outstanding requests, refuse new ones
                    self._dead = e
                    self._abort_all(lambda req: UnavailableError(
                        f"request {req.id} aborted: serving engine loop "
                        f"died: {e!r}"))
                    return
                if not did:
                    self._work.wait(0.002)
                    self._work.clear()

        self._thread = threading.Thread(target=loop, name="serving-engine",
                                        daemon=True)
        self._thread.start()

    def close(self):
        """Stop the loop and fail any still-outstanding requests (a
        Response consumer must never be left blocked on a closed
        engine).  Idempotent and safe under concurrent double-close: the
        flag flips before the lock so racing submitters reject early, and
        the join/abort sequence runs under _close_lock so a second closer
        can never join a half-torn-down thread or re-abort a drain in
        progress."""
        self._closed = True
        self._stop.set()
        self._work.set()
        with self._close_lock:
            if self._thread is not None:
                self._thread.join(timeout=5.0)
                self._thread = None
            self._abort_all(lambda req: RequestCancelled(
                f"request {req.id} aborted: serving engine closed"))

    @property
    def warm(self) -> bool:
        """True once warmup() has precompiled every program the engine
        will ever run — the gateway's /healthz readiness signal."""
        return self._warm

    # ------------------------------------------------------------------
    # program lifecycle: example args, warmup, AOT program sets
    # ------------------------------------------------------------------
    def _example_prefill_args(self, bucket: int):
        """The exact argument tuple a live admission passes to this
        bucket's prefill program (same avals, CURRENT pools) — one
        builder shared by warmup and the program-set exporter so their
        signatures can never drift.  Fixed pools target slot 0 (warmup
        junk dies at the slot's next prefill); paged args route every
        write through the allocator's sentinel table (dropped)."""
        if self.kv == "paged":
            slot_arg = jnp.asarray(self.kv_pool.sentinel_table())
        else:
            slot_arg = jnp.int32(0)
        ids = np.full((1, bucket), self.pad_token_id, np.int32)
        zero_key = jnp.asarray(np.zeros(self._key_width, np.uint32))
        plen_args = ((jnp.int32(1), jnp.int32(0))   # plen, cached_len
                     if self.prefix_cache is not None
                     else (jnp.int32(1),))
        if self.lora is not None:
            # adapter id 0 = base: warmup decodes under the all-zero
            # slot-0 factors, same avals as any live adapter id
            plen_args = plen_args + (jnp.int32(0),)
        common = (jnp.asarray(ids), slot_arg) + plen_args + (
            zero_key, jnp.float32(1.0), jnp.int32(0), jnp.float32(1.0),
            jnp.asarray(True))
        if self.draft_model is not None:
            return (self._state, self._dstate, self._pools,
                    self._draft_pools) + common
        if self.lora is not None:
            return (self._state, self._pools,
                    self._lora_reg.device_args()) + common
        return (self._state, self._pools) + common

    def _example_decode_args(self):
        """The exact argument tuple a live tick passes to the decode (or
        speculative verify) program — shared by warmup and the exporter."""
        s = self.max_slots
        pre = []
        if self.kv == "paged":
            pre = [jnp.asarray(np.tile(self.kv_pool.sentinel_table(),
                                       (s, 1))),
                   jnp.zeros((s,), bool)]
        base = [jnp.zeros((s,), jnp.int32), jnp.zeros((s,), jnp.int32),
                jnp.zeros((s, self._key_width), jnp.uint32),
                jnp.ones((s,), jnp.float32), jnp.zeros((s,), jnp.int32),
                jnp.ones((s,), jnp.float32), jnp.ones((s,), bool)]
        if self.lora is not None:
            # per-slot adapter ids slide in right after `pos`
            base.insert(2, jnp.zeros((s,), jnp.int32))
        if self.draft_model is not None:
            args = pre + base + [jnp.ones((s,), bool),
                                 jnp.zeros((s,), bool), jnp.asarray(False)]
            return (self._state, self._dstate, self._pools,
                    self._draft_pools, *args)
        args = pre + base + [jnp.zeros((s,), bool)]
        if self.lora is not None:
            return (self._state, self._pools,
                    self._lora_reg.device_args(), *args)
        return (self._state, self._pools, *args)

    def _program_family(self):
        """[(name, fn, example_args, donate_argnums)] for every compiled
        program this engine configuration will ever run — the unit the
        program store and AOT program sets operate on.  Names are
        layout-agnostic (`prefill_b{bucket}`, `decode`) so a paged
        artifact can never be confused with a fixed one except through
        the manifest, which records the layout explicitly.  The donation
        indices ride along because `jax.export` does not preserve
        donation — the program-set loader re-applies them (losing them
        silently would turn every tick into a full KV-pool copy)."""
        donate = (2, 3) if self.draft_model is not None else (1,)
        family = [(f"prefill_b{b}", self._prefill_fns[b],
                   self._example_prefill_args(b), donate)
                  for b in self.buckets]
        family.append(("decode", self._decode_fn,
                       self._example_decode_args(), donate))
        return family

    def warmup(self) -> Dict:
        """Compile every program the engine will ever run (one prefill per
        bucket + the decode/verify step — on speculative engines the
        verify program and the draft halves of each bucket prefill ride
        the same calls; paged variants route writes through the sentinel
        table) so no request pays a trace — the program-lifecycle warmup
        the gateway calls before admitting traffic.  After it returns,
        `post_warmup_compiles()` must stay 0 under ANY traffic mix —
        spec on/off, greedy/sampling, preempt/restore.

        Programs preloaded from an AOT program set in the native 'exe'
        representation are already compiled and are NOT executed here
        (their first execution is the first real request); 'stablehlo'
        programs and freshly traced ones are invoked once to force the
        compile now.  Safe any time no request is in flight.  Returns a
        report: per-program compile source + wall seconds + store stats."""
        from ..programs.program_set import LoadedProgram
        t0 = time.perf_counter()
        sources = {}
        for b in self.buckets:
            fn = self._prefill_fns[b]
            if isinstance(fn, LoadedProgram) and fn.kind == "exe":
                sources[f"prefill_b{b}"] = "program_set:exe"
                continue
            out = fn(*self._example_prefill_args(b))
            if self.draft_model is not None:
                self._pools, self._draft_pools = out[3], out[4]
            else:
                self._pools = out[3]
            sources[f"prefill_b{b}"] = (
                "program_set:stablehlo" if isinstance(fn, LoadedProgram)
                else "traced")
        fn = self._decode_fn
        if isinstance(fn, LoadedProgram) and fn.kind == "exe":
            sources["decode"] = "program_set:exe"
        else:
            out = fn(*self._example_decode_args())
            if self.draft_model is not None:
                self._pools, self._draft_pools = out[-2], out[-1]
            else:
                self._pools = out[-1]
            sources["decode"] = (
                "program_set:stablehlo" if isinstance(fn, LoadedProgram)
                else "traced")
        if self._cow_fn is not None:
            # precompile the COW block copy with the sentinel dst (mode=
            # "drop" makes it a no-op) so the first real COW pays no trace
            self._pools = self._cow_fn(self._pools, jnp.int32(0),
                                       jnp.int32(self.kv_pool.num_blocks))
            sources["cow_copy"] = "traced"
        self._warm = True
        self._warm_marks = self._compile_marks()
        report = {"seconds": time.perf_counter() - t0,
                  "programs": sources,
                  "compile_counts": self.compile_counts()}
        try:
            from ..programs.store import store_stats
            report["store"] = store_stats()
        except Exception:
            pass
        return report

    def _compile_marks(self) -> Dict:
        """Snapshot of every serving-compile counter: the engine's own
        trace counts AND the observability program registry (loaded
        program sets never touch the former; TrackedJit programs report
        to the latter)."""
        try:
            from ..observability import get_program_registry
            reg = {name: rec["compiles"] for name, rec
                   in get_program_registry().snapshot().items()
                   if name.startswith("serving_")}
        except Exception:
            reg = {}
        return {"engine": (self._compiles["decode"]
                           + sum(self._compiles["prefill"].values())),
                "registry": reg}

    def post_warmup_compiles(self) -> int:
        """Compiles observed since warmup() finished — the fleet
        contract is that this stays 0 under ANY traffic mix (probes and
        tier-1 assert it).  Counts both engine trace counters and new
        `serving_*` registry compiles; returns -1 if warmup never ran."""
        if self._warm_marks is None:
            return -1
        now = self._compile_marks()
        extra = now["engine"] - self._warm_marks["engine"]
        base = self._warm_marks["registry"]
        for name, compiles in now["registry"].items():
            extra += compiles - base.get(name, 0)
        return extra

    def save_program_set(self, path: str,
                         extra_meta: Optional[dict] = None) -> str:
        """Export this engine's whole program family (+ config manifest)
        as one artifact loadable by ``ServingEngine(program_set=...)`` /
        ``enable_serving(program_set=...)`` — see
        paddle_tpu/programs/program_set.py.  Call after `warmup()` to
        reuse the already-compiled executables (saving then compiles
        nothing)."""
        from ..programs.program_set import save_program_set as _save
        return _save(self, path, extra_meta)

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------
    def compile_counts(self) -> Dict:
        """Traced-program counts: the ≤ len(buckets) + 1 guarantee.  For
        speculative engines the same bound holds — "decode" counts the one
        verify program (draft proposal scan + batched target verify +
        in-program commit) and each per-bucket prefill program covers
        target AND draft prefill, so spec on/off × greedy/sampling traffic
        never adds a program."""
        return {"decode": self._compiles["decode"],
                "prefill": dict(self._compiles["prefill"]),
                "total": (self._compiles["decode"]
                          + sum(self._compiles["prefill"].values())),
                "bound": len(self.buckets) + 1}

    def adapter_shas(self) -> Optional[Dict[str, str]]:
        """name -> artifact sha of every resident LoRA adapter, or None
        on a no-LoRA engine.  Cheaper than metrics(): fleet health
        snapshots call this per replica per tick."""
        if self._lora_reg is None:
            return None
        return self._lora_reg.shas() or None

    def metrics(self) -> Dict:
        """Serving metrics snapshot (also published as STAT_serving_*
        monitor counters and, under enable_profile, in the profiler
        report)."""
        with self._m_lock:
            ttfts = sorted(self._ttfts)
            p50 = ttfts[len(ttfts) // 2] if ttfts else None
            itl = self._itl_sum / self._itl_n if self._itl_n else None
            elapsed = time.monotonic() - self._started_at
            return {
                "requests_completed": self._completed,
                "requests_errored": self._errored,
                "tokens_out": self._tokens_out,
                "tokens_per_sec": (self._tokens_out / elapsed
                                   if elapsed > 0 else 0.0),
                "ttft_p50_ms": None if p50 is None else p50 * 1e3,
                "inter_token_ms": None if itl is None else itl * 1e3,
                "queue_depth": self.scheduler.queue_depth(),
                "slot_occupancy": self.scheduler.occupancy(),
                "max_slots": self.max_slots,
                "compile_counts": self.compile_counts(),
                "spec": self._spec_metrics(),
                "warm": self._warm,
                "post_warmup_compiles": (self.post_warmup_compiles()
                                         if self._warm else None),
                "program_set": self.program_set_info,
                "kv_pool": self._kv_pool_metrics(),
                "lora": (None if self._lora_reg is None
                         else self._lora_reg.stats()),
                "mesh": (None if self.mesh is None else {
                    "devices": int(self.mesh.devices.size),
                    "tp": int(self.mesh.shape.get("tp", 1))}),
            }

    def _kv_pool_metrics(self):
        if self.kv != "paged":
            return {"kind": "fixed", "max_slots": self.max_slots,
                    "pool_len": self._pool_len}
        out = {"kind": "paged", **self.kv_pool.stats(),
               "oom_preempts": self._oom_preempts,
               "oom_failed": self._oom_failed,
               "oom_paused": len(self._oom_paused)}
        if self.prefix_cache is not None:
            out["prefix_cache"] = self.prefix_cache.stats()
        return out

    def _spec_metrics(self):
        if self.draft_model is None:
            return {"enabled": False}
        return {
            "enabled": True,
            "spec_tokens": self.spec_tokens,
            "ticks": self._spec_ticks,
            "proposed": self._spec_proposed,
            "accepted": self._spec_accepted,
            "accept_rate": (self._spec_accepted / self._spec_proposed
                            if self._spec_proposed else None),
        }

    def reset_metrics(self):
        with self._m_lock:
            self._ttfts = []
            self._itl_sum = 0.0
            self._itl_n = 0
            self._tokens_out = 0
            self._completed = 0
            self._errored = 0
            self._started_at = time.monotonic()
            if self.draft_model is not None:
                self._spec_ticks = 0
                self._spec_proposed = 0
                self._spec_accepted = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
