"""Replica-portable run transfer codec.

The PR-6 `engine.preempt_slot` snapshot (`PreemptedRun`: per-layer KV
row arrays + RNG key / write position / produced count / last token)
was designed for pause-and-resume on ONE engine.  This module
generalizes it into the unit of fleet failover: a preempted run encodes
to a pure-numpy blob (optionally to bytes — npz — for subprocess
replicas) that any CONFIGURATION-COMPATIBLE replica decodes back into a
`PreemptedRun` and feeds to its own `restore_run`, so a stream migrated
mid-decode resumes bit-identical to a run that never moved:

- the KV rows are the layout-agnostic ``(pos, heads, head_dim)`` row
  arrays both the fixed and paged snapshot paths already produce, so a
  run can migrate between fixed- and paged-pool replicas of the same
  model;
- the sampling state (raw RNG key + write position + produced count +
  last committed token) is exactly what decode step ``pos`` needs to
  fold the same key it would have folded uninterrupted;
- the request descriptor (prompt, budget, sampling knobs, seed, tenant,
  session, REMAINING deadline) rides along so a subprocess replica can
  rebuild the Request on its side of the wire — in-process migration passes the original
  Request/Response straight through instead (the consumer keeps
  iterating the same stream object).

Every compatibility axis is checked loudly: codec version, layer count,
per-layer row shapes and dtypes against the target engine's live pools,
the position budget against the target's max_len, and — when the
snapshot carries one — the source engine's CONFIG HASH against the
target's (`engine_config_hash`: model class, weight-shape signature,
length budget, spec/dtype axes — the axes a program-set manifest pins).
A worker built from a different program-set manifest therefore rejects
a migrated run with the typed `RunTransferError` instead of decoding
garbage rows into its pools; a quiet shape cast would corrupt the
stream the migration was supposed to save.

Cross-process targets (the subprocess replica proxy) cannot expose live
pools; they implement ``transfer_manifest()`` returning the same
descriptor `target_manifest` derives from a live engine, and every
check runs against that.
"""
from __future__ import annotations

import hashlib
import io
import json
from typing import Optional

import numpy as np

from ..core.errors import InvalidArgumentError
from .engine import PreemptedRun
from .request import Request, Response

__all__ = ["RunTransferError", "encode_run", "decode_run", "run_to_bytes",
           "run_from_bytes", "check_compatible", "engine_config_hash",
           "target_manifest", "TRANSFER_VERSION", "file_sha256",
           "artifact_manifest", "iter_artifact_chunks",
           "ARTIFACT_CHUNK_SIZE"]

# weight / program-set shipping: frames this size keep any single RPC
# frame small enough that a mid-frame connection cut loses at most one
# chunk (and the per-chunk sha pinpoints exactly which one was torn)
ARTIFACT_CHUNK_SIZE = 1 << 18

# v2: the npz header gained the codec version INSIDE the wire form (not
# only the in-memory blob) plus the source engine's config hash, so a
# cross-process restore can be refused typed before any row is decoded.
TRANSFER_VERSION = 2

# Request fields the codec carries so a subprocess replica can rebuild
# the request on its side of the wire (json-serializable scalars only)
_REQ_FIELDS = ("id", "max_new_tokens", "greedy", "temperature", "top_k",
               "top_p", "eos_token_id", "seed", "priority", "tenant",
               "spec", "session", "resubmit", "adapter")


class RunTransferError(InvalidArgumentError):
    """The snapshot cannot be restored on the target replica: version,
    config-hash, layer-count, shape, dtype, or length-budget mismatch.
    Typed so the fleet can fail the stream terminally instead of
    corrupting it."""
    code = "InvalidArgument"


def engine_config_hash(engine) -> str:
    """Digest of the config axes a run transfer depends on: model class,
    weight shape/dtype signature (target and draft), max_len/pool_len,
    spec_tokens, KV dtype override and RNG key width.  Deliberately
    EXCLUDES the axes a run may legitimately cross — kv layout
    (fixed/paged), block_size, max_slots, buckets, decode_chunk — a run
    migrates between fixed- and paged-pool replicas of the same model by
    design.  Two engines built from the same program-set manifest hash
    equal; a worker built from a different manifest does not."""
    tm = getattr(engine, "transfer_manifest", None)
    if callable(tm):
        return tm()["config_hash"]
    from ..programs.program_set import _state_sig
    ident = {
        "model_class": type(engine.model).__name__,
        "state_sig": _state_sig(engine._state),
        "draft_state_sig": (_state_sig(engine._dstate)
                            if engine.draft_model is not None else None),
        "max_len": int(engine.max_len),
        "pool_len": int(engine._pool_len),
        "spec_tokens": (int(engine.spec_tokens)
                        if engine.draft_model is not None else None),
        "dtype": (str(engine._dtype)
                  if engine._dtype is not None else None),
        "key_width": int(engine._key_width),
    }
    return hashlib.sha256(
        json.dumps(ident, sort_keys=True).encode()).hexdigest()[:16]


def target_manifest(engine) -> dict:
    """The restore-compatibility descriptor of an engine: per-layer KV
    row trailing shapes + dtypes (target and draft halves), max_len, and
    the config hash.  A live engine derives it from its pools; a
    subprocess replica PROXY returns the one its worker computed at boot
    via ``transfer_manifest()`` — so `check_compatible` works identically
    against both."""
    tm = getattr(engine, "transfer_manifest", None)
    if callable(tm):
        return tm()

    def side(pools):
        return [{"k_shape": [int(d) for d in k.shape[2:]],
                 "v_shape": [int(d) for d in v.shape[2:]],
                 "k_dtype": str(k.dtype), "v_dtype": str(v.dtype)}
                for k, v in pools]

    return {
        "config_hash": engine_config_hash(engine),
        "max_len": int(engine.max_len),
        "kv": side(engine._pools),
        "draft_kv": (side(engine._draft_pools)
                     if engine.draft_model is not None else None),
    }


# ---------------------------------------------------------------------------
# artifact shipping: weight / program-set files over the boot handshake
# ---------------------------------------------------------------------------

def file_sha256(path: str) -> str:
    """Whole-file sha256 hex digest (streamed; artifacts can be GBs)."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def artifact_manifest(path: str,
                      chunk_size: int = ARTIFACT_CHUNK_SIZE) -> dict:
    """The shipping manifest of one artifact file (a jit.save weight npz
    or a PR-9 program set): whole-artifact sha256 + per-chunk sha256s.
    The receiving worker verifies EVERY chunk against this before any
    byte reaches an engine — a mismatch is the typed reject, never
    garbage weights."""
    chunks = []
    total = hashlib.sha256()
    nbytes = 0
    with open(path, "rb") as f:
        while True:
            data = f.read(chunk_size)
            if not data:
                break
            total.update(data)
            nbytes += len(data)
            chunks.append({"sha256": hashlib.sha256(data).hexdigest(),
                           "nbytes": len(data)})
    return {"sha256": total.hexdigest(), "nbytes": nbytes,
            "chunk_size": int(chunk_size), "chunks": chunks}


def iter_artifact_chunks(path: str,
                         chunk_size: int = ARTIFACT_CHUNK_SIZE):
    """Yield (seq, bytes) chunks of the artifact in manifest order."""
    seq = 0
    with open(path, "rb") as f:
        while True:
            data = f.read(chunk_size)
            if not data:
                break
            yield seq, data
            seq += 1


def encode_run(paused: PreemptedRun, engine=None) -> dict:
    """PreemptedRun -> portable blob: pure numpy + scalars, no live
    object references.  The blob alone (via `run_to_bytes`) is enough to
    resume the stream in another process; in-process callers pass the
    original req/resp back to `decode_run` so the consumer's stream
    object survives the move.  The source engine's config hash rides
    the manifest — from `engine=` when given, else from the hash
    `preempt_slot` stamped on the PreemptedRun itself
    (`source_config_hash`), so it survives manager-side
    decode/re-encode hops of a migration and a cross-process restore
    onto a worker built from a different program-set manifest is
    refused typed on EVERY path."""
    kv = [(np.asarray(k), np.asarray(v)) for k, v in paused.kv_rows]
    draft = None
    if paused.draft_kv_rows is not None:
        draft = [(np.asarray(k), np.asarray(v))
                 for k, v in paused.draft_kv_rows]
    req = paused.req
    req_desc = {f: getattr(req, f) for f in _REQ_FIELDS}
    # the deadline crosses the wire as its REMAINING budget at encode
    # time (the Deadline object is anchored to this process's clock): a
    # migrated run must keep counting down, not get a fresh budget
    req_desc["deadline_remaining_s"] = (
        None if req.deadline is None else req.deadline.remaining())
    return {
        "version": TRANSFER_VERSION,
        "pos": int(paused.pos),
        "produced": int(paused.produced),
        "last_token": int(paused.last_token),
        "key": np.asarray(paused.key),
        "kv_rows": kv,
        "draft_kv_rows": draft,
        "prompt": np.asarray(req.prompt, np.int32),
        "req": req_desc,
        "manifest": {
            "layers": len(kv),
            "draft_layers": None if draft is None else len(draft),
            "kv_shapes": [(list(k.shape), list(v.shape)) for k, v in kv],
            "kv_dtypes": [(str(k.dtype), str(v.dtype)) for k, v in kv],
            "config_hash": (engine_config_hash(engine)
                            if engine is not None
                            else getattr(paused, "source_config_hash",
                                         None)),
        },
    }


def check_compatible(blob: dict, engine) -> None:
    """Raise RunTransferError unless `blob` can restore into `engine`'s
    pools bit-exactly: a codec version this build understands, a
    matching engine config hash (when the snapshot carries one), same
    layer count, same per-row trailing shape and dtype per layer (target
    AND draft halves), and remaining budget within the target's
    max_len.  `engine` may be a live ServingEngine or anything exposing
    ``transfer_manifest()`` (the subprocess replica proxy)."""
    if blob.get("version") != TRANSFER_VERSION:
        raise RunTransferError(
            f"run snapshot codec version {blob.get('version')!r} != "
            f"{TRANSFER_VERSION} — refusing a format this build does not "
            "understand")
    man = blob["manifest"]
    target = target_manifest(engine)
    src_hash = man.get("config_hash")
    if src_hash is not None and src_hash != target["config_hash"]:
        raise RunTransferError(
            f"snapshot came from an engine with config hash {src_hash} "
            f"but the target's is {target['config_hash']} — the replicas "
            "were built from different program-set manifests (model, "
            "weights signature, length budget, or spec config differ); "
            "a silent restore would decode garbage rows")

    def check_side(rows, sides, what):
        if len(rows) != len(sides):
            raise RunTransferError(
                f"{what}: snapshot has {len(rows)} layers, target engine "
                f"has {len(sides)} — replicas must serve the same model")
        for i, ((rk, rv), s) in enumerate(zip(rows, sides)):
            for r, shape, dt, half in (
                    (rk, s["k_shape"], s["k_dtype"], "k"),
                    (rv, s["v_shape"], s["v_dtype"], "v")):
                # pool leaves are (slots|blocks, rows, heads, dim); a
                # snapshot row array is (pos, heads, dim) — trailing
                # dims must agree exactly
                if list(r.shape[1:]) != list(shape):
                    raise RunTransferError(
                        f"{what} layer {i}/{half}: snapshot row shape "
                        f"{tuple(r.shape[1:])} != target pool row shape "
                        f"{tuple(shape)}")
                if str(r.dtype) != dt:
                    raise RunTransferError(
                        f"{what} layer {i}/{half}: snapshot dtype "
                        f"{r.dtype} != target pool dtype {dt} — a "
                        "silent cast would break bit-identity")

    check_side(blob["kv_rows"], target["kv"], "KV rows")
    if blob["draft_kv_rows"] is not None:
        if target["draft_kv"] is None:
            raise RunTransferError(
                "snapshot carries draft KV but the target engine has no "
                "draft model")
        check_side(blob["draft_kv_rows"], target["draft_kv"],
                   "draft KV rows")
    elif target["draft_kv"] is not None:
        # restorable (the draft pool just starts cold — correctness never
        # depends on draft KV), but the accept rate of the resumed stream
        # would silently collapse; the fleet treats this as a mismatch
        raise RunTransferError(
            "target engine is speculative but the snapshot has no draft "
            "KV rows — resume would decay to target-only throughput")
    pos = int(blob["pos"])
    plen = int(blob["prompt"].shape[0])
    budget = int(blob["req"]["max_new_tokens"])
    max_len = int(target["max_len"])
    if plen + budget > max_len:
        raise RunTransferError(
            f"run needs {plen} prompt + {budget} new tokens but the "
            f"target engine's max_len is {max_len}")
    if pos > max_len:
        raise RunTransferError(
            f"snapshot position {pos} exceeds target max_len {max_len}")
    if man["layers"] != len(blob["kv_rows"]):
        raise RunTransferError(
            f"manifest says {man['layers']} layers, blob carries "
            f"{len(blob['kv_rows'])} — corrupt snapshot")


def decode_run(blob: dict, req: Optional[Request] = None,
               resp: Optional[Response] = None,
               engine=None) -> PreemptedRun:
    """Blob -> PreemptedRun ready for `engine.restore_run`.

    In-process migration passes the ORIGINAL `req`/`resp` so the
    consumer's open stream continues uninterrupted; a subprocess replica
    omits them and the Request is rebuilt from the blob (the caller owns
    bridging the fresh Response back over its IPC).  Passing `engine`
    runs `check_compatible` first."""
    if engine is not None:
        check_compatible(blob, engine)
    if req is None:
        r = blob["req"]
        req = Request(r["id"], blob["prompt"], r["max_new_tokens"],
                      greedy=r["greedy"], temperature=r["temperature"],
                      top_k=r["top_k"], top_p=r["top_p"],
                      eos_token_id=r["eos_token_id"], seed=r["seed"],
                      deadline=r.get("deadline_remaining_s"),
                      priority=r["priority"], tenant=r["tenant"],
                      spec=r["spec"], session=r["session"],
                      resubmit=r["resubmit"],
                      adapter=r.get("adapter"))
    if resp is None:
        resp = Response(req)
    paused = PreemptedRun.from_state(
        req, resp, pos=blob["pos"], produced=blob["produced"],
        last_token=blob["last_token"], key=blob["key"],
        kv_rows=blob["kv_rows"], draft_kv_rows=blob["draft_kv_rows"])
    # keep the source hash on the decoded snapshot: a later re-encode
    # (manager-side migration hop) must not silently drop the check
    paused.source_config_hash = blob["manifest"].get("config_hash")
    return paused


def run_to_bytes(blob: dict) -> bytes:
    """Serialize a blob to one npz byte string (the subprocess wire
    format): arrays under indexed keys, scalars — including the codec
    version and the source engine's config hash — in a json header."""
    arrays = {"key": blob["key"], "prompt": blob["prompt"]}
    for i, (k, v) in enumerate(blob["kv_rows"]):
        arrays[f"k{i}"] = k
        arrays[f"v{i}"] = v
    if blob["draft_kv_rows"] is not None:
        for i, (k, v) in enumerate(blob["draft_kv_rows"]):
            arrays[f"dk{i}"] = k
            arrays[f"dv{i}"] = v
    header = {kk: blob[kk] for kk in ("version", "pos", "produced",
                                      "last_token", "req", "manifest")}
    arrays["header"] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8).copy()
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def run_from_bytes(data: bytes) -> dict:
    """Inverse of `run_to_bytes`.  Any malformed header — including a
    codec version this build does not speak — is the typed
    RunTransferError, never a KeyError deep in a pool write."""
    with np.load(io.BytesIO(data), allow_pickle=False) as z:
        try:
            header = json.loads(bytes(z["header"].tobytes()).decode())
        except Exception as e:
            raise RunTransferError(f"corrupt run snapshot header: {e!r}")
        if header.get("version") != TRANSFER_VERSION:
            raise RunTransferError(
                f"run snapshot codec version {header.get('version')!r} "
                f"!= {TRANSFER_VERSION} — refusing a wire format this "
                "build does not understand")
        n = header["manifest"]["layers"]
        kv = [(z[f"k{i}"], z[f"v{i}"]) for i in range(n)]
        dn = header["manifest"]["draft_layers"]
        draft = (None if dn is None
                 else [(z[f"dk{i}"], z[f"dv{i}"]) for i in range(dn)])
        blob = dict(header, key=z["key"], prompt=z["prompt"],
                    kv_rows=kv, draft_kv_rows=draft)
    return blob
