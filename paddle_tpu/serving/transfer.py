"""Replica-portable run transfer codec.

The PR-6 `engine.preempt_slot` snapshot (`PreemptedRun`: per-layer KV
row arrays + RNG key / write position / produced count / last token)
was designed for pause-and-resume on ONE engine.  This module
generalizes it into the unit of fleet failover: a preempted run encodes
to a pure-numpy blob (optionally to bytes — npz — for subprocess
replicas) that any CONFIGURATION-COMPATIBLE replica decodes back into a
`PreemptedRun` and feeds to its own `restore_run`, so a stream migrated
mid-decode resumes bit-identical to a run that never moved:

- the KV rows are the layout-agnostic ``(pos, heads, head_dim)`` row
  arrays both the fixed and paged snapshot paths already produce, so a
  run can migrate between fixed- and paged-pool replicas of the same
  model;
- the sampling state (raw RNG key + write position + produced count +
  last committed token) is exactly what decode step ``pos`` needs to
  fold the same key it would have folded uninterrupted;
- the request descriptor (prompt, budget, sampling knobs, seed, tenant,
  session, REMAINING deadline) rides along so a subprocess replica can
  rebuild the Request on its side of the wire — in-process migration passes the original
  Request/Response straight through instead (the consumer keeps
  iterating the same stream object).

Every compatibility axis is checked loudly: layer count, per-layer row
shapes and dtypes against the target engine's live pools, and the
position budget against the target's max_len.  A mismatch raises the
typed `RunTransferError` — a run must never be written into a pool it
does not fit, and a quiet shape cast would corrupt the stream it was
supposed to save.
"""
from __future__ import annotations

import io
import json
from typing import Optional

import numpy as np

from ..core.errors import InvalidArgumentError
from .engine import PreemptedRun
from .request import Request, Response

__all__ = ["RunTransferError", "encode_run", "decode_run", "run_to_bytes",
           "run_from_bytes", "check_compatible", "TRANSFER_VERSION"]

TRANSFER_VERSION = 1

# Request fields the codec carries so a subprocess replica can rebuild
# the request on its side of the wire (json-serializable scalars only)
_REQ_FIELDS = ("id", "max_new_tokens", "greedy", "temperature", "top_k",
               "top_p", "eos_token_id", "seed", "priority", "tenant",
               "spec", "session", "resubmit")


class RunTransferError(InvalidArgumentError):
    """The snapshot cannot be restored on the target replica: version,
    layer-count, shape, dtype, or length-budget mismatch.  Typed so the
    fleet can fail the stream terminally instead of corrupting it."""
    code = "InvalidArgument"


def encode_run(paused: PreemptedRun) -> dict:
    """PreemptedRun -> portable blob: pure numpy + scalars, no live
    object references.  The blob alone (via `run_to_bytes`) is enough to
    resume the stream in another process; in-process callers pass the
    original req/resp back to `decode_run` so the consumer's stream
    object survives the move."""
    kv = [(np.asarray(k), np.asarray(v)) for k, v in paused.kv_rows]
    draft = None
    if paused.draft_kv_rows is not None:
        draft = [(np.asarray(k), np.asarray(v))
                 for k, v in paused.draft_kv_rows]
    req = paused.req
    req_desc = {f: getattr(req, f) for f in _REQ_FIELDS}
    # the deadline crosses the wire as its REMAINING budget at encode
    # time (the Deadline object is anchored to this process's clock): a
    # migrated run must keep counting down, not get a fresh budget
    req_desc["deadline_remaining_s"] = (
        None if req.deadline is None else req.deadline.remaining())
    return {
        "version": TRANSFER_VERSION,
        "pos": int(paused.pos),
        "produced": int(paused.produced),
        "last_token": int(paused.last_token),
        "key": np.asarray(paused.key),
        "kv_rows": kv,
        "draft_kv_rows": draft,
        "prompt": np.asarray(req.prompt, np.int32),
        "req": req_desc,
        "manifest": {
            "layers": len(kv),
            "draft_layers": None if draft is None else len(draft),
            "kv_shapes": [(list(k.shape), list(v.shape)) for k, v in kv],
            "kv_dtypes": [(str(k.dtype), str(v.dtype)) for k, v in kv],
        },
    }


def check_compatible(blob: dict, engine) -> None:
    """Raise RunTransferError unless `blob` can restore into `engine`'s
    pools bit-exactly: same layer count, same per-row trailing shape and
    dtype per layer (target AND draft halves), remaining budget within
    the target's max_len, and a codec version this build understands."""
    if blob.get("version") != TRANSFER_VERSION:
        raise RunTransferError(
            f"run snapshot codec version {blob.get('version')!r} != "
            f"{TRANSFER_VERSION} — refusing a format this build does not "
            "understand")
    man = blob["manifest"]

    def check_side(rows, pools, what):
        if len(rows) != len(pools):
            raise RunTransferError(
                f"{what}: snapshot has {len(rows)} layers, target engine "
                f"has {len(pools)} — replicas must serve the same model")
        for i, ((rk, rv), (pk, pv)) in enumerate(zip(rows, pools)):
            for r, p, half in ((rk, pk, "k"), (rv, pv, "v")):
                # pool leaves are (slots|blocks, rows, heads, dim); a
                # snapshot row array is (pos, heads, dim) — trailing
                # dims must agree exactly
                if tuple(r.shape[1:]) != tuple(p.shape[2:]):
                    raise RunTransferError(
                        f"{what} layer {i}/{half}: snapshot row shape "
                        f"{tuple(r.shape[1:])} != target pool row shape "
                        f"{tuple(p.shape[2:])}")
                if r.dtype != p.dtype:
                    raise RunTransferError(
                        f"{what} layer {i}/{half}: snapshot dtype "
                        f"{r.dtype} != target pool dtype {p.dtype} — a "
                        "silent cast would break bit-identity")

    check_side(blob["kv_rows"], engine._pools, "KV rows")
    if blob["draft_kv_rows"] is not None:
        if engine.draft_model is None:
            raise RunTransferError(
                "snapshot carries draft KV but the target engine has no "
                "draft model")
        check_side(blob["draft_kv_rows"], engine._draft_pools,
                   "draft KV rows")
    elif engine.draft_model is not None:
        # restorable (the draft pool just starts cold — correctness never
        # depends on draft KV), but the accept rate of the resumed stream
        # would silently collapse; the fleet treats this as a mismatch
        raise RunTransferError(
            "target engine is speculative but the snapshot has no draft "
            "KV rows — resume would decay to target-only throughput")
    pos = int(blob["pos"])
    plen = int(blob["prompt"].shape[0])
    budget = int(blob["req"]["max_new_tokens"])
    if plen + budget > engine.max_len:
        raise RunTransferError(
            f"run needs {plen} prompt + {budget} new tokens but the "
            f"target engine's max_len is {engine.max_len}")
    if pos > engine.max_len:
        raise RunTransferError(
            f"snapshot position {pos} exceeds target max_len "
            f"{engine.max_len}")
    if man["layers"] != len(blob["kv_rows"]):
        raise RunTransferError(
            f"manifest says {man['layers']} layers, blob carries "
            f"{len(blob['kv_rows'])} — corrupt snapshot")


def decode_run(blob: dict, req: Optional[Request] = None,
               resp: Optional[Response] = None,
               engine=None) -> PreemptedRun:
    """Blob -> PreemptedRun ready for `engine.restore_run`.

    In-process migration passes the ORIGINAL `req`/`resp` so the
    consumer's open stream continues uninterrupted; a subprocess replica
    omits them and the Request is rebuilt from the blob (the caller owns
    bridging the fresh Response back over its IPC).  Passing `engine`
    runs `check_compatible` first."""
    if engine is not None:
        check_compatible(blob, engine)
    if req is None:
        r = blob["req"]
        req = Request(r["id"], blob["prompt"], r["max_new_tokens"],
                      greedy=r["greedy"], temperature=r["temperature"],
                      top_k=r["top_k"], top_p=r["top_p"],
                      eos_token_id=r["eos_token_id"], seed=r["seed"],
                      deadline=r.get("deadline_remaining_s"),
                      priority=r["priority"], tenant=r["tenant"],
                      spec=r["spec"], session=r["session"],
                      resubmit=r["resubmit"])
    if resp is None:
        resp = Response(req)
    return PreemptedRun.from_state(
        req, resp, pos=blob["pos"], produced=blob["produced"],
        last_token=blob["last_token"], key=blob["key"],
        kv_rows=blob["kv_rows"], draft_kv_rows=blob["draft_kv_rows"])


def run_to_bytes(blob: dict) -> bytes:
    """Serialize a blob to one npz byte string (the subprocess wire
    format): arrays under indexed keys, scalars in a json header."""
    arrays = {"key": blob["key"], "prompt": blob["prompt"]}
    for i, (k, v) in enumerate(blob["kv_rows"]):
        arrays[f"k{i}"] = k
        arrays[f"v{i}"] = v
    if blob["draft_kv_rows"] is not None:
        for i, (k, v) in enumerate(blob["draft_kv_rows"]):
            arrays[f"dk{i}"] = k
            arrays[f"dv{i}"] = v
    header = {kk: blob[kk] for kk in ("version", "pos", "produced",
                                      "last_token", "req", "manifest")}
    arrays["header"] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8).copy()
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def run_from_bytes(data: bytes) -> dict:
    """Inverse of `run_to_bytes`."""
    with np.load(io.BytesIO(data), allow_pickle=False) as z:
        try:
            header = json.loads(bytes(z["header"].tobytes()).decode())
        except Exception as e:
            raise RunTransferError(f"corrupt run snapshot header: {e!r}")
        n = header["manifest"]["layers"]
        kv = [(z[f"k{i}"], z[f"v{i}"]) for i in range(n)]
        dn = header["manifest"]["draft_layers"]
        draft = (None if dn is None
                 else [(z[f"dk{i}"], z[f"dv{i}"]) for i in range(dn)])
        blob = dict(header, key=z["key"], prompt=z["prompt"],
                    kv_rows=kv, draft_kv_rows=draft)
    return blob
