"""SLO-aware admission policy objects for the serving gateway.

Pure host-side policy — no jax, no engine state.  The gateway feeds these
objects live signals (its lane depths, the engine's slot occupancy, the
TTFT samples it observes) and they answer the two admission questions:

- **May this tenant send right now?**  `TokenBucket` per tenant: classic
  rate/burst limiting, consulted at submit time so a rate-limited request
  is rejected before it costs a queue entry, a prefill, or a slot.
- **Should this arrival be shed?**  `ShedPolicy.decide` — reject
  cheap-to-reject work EARLY (at submit, with a typed terminal response)
  instead of letting it time out expensively late (after queue residence
  + prefill + partial decode).  Driven by live signals: lane depth, slot
  occupancy, the recent TTFT tail, and a queue-wait estimate derived from
  the measured per-request service time.

The reference framework's front door exposes thread-pool/queue knobs per
AnalysisPredictor instance but degrades every caller equally under
overload; this is the missing production half — per-tenant isolation and
an explicit, observable shedding decision.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

__all__ = ["TokenBucket", "TenantConfig", "SLOTracker", "Signals",
           "ShedPolicy"]


class TokenBucket:
    """Classic token bucket: `rate` tokens/sec refill up to `burst`
    capacity; `try_take(cost)` is all-or-nothing.  Thread-safe (submit
    runs on caller threads).  rate=inf means unlimited."""

    def __init__(self, rate: float, burst: Optional[float] = None,
                 _clock: Callable[[], float] = time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else max(1.0, rate))
        self._level = self.burst
        self._clock = _clock
        self._t = _clock()
        self._lock = threading.Lock()

    def _refill(self):
        now = self._clock()
        self._level = min(self.burst,
                          self._level + (now - self._t) * self.rate)
        self._t = now

    def try_take(self, cost: float = 1.0) -> bool:
        if self.rate == float("inf"):
            return True
        with self._lock:
            self._refill()
            if self._level >= cost:
                self._level -= cost
                return True
            return False

    def level(self) -> float:
        with self._lock:
            self._refill()
            return self._level


class TenantConfig:
    """Per-tenant admission parameters.

    rate / burst    token-bucket rate limit in requests/sec (rate=inf
                    disables limiting; burst defaults to max(1, rate))
    weight          share of admission bandwidth relative to other tenants
                    with queued work (stride scheduling: a weight-2 tenant
                    is admitted twice as often as a weight-1 tenant while
                    both have requests waiting)
    max_priority    highest priority lane this tenant may use (requests
                    asking for more are clamped — priority is a tenant
                    entitlement, not a caller free-for-all)
    kv_share_group  prefix-cache share partition.  None (default) keeps
                    the tenant's cached KV blocks private to it; tenants
                    naming the same group share each other's cached
                    prefixes.  Cross-group reuse is impossible by
                    construction (serving/prefix_cache.py).
    adapter         LoRA adapter registry NAME this tenant decodes under
                    (paddle_tpu.lora).  None (default) serves the base
                    model.  The gateway stamps it on every request the
                    tenant submits; an adapter that is not loaded on the
                    engine fails the request typed
                    (AdapterNotFoundError) through the normal admission
                    path — never a hung consumer.
    """

    __slots__ = ("rate", "burst", "weight", "max_priority",
                 "kv_share_group", "adapter")

    def __init__(self, rate: float = float("inf"),
                 burst: Optional[float] = None, weight: float = 1.0,
                 max_priority: int = 1,
                 kv_share_group: Optional[str] = None,
                 adapter: Optional[str] = None):
        self.rate = float(rate)
        self.burst = burst
        self.weight = float(weight)
        if self.weight <= 0:
            raise ValueError(f"tenant weight must be positive, got {weight}")
        self.max_priority = int(max_priority)
        self.kv_share_group = kv_share_group
        self.adapter = adapter

    def make_bucket(self) -> TokenBucket:
        return TokenBucket(self.rate, self.burst)


class SLOTracker:
    """Sliding-window latency tracker feeding the shed decision.

    - `note_ttft(lane, seconds)`: TTFT samples per lane, windowed by
      count AND age (stale burst samples must not shed an idle system);
      `ttft_p99(lane)` is the live tail the policy checks against the
      SLO target.
    - `note_service(seconds)`: completed-request service time (first
      token -> terminal — queue wait excluded so congestion cannot feed
      back into the estimate), EWMA-smoothed; `est_wait(depth, slots)`
      turns a lane depth into an expected queue wait — the
      cheap-to-compute signal that lets the gateway reject a request
      that would time out anyway.
    """

    def __init__(self, window: int = 256, ewma_alpha: float = 0.2,
                 max_age: float = 30.0,
                 _clock: Callable[[], float] = time.monotonic):
        self._window = int(window)
        self._alpha = float(ewma_alpha)
        # samples older than max_age drop out of the tail: without time
        # decay, a burst's over-SLO p99 would keep slo_pressure shedding
        # the low lane forever after the system went idle (the window
        # only turns over when NEW high-lane requests complete)
        self._max_age = float(max_age)
        self._clock = _clock
        self._ttft: Dict[str, deque] = {}
        self._service_ewma: Optional[float] = None
        self._lock = threading.Lock()

    def _prune(self, dq: deque):
        horizon = self._clock() - self._max_age
        while dq and dq[0][0] < horizon:
            dq.popleft()

    def note_ttft(self, lane: str, seconds: float):
        with self._lock:
            dq = self._ttft.setdefault(lane, deque(maxlen=self._window))
            self._prune(dq)
            dq.append((self._clock(), float(seconds)))

    def note_service(self, seconds: float):
        with self._lock:
            if self._service_ewma is None:
                self._service_ewma = float(seconds)
            else:
                self._service_ewma += self._alpha * (
                    float(seconds) - self._service_ewma)

    def ttft_p99(self, lane: str) -> Optional[float]:
        with self._lock:
            dq = self._ttft.get(lane)
            if dq is None:
                return None
            self._prune(dq)
            if not dq:
                return None
            xs = sorted(v for _, v in dq)
            return xs[min(len(xs) - 1, int(0.99 * len(xs)))]

    def service_ewma(self) -> Optional[float]:
        with self._lock:
            return self._service_ewma

    def est_wait(self, queue_depth: int, slots: int) -> Optional[float]:
        """Expected wait for a request arriving behind `queue_depth`
        others on a `slots`-wide engine, from the service-time EWMA.
        None until at least one request has completed."""
        s = self.service_ewma()
        if s is None or slots <= 0:
            return None
        return queue_depth * s / slots


class Signals:
    """Live admission signals, assembled by the gateway per decision."""

    __slots__ = ("lane_depth", "total_depth", "occupancy", "free_slots",
                 "max_slots", "ttft_p99_hi", "est_wait", "paused")

    def __init__(self, lane_depth=0, total_depth=0, occupancy=0,
                 free_slots=0, max_slots=0, ttft_p99_hi=None, est_wait=None,
                 paused=0):
        self.lane_depth = lane_depth      # waiting in THIS request's lane
        self.total_depth = total_depth    # waiting across all lanes
        self.occupancy = occupancy
        self.free_slots = free_slots
        self.max_slots = max_slots
        self.ttft_p99_hi = ttft_p99_hi    # seconds, high lane, or None
        self.est_wait = est_wait          # seconds, this lane, or None
        self.paused = paused              # preempted runs awaiting restore


class ShedPolicy:
    """Early-rejection rules, checked in order at submit time.

    max_lane_depth      lane depth cap; an arrival past it is shed
                        ("queue_depth") — bounded queues are the
                        backpressure primitive
    max_est_wait        shed ("est_wait") when the measured service rate
                        says the request would wait longer than this
                        before even starting; None disables
    ttft_slo            high-lane TTFT target in seconds; while the live
                        p99 is above it, LOW-priority arrivals are shed
                        ("slo_pressure") so the high lane recovers —
                        shedding the cheap lane early is what keeps the
                        expensive lane's tail inside the SLO
    shed_priority_below requests with priority >= this value are exempt
                        from est_wait/slo_pressure shedding (they may
                        still hit the hard lane-depth cap)
    """

    def __init__(self, max_lane_depth: int = 64,
                 max_est_wait: Optional[float] = None,
                 ttft_slo: Optional[float] = None,
                 shed_priority_below: int = 1):
        self.max_lane_depth = int(max_lane_depth)
        self.max_est_wait = max_est_wait
        self.ttft_slo = ttft_slo
        self.shed_priority_below = int(shed_priority_below)

    def decide(self, sig: Signals, priority: int) -> Optional[str]:
        """Shed reason, or None to admit."""
        if sig.lane_depth >= self.max_lane_depth:
            return "queue_depth"
        if priority >= self.shed_priority_below:
            return None
        if (self.max_est_wait is not None and sig.est_wait is not None
                and sig.est_wait > self.max_est_wait):
            return "est_wait"
        if (self.ttft_slo is not None and sig.ttft_p99_hi is not None
                and sig.ttft_p99_hi > self.ttft_slo):
            return "slo_pressure"
        return None
