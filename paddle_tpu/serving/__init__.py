"""paddle_tpu.serving — continuous-batching inference engine.

The ROADMAP's "serves heavy traffic from millions of users" surface: where
`inference.Predictor` runs one whole-batch program per call and
`generation.generate` owns a compiled `(batch, prompt_len, max_new)` loop
per shape, the ServingEngine keeps ONE resident slot-based KV-cache pool
and exactly two compiled program families — bucketed prefill and a single
all-slots decode step — that requests join and leave between iterations.
This is the TPU-native equivalent of the reference's AnalysisPredictor +
dynamic_decode deployment path, redesigned for continuous batching.

Model protocol contract
-----------------------
Any model can be served if it implements the fixed-cache decode protocol
(`models/gpt.py:190,201` is the reference implementation):

- ``gen_fixed_cache(batch_size, max_length, dtype=None)`` returns the
  per-layer KV buffers as a list of ``(k, v)`` RAW jax arrays, each of
  shape ``(batch_size, max_length, heads, head_dim)`` (any per-layer pytree
  with a leading batch axis on every leaf works — the engine only ever
  slices/maps axis 0 and axis 1 of each leaf).
- ``forward_fixed(input_ids, caches, pos)`` runs the model over
  ``input_ids`` (B, S) with the chunk's KV written into the fixed buffers
  at ``[pos, pos + S)`` (``pos`` may be a traced scalar), attention masked
  causally so query ``i`` sees buffer slots ``<= pos + i``, and returns
  ``(logits, new_caches)``.  Content of the buffers at positions
  ``> pos + S`` must never influence the output (the engine relies on this
  to reuse slots without scrubbing, and ADDITIONALLY overwrites the full
  slot range at prefill).

Engine lifecycle
----------------
::

    engine = ServingEngine(model, max_slots=8, max_len=256,
                           prefill_buckets=(16, 32, 64), max_queue_depth=64)
    engine.warmup()          # compile len(buckets) + 1 programs, the total
    engine.start()           # background loop (or drive step() yourself)
    resp = engine.submit(prompt_ids, max_new_tokens=64,
                         eos_token_id=eos, deadline=30.0)
    for tok in resp:         # streams as decoded; TTFT at first yield
        ...
    engine.close()

Guarantees: compilation count ≤ len(prefill_buckets) + 1 programs per
engine regardless of traffic mix (`compile_counts()` asserts it); greedy
requests are bit-identical to a solo `generation.generate` of the same
prompt; one poisoned/expired/cancelled request only ever costs its own
slot.

Speculative decoding
--------------------
``ServingEngine(model, draft_model=small_model, spec_tokens=K)`` swaps
the decode program for ONE verify program: the draft proposes K tokens
per tick (its own slot pool, same protocol), the target scores all K+1
positions in one batched forward, and the longest accepted prefix plus a
corrected token commits in-program (`generation.speculative` — greedy
argmax-equality accept, distribution-preserving rejection sampling for
sampling slots).  The program bound is unchanged; per-request
``submit(..., spec=False)`` opts out inside the same trace; greedy
streams stay bit-identical to solo generate at ANY draft quality.
Quantize the served weights with
``quantization.quantize_for_serving(model)`` (int8 weight-only,
dequant-at-use) — composable with speculation and with the gateway.  See
the README "Speculative + quantized decoding" section.

Distributed serving
-------------------
``ServingEngine(kv="paged", block_size=B, num_blocks=N)`` swaps the
slot-row pool for ONE block pool per layer (`PagedKVPool`,
serving/kv_pool.py): block-granular KV allocation with per-slot block
tables, so long and short requests share HBM instead of every slot
paying ``max_len`` — ≥2x resident slots in the same KV byte budget on
mixed traffic (probes/paged_serving_probe.py).  Recycled blocks are
scrubbed in-program at re-serve, exhaustion is backpressure (admission
waits, mid-decode shortfall preempts the newest low-priority run and
resumes it later; `KVPoolExhaustedError` is the typed terminal state;
``PDTPU_FAULT_KV_EXHAUST=N`` forces it all).  ``mesh=`` runs the whole
engine tensor-parallel over a `jax.sharding.Mesh` — Megatron param
layout, heads-sharded KV pool, same program count, streams bit-identical
to the single-device engine.  Both compose with the gateway,
speculation, and quantization.  See the README "Distributed serving"
section.

Gateway
-------
`ServingGateway` (gateway.py + slo.py) is the multi-tenant front door
over the engine: per-tenant token-bucket rate limits with stride-fair
weighted admission, priority lanes whose high-priority arrivals preempt
resumable low-priority decodes (slot KV rows + sampling state snapshotted
to host via `engine.preempt_slot`, restored bit-identical via
`engine.restore_run` — zero extra compiled programs), SLO-driven load
shedding (`ShedPolicy` over live lane depth / occupancy / TTFT-p99
signals), and an OpenAI-shaped streaming HTTP endpoint
(`GatewayServer`, port-free `gateway.handle()` for tests).  Every
admission outcome — shed, rate-limited, expired, preempted-then-cancelled
— is a terminal Response: no consumer ever hangs.  See the README
"Gateway" section.

Fleet
-----
`FleetRouter` + `ReplicaManager` (fleet.py) front N engine replicas:
least-loaded routing with session affinity, health from
warmup/step-time/heartbeat evidence, crash/brownout fencing with
failover — in-flight runs migrate between replicas bit-identical
through the run-transfer codec (transfer.py, the PR-6 preempt/restore
snapshot made replica-portable), runs whose snapshot died with a
crashed replica are re-prefilled from the prompt (``resubmit=True``,
greedy-only) or fail with the typed `ReplicaLostError` — and
`drain()`/`rollout()` give zero-downtime weight/program rollouts.
``ServingGateway(fleet, ...)`` turns the multi-tenant front door into a
cluster front door.  ``fleet.add_worker(spec)`` makes a replica its own
OS process (serving/worker.py): a subprocess engine worker booted from
a model-factory spec + AOT program set, spoken to over a
length-prefixed npz RPC, with OUT-OF-BAND heartbeat liveness (a wedged
step — the hang an in-process fleet cannot survive — fences on
heartbeat age, is SIGKILLed after a grace period, and is restarted by
the supervisor with backoff under a budget).  See the README "Fleet
serving" section.

Program lifecycle
-----------------
`engine.warmup()` precompiles the whole program family before traffic
(returns a compile report; `post_warmup_compiles()` asserts ZERO compiles
under any later traffic mix), `engine.save_program_set(path)` serializes
the family as one AOT artifact, and ``ServingEngine(program_set=path)`` /
``enable_serving(program_set=path)`` boots from it without retracing —
see `paddle_tpu.programs` and the README "Program lifecycle" section.

Metrics (all live under `metrics()`, the STAT_serving_* monitor counters,
and — with profiling enabled — the profiler report): ttft_p50_ms,
inter_token_ms, tokens_per_sec, queue_depth, slot_occupancy,
requests_completed/errored, STAT_serving_{requests,rejects,tokens,
prefills,decode_steps,compiles,queue_depth,slots_active,cancelled,
deadline_expired,nonfinite}.
"""
from __future__ import annotations

from .engine import ServingEngine, NonFiniteLogitsError, PreemptedRun
from .kv_pool import PagedKVPool, KVPoolExhaustedError
from .prefix_cache import PrefixCache
from .request import Request, Response, RequestCancelled
from .scheduler import (RequestScheduler, QueueFullError,
                        DeadlineExceededError)
from .slo import ShedPolicy, Signals, SLOTracker, TenantConfig, TokenBucket
from .gateway import (ServingGateway, GatewayServer, RateLimitedError,
                      SheddedError, serve_gateway, PRIORITY_HIGH,
                      PRIORITY_LOW)
from .fleet import (FleetRouter, ReplicaManager, Replica,
                    SubprocessReplica, RestartBackoff, ReplicaLostError)
from .transfer import (RunTransferError, encode_run, decode_run,
                       run_to_bytes, run_from_bytes, engine_config_hash)
from .worker import WorkerClient, WorkerDiedError, WireFormatError
from .refresh import WeightPublisher, FleetRefresher, latest_publish
from .autoscaler import Autoscaler

__all__ = [
    "ServingEngine", "Request", "Response", "RequestScheduler",
    "QueueFullError", "DeadlineExceededError", "RequestCancelled",
    "NonFiniteLogitsError", "PreemptedRun",
    # distributed serving (paged KV pool + tensor-parallel engine)
    "PagedKVPool", "KVPoolExhaustedError", "PrefixCache",
    # gateway (multi-tenant SLO-aware admission over the engine)
    "ServingGateway", "GatewayServer", "serve_gateway", "TenantConfig",
    "TokenBucket", "ShedPolicy", "Signals", "SLOTracker",
    "RateLimitedError", "SheddedError", "PRIORITY_HIGH", "PRIORITY_LOW",
    # fleet (multi-replica router: health-driven failover, run
    # migration, zero-downtime rollout, supervised subprocess workers)
    "FleetRouter", "ReplicaManager", "Replica", "SubprocessReplica",
    "RestartBackoff", "ReplicaLostError",
    "RunTransferError", "encode_run", "decode_run", "run_to_bytes",
    "run_from_bytes", "engine_config_hash",
    # subprocess worker replicas (process isolation + heartbeat)
    "WorkerClient", "WorkerDiedError", "WireFormatError",
    # train->serve loop (continuous weight refresh + elastic capacity)
    "WeightPublisher", "FleetRefresher", "latest_publish", "Autoscaler",
]
