"""SLO-driven elastic capacity for a replica fleet.

The autoscaler closes the loop between the gateway's admission-time SLO
signals (`ServingGateway.scale_signals`: estimated queue wait, lane
depths, shed counters) and fleet membership: sustained overload spawns
replicas, sustained idleness retires them — and a retirement is ALWAYS
a drain (`FleetRouter.drain` + deferred remove), never a kill, so no
stream is ever dropped for capacity reasons.

Three dampers keep it from flapping:

* **hysteresis** — scale-up needs `breach_ticks` CONSECUTIVE breached
  ticks (est-wait over threshold, or fresh sheds); scale-down needs
  `idle_ticks` consecutive idle ticks (empty queue, est-wait under the
  idle threshold, no sheds).  A single spiky tick resets the opposite
  streak and moves nothing.
* **cooldown** — after any action, no further action for `cooldown_s`
  (booting capacity must land before it can be judged insufficient).
* **bounds** — membership stays within [min_replicas, max_replicas];
  BOOTING replicas count toward the bound so one sustained breach
  cannot spawn a thundering herd while the first spawn warms.

Who-wins with concurrent fleet ops: the autoscaler never retires a
replica that is mid-weight-flip (`rep.flipping`) or already DRAINING,
and a replica it retires is skipped by the refresher's convergence
sweep (flips require liveness; a drained replica is removed).  The
`spawn` callable owns replica construction — in-process engine factory
or `fleet.add_worker(spec)` — so scale-up capacity converges onto the
current verified weights via the refresher's sweep once warm.

Runs OFF the driving thread (like the refresher): `tick()` only calls
thread-safe fleet surfaces.  Drive it manually (tests inject `_clock`)
or with `start()`.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..core.errors import InvalidArgumentError
from .fleet import BOOTING, DEGRADED, DRAINING, HEALTHY

__all__ = ["Autoscaler"]


class Autoscaler:
    def __init__(self, fleet, signals: Callable[[], Dict],
                 spawn: Callable[[], Optional[int]],
                 min_replicas: int = 1, max_replicas: int = 4,
                 scale_up_est_wait_s: float = 0.5,
                 idle_est_wait_s: Optional[float] = None,
                 breach_ticks: int = 3, idle_ticks: int = 10,
                 cooldown_s: float = 10.0,
                 _clock=time.monotonic):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise InvalidArgumentError(
                "need 1 <= min_replicas <= max_replicas "
                f"(got {min_replicas}..{max_replicas})")
        self.fleet = fleet
        self.signals = signals
        self.spawn = spawn
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_est_wait_s = float(scale_up_est_wait_s)
        self.idle_est_wait_s = (float(idle_est_wait_s)
                                if idle_est_wait_s is not None
                                else self.scale_up_est_wait_s * 0.25)
        self.breach_ticks = max(1, int(breach_ticks))
        self.idle_ticks = max(1, int(idle_ticks))
        self.cooldown_s = float(cooldown_s)
        self._clock = _clock
        self._breach = 0
        self._idle = 0
        self._last_action_t: Optional[float] = None
        self._last_shed = 0
        self._last_error: Optional[str] = None
        # every action, for flap analysis: {"dir", "t", "replicas"}
        self.actions: List[Dict] = []
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- introspection -------------------------------------------------
    def status(self) -> Dict:
        with self._lock:
            return {
                "breach_streak": self._breach,
                "idle_streak": self._idle,
                "actions": len(self.actions),
                "scale_ups": sum(1 for a in self.actions
                                 if a["dir"] == "up"),
                "scale_downs": sum(1 for a in self.actions
                                   if a["dir"] == "down"),
                "last_error": self._last_error,
            }

    def _counts(self):
        """(serving_or_booting, retiring): DRAINING replicas are
        already-decided retirements, not capacity."""
        reps = self.fleet.manager.replicas(
            (BOOTING, HEALTHY, DEGRADED, DRAINING))
        live = [r for r in reps if r.state != DRAINING]
        return live, [r for r in reps if r.state == DRAINING]

    # -- one decision cycle --------------------------------------------
    def tick(self) -> Optional[str]:
        """One decision: observe signals, advance the streaks, maybe
        act.  Returns "up"/"down" when an action was taken, else
        None."""
        now = self._clock()
        try:
            sig = self.signals()
        except Exception as e:  # noqa: BLE001 — a dead gateway is idle
            with self._lock:
                self._last_error = (
                    f"signals failed: {type(e).__name__}: {e}")
            return None
        est_wait = float(sig.get("est_wait_s") or 0.0)
        depth = int(sig.get("queue_depth") or 0)
        shed_total = int(sig.get("shed_total") or 0)
        with self._lock:
            shed_delta = shed_total - self._last_shed
            self._last_shed = shed_total
            breach = (est_wait > self.scale_up_est_wait_s
                      or shed_delta > 0)
            idle = (not breach and depth == 0
                    and est_wait <= self.idle_est_wait_s)
            if breach:
                self._breach += 1
                self._idle = 0
            elif idle:
                self._idle += 1
                self._breach = 0
            else:
                # the comfortable middle: demand matches capacity
                self._breach = 0
                self._idle = 0
            in_cooldown = (self._last_action_t is not None
                           and now - self._last_action_t
                           < self.cooldown_s)
            want_up = self._breach >= self.breach_ticks
            want_down = self._idle >= self.idle_ticks
        live, _ = self._counts()
        manager = self.fleet.manager
        action = None
        if want_up and not in_cooldown and len(live) < self.max_replicas:
            try:
                self.spawn()
                action = "up"
            except Exception as e:  # noqa: BLE001 — spawn host errors
                with self._lock:
                    self._last_error = (
                        f"spawn failed: {type(e).__name__}: {e}")
        elif want_down and not in_cooldown \
                and len(live) > self.min_replicas:
            victim = self._pick_victim(live)
            if victim is not None:
                # drain, never kill: residents migrate/finish, then the
                # deferred remove (remove-of-DRAINING) reaps it
                self.fleet.drain(victim.id)
                self.fleet.remove(victim.id)
                action = "down"
        if action is not None:
            with self._lock:
                self._last_action_t = now
                self._breach = 0
                self._idle = 0
                self.actions.append({"dir": action, "t": now,
                                     "replicas": len(live)})
            manager.note_scale(action == "up")
        live, _ = self._counts()
        manager.set_target_replicas(len(live))
        return action

    def _pick_victim(self, live):
        """Least-loaded routable replica that is not mid-flip; None
        defers the retirement a tick rather than racing a refresh."""
        cands = [r for r in live
                 if r.state == HEALTHY and not r.flipping]
        if not cands:
            return None
        return min(cands, key=lambda r: r.load())

    # -- background loop ----------------------------------------------
    def start(self, tick_interval_s: float = 0.25):
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.tick()
                except Exception as e:  # noqa: BLE001 — keep scaling
                    with self._lock:
                        self._last_error = (
                            f"tick failed: {type(e).__name__}: {e}")
                self._stop.wait(tick_interval_s)

        self._thread = threading.Thread(target=loop,
                                        name="fleet-autoscaler",
                                        daemon=True)
        self._thread.start()

    def close(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
