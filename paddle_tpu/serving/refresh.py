"""Continuous weight refresh: the train->serve side of the loop.

A trainer publishes checkpoints into a watch directory with the same
atomic-rename contract the distributed checkpoint writer uses (tmp dir
-> fsync -> ``os.rename`` -> fsync parent -> atomic LATEST pointer), so
a reader can NEVER observe a half-written publish.  The serving side
watches that directory and walks every new publish through three gates
before the fleet converges onto it:

1. **artifact gate** — the whole-file sha256 must match the manifest
   (the manifest sha is computed from the good bytes BEFORE the rename,
   so any post-publish corruption is detectable);
2. **oracle gate** — an in-process reference engine swaps to the new
   weights and generates the expected canary streams (shape/key
   mismatches die here, before any serving replica is touched);
3. **canary gate** — exactly ONE routable replica is flipped
   (`FleetRouter.flip_weights`: fence -> idle boundary -> zero-recompile
   swap) and its canary streams must be BIT-IDENTICAL to the oracle's.

A publish that fails any gate is quarantined by content hash and the
canary replica is flipped back to the last verified weights — a corrupt
or regressed checkpoint degrades to "keep serving the old model", never
to an outage.  Only after the canary passes does the refresher converge
every remaining replica (and, via the updated restart lineage and its
own convergence sweep, every replica that boots later).

The refresher runs OFF the fleet's driving thread: it only schedules
flips and polls their entries, so something else (the gateway loop or
``fleet.start()``) must be driving ``fleet.step()``.

Chaos knobs (utils.faults): ``PDTPU_FAULT_PUBLISH_CORRUPT=n`` bit-rots
the n-th published artifact AFTER the atomic rename (gate 1 must catch
it); ``PDTPU_FAULT_CANARY_DIVERGE=1`` forces the canary comparison to
fail (gate 3's rollback choreography, drillable on demand).
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.errors import InvalidArgumentError
from ..distributed.checkpoint import _fsync_dir, _write_atomic
from ..utils import faults
from .fleet import DEGRADED, HEALTHY
from .transfer import file_sha256

__all__ = ["WeightPublisher", "latest_publish", "FleetRefresher"]

_PUSH_DIR_RE = re.compile(r"^push-(\d{9})$")
_LATEST = "LATEST"
_MANIFEST = "manifest.json"
_WEIGHTS = "weights.npz"


# ---------------------------------------------------------------------------
# trainer side: atomic publishes
# ---------------------------------------------------------------------------

class WeightPublisher:
    """Writes ``push-<step>/{weights.npz, manifest.json}`` publishes a
    refresher can trust: the npz and manifest are written and fsynced in
    a hidden tmp dir, the manifest records the sha256 of the GOOD npz
    bytes, and one ``os.rename`` makes the publish visible — followed by
    an atomic LATEST pointer update.  A crash mid-publish leaves only an
    invisible tmp dir; a publish corrupted after the rename still
    carries the pre-corruption sha and fails the refresher's artifact
    gate."""

    def __init__(self, directory: str):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._lock = threading.Lock()
        # resume numbering past anything already on disk
        step = 0
        try:
            for name in os.listdir(self.directory):
                m = _PUSH_DIR_RE.match(name)
                if m:
                    step = max(step, int(m.group(1)) + 1)
        except OSError:
            pass
        self._step = step

    def publish(self, model=None, state: Optional[Dict] = None,
                step: Optional[int] = None) -> Dict:
        """Publish one weight set (a Layer via ``model=`` or a host
        state dict via ``state=``); returns
        ``{"dir", "step", "sha256", "path"}``."""
        if (model is None) == (state is None):
            raise InvalidArgumentError(
                "publish takes exactly one of model= or state=")
        if model is not None:
            from ..jit import state_arrays
            state = state_arrays(model)
        arrs = {k: np.asarray(v) for k, v in state.items()}
        with self._lock:
            explicit = step is not None
            step = self._step if step is None else int(step)
            if not explicit:
                # another publisher (or a previous process) may have
                # taken this number: auto-assigned steps skip forward
                while os.path.exists(os.path.join(
                        self.directory, f"push-{step:09d}")):
                    step += 1
            self._step = max(self._step, step) + 1
        name = f"push-{step:09d}"
        final_dir = os.path.join(self.directory, name)
        if os.path.exists(final_dir):
            raise InvalidArgumentError(
                f"publish step {step} already exists at {final_dir}")
        tmp_dir = os.path.join(self.directory,
                               f".{name}.tmp-{os.getpid()}")
        os.makedirs(tmp_dir)
        npz_tmp = os.path.join(tmp_dir, _WEIGHTS)
        with open(npz_tmp, "wb") as f:
            np.savez(f, **arrs)
            f.flush()
            os.fsync(f.fileno())
        # sha of the good bytes, BEFORE the rename: later corruption of
        # the visible artifact can only ever DISAGREE with the manifest
        sha = file_sha256(npz_tmp)
        with open(os.path.join(tmp_dir, _MANIFEST), "w") as f:
            json.dump({"step": step, "sha256": sha, "file": _WEIGHTS,
                       "keys": len(arrs)}, f)
            f.flush()
            os.fsync(f.fileno())
        _fsync_dir(tmp_dir)
        os.rename(tmp_dir, final_dir)
        _fsync_dir(self.directory)
        _write_atomic(os.path.join(self.directory, _LATEST), name)
        path = os.path.join(final_dir, _WEIGHTS)
        # chaos: bit-rot the artifact AFTER it became visible — the
        # manifest still carries the good sha, so the refresher's
        # artifact gate (not luck) must keep this off the fleet
        faults.maybe_corrupt_publish(path)
        return {"dir": final_dir, "step": step, "sha256": sha,
                "path": path}


def _load_publish(d: str) -> Optional[Dict]:
    try:
        with open(os.path.join(d, _MANIFEST)) as f:
            man = json.load(f)
    except (OSError, ValueError):
        return None
    path = os.path.join(d, str(man.get("file") or _WEIGHTS))
    if not man.get("sha256") or not os.path.exists(path):
        return None
    return {"dir": d, "step": int(man.get("step", -1)),
            "sha256": str(man["sha256"]), "path": path}


def latest_publish(directory: str) -> Optional[Dict]:
    """Newest complete publish in `directory`, or None.  The LATEST
    pointer is a hint; a missing/torn pointer falls back to scanning
    push-* dirs newest-first for one with a valid manifest (the same
    stance as checkpoint.latest_step_dir)."""
    try:
        with open(os.path.join(directory, _LATEST)) as f:
            hint = f.read().strip()
    except OSError:
        hint = ""
    if hint and _PUSH_DIR_RE.match(hint):
        pub = _load_publish(os.path.join(directory, hint))
        if pub is not None:
            return pub
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    for name in sorted((n for n in names if _PUSH_DIR_RE.match(n)),
                       reverse=True):
        pub = _load_publish(os.path.join(directory, name))
        if pub is not None:
            return pub
    return None


# ---------------------------------------------------------------------------
# serving side: watch -> verify -> canary -> converge (or roll back)
# ---------------------------------------------------------------------------

class FleetRefresher:
    """Watches a publish directory and walks the fleet onto each new
    weight set through the three gates described in the module
    docstring.  `oracle` is an in-process ServingEngine built from the
    same model config as the fleet's replicas (deterministic greedy
    decode makes its canary streams the bit-exact reference); it must
    NOT be started — the refresher drives it synchronously — and should
    be warmed by the caller before traffic starts if post-warmup
    compiles are being asserted.

    ``sha_ok()`` backs the fleet's ``routable_verified`` health field:
    a replica serving a quarantined sha never counts as verified
    capacity, and the gateway's /healthz turns 503 when NO routable
    replica serves verified weights."""

    def __init__(self, fleet, directory: str, oracle,
                 canary_prompts: Sequence[Sequence[int]] = ((1, 2, 3),),
                 canary_max_new_tokens: int = 8,
                 poll_interval_s: float = 0.25,
                 flip_timeout_s: float = 120.0,
                 canary_timeout_s: float = 60.0,
                 _clock=time.monotonic):
        if getattr(oracle, "_thread", None) is not None:
            raise InvalidArgumentError(
                "the oracle engine must not be started: the refresher "
                "drives it synchronously (run_until_drained)")
        self.fleet = fleet
        self.directory = os.path.abspath(directory)
        self.oracle = oracle
        self.canary_prompts = [list(map(int, p)) for p in canary_prompts]
        if not self.canary_prompts:
            raise InvalidArgumentError(
                "at least one canary prompt is required")
        self.canary_max_new_tokens = int(canary_max_new_tokens)
        self.poll_interval_s = float(poll_interval_s)
        self.flip_timeout_s = float(flip_timeout_s)
        self.canary_timeout_s = float(canary_timeout_s)
        self._clock = _clock
        self._verified: set = set()
        self._quarantined: Dict[str, str] = {}
        self._current: Optional[Dict] = None   # last canary-passed publish
        self._baseline: Optional[Dict] = None  # oracle boot-state arrays
        self._last_error: Optional[str] = None
        self._lock = threading.Lock()
        self._poll_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        fleet.attach_refresher(self)

    # -- health plumbing ----------------------------------------------
    def sha_ok(self, sha: Optional[str]) -> bool:
        """Is `sha` acceptable to serve?  Boot weights (sha None) are
        implicitly good — they were never rolled back — and anything
        else must have passed the canary and never been quarantined."""
        with self._lock:
            if sha in self._quarantined:
                return False
            return sha is None or sha in self._verified

    def status(self) -> Dict:
        with self._lock:
            return {
                "current_sha": (None if self._current is None
                                else self._current["sha256"]),
                "current_step": (None if self._current is None
                                 else self._current["step"]),
                "verified": len(self._verified),
                "quarantined": dict(self._quarantined),
                "last_error": self._last_error,
            }

    # -- one refresh cycle --------------------------------------------
    def poll(self) -> Dict:
        """One watch cycle: admit any new publish through the gates,
        then converge stragglers (restarted / scaled-up replicas) onto
        the current verified weights.  Safe to call from any single
        thread; `start()` wraps it in a background loop."""
        with self._poll_lock:
            self._capture_baseline()
            pub = latest_publish(self.directory)
            if pub is not None:
                sha = pub["sha256"]
                with self._lock:
                    stale = (sha in self._quarantined
                             or sha in self._verified)
                if not stale:
                    self._admit(pub)
            self._converge()
            return self.status()

    def _capture_baseline(self):
        if self._baseline is None:
            self._baseline = {k: np.asarray(v)
                              for k, v in self.oracle._state.items()}

    def _admit(self, pub: Dict):
        sha = pub["sha256"]
        # gate 1: the artifact's bytes vs the manifest's pre-rename sha
        try:
            actual = file_sha256(pub["path"])
        except OSError as e:
            self._quarantine(sha, f"artifact unreadable: {e!r}")
            return
        if actual != sha:
            self._quarantine(sha,
                             "artifact sha mismatch (corrupt publish)")
            return
        try:
            with np.load(pub["path"], allow_pickle=False) as z:
                state = {k: z[k] for k in z.files}
        except Exception as e:  # noqa: BLE001 — any decode failure
            self._quarantine(sha, f"artifact undecodable: {e!r}")
            return
        # gate 2: the oracle swaps first — shape/key mismatches die
        # here; then it generates the expected canary streams
        try:
            self.oracle.swap_weights(state, sha)
            expected = self._oracle_tokens()
        except Exception as e:  # noqa: BLE001 — typed swap/gen errors
            self._quarantine(
                sha, f"oracle rejected publish: {type(e).__name__}: {e}")
            self._oracle_rollback()
            return
        # gate 3: one canary replica, bit-identity required
        rep = self._pick_canary()
        if rep is None:
            # no routable capacity right now — leave the publish
            # unjudged and retry next cycle (oracle back to old weights
            # keeps poll idempotent)
            self._oracle_rollback()
            with self._lock:
                self._last_error = ("no routable replica for canary; "
                                    "deferred")
            return
        try:
            entry = self.fleet.flip_weights(rep.id, path=pub["path"],
                                            sha=sha, state=state)
        except InvalidArgumentError as e:
            self._oracle_rollback()
            with self._lock:
                self._last_error = f"canary flip not schedulable: {e}"
            return
        if not self._wait_entry(entry):
            self._quarantine(sha, "canary flip failed: "
                             f"{entry.get('error') or 'timeout'}")
            self._oracle_rollback()
            return
        try:
            got = self._replica_tokens(rep)
        except Exception as e:  # noqa: BLE001 — failed canary = diverged
            got = f"canary request failed: {type(e).__name__}: {e}"
        if faults.canary_diverge() or got != expected:
            self._rollback_canary(rep)
            self._quarantine(
                sha, "canary diverged from the new-weights oracle")
            self._oracle_rollback()
            return
        with self._lock:
            self._verified.add(sha)
            self._current = pub
            self._last_error = None

    def _converge(self):
        """Flip every serving replica that is not on the current
        verified weights — the sweep that heals restarts, rollout
        replacements and scale-ups without special cases."""
        cur = self._current
        if cur is None:
            return
        sha = cur["sha256"]
        state = None
        for rep in self.fleet.manager.replicas((HEALTHY, DEGRADED)):
            if rep.flipping or not getattr(rep.engine, "warm", False):
                continue
            if getattr(rep.engine, "weights_sha", None) == sha:
                continue
            if state is None:
                with np.load(cur["path"], allow_pickle=False) as z:
                    state = {k: z[k] for k in z.files}
            try:
                self.fleet.flip_weights(rep.id, path=cur["path"],
                                        sha=sha, state=state)
            except InvalidArgumentError:
                pass  # lost liveness between the snapshot and the flip

    # -- internals -----------------------------------------------------
    def _oracle_tokens(self) -> List[List[int]]:
        resps = [self.oracle.submit(
            p, max_new_tokens=self.canary_max_new_tokens)
            for p in self.canary_prompts]
        self.oracle.run_until_drained(timeout=self.canary_timeout_s)
        return [list(r.tokens(timeout=1.0)) for r in resps]

    def _replica_tokens(self, rep) -> List[List[int]]:
        resps = []
        for p in self.canary_prompts:
            req, resp = rep.engine.make_request(
                p, self.canary_max_new_tokens)
            rep.engine.scheduler.submit(req, resp)
            resps.append(resp)
        # the fleet's driving loop executes these; wake it
        self.fleet._work.set()
        return [list(r.tokens(timeout=self.canary_timeout_s))
                for r in resps]

    def _pick_canary(self):
        reps = self.fleet.manager.routable()
        if not reps:
            return None
        return min(reps, key=lambda r: r.load())

    def _wait_entry(self, entry: Dict,
                    timeout: Optional[float] = None) -> bool:
        deadline = self._clock() + (self.flip_timeout_s
                                    if timeout is None else timeout)
        while not entry["done"]:
            if self._clock() > deadline:
                return False
            time.sleep(0.01)
        return bool(entry["ok"])

    def _rollback_target(self):
        """(path, sha, state) of the weights a bad canary rolls back
        to: the last verified publish, or — before any publish passed —
        the oracle's boot state, materialized as an artifact once (a
        subprocess canary needs a PATH to roll back to)."""
        with self._lock:
            cur = self._current
        if cur is not None:
            return cur["path"], cur["sha256"], None
        d = os.path.join(self.directory, ".baseline")
        path = os.path.join(d, _WEIGHTS)
        if not os.path.exists(path):
            os.makedirs(d, exist_ok=True)
            tmp = path + f".tmp-{os.getpid()}"
            with open(tmp, "wb") as f:
                np.savez(f, **self._baseline)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        sha = file_sha256(path)
        with self._lock:
            # the baseline IS the boot weights: implicitly verified
            self._verified.add(sha)
        return path, sha, self._baseline

    def _rollback_canary(self, rep):
        path, sha, state = self._rollback_target()
        try:
            back = self.fleet.flip_weights(rep.id, path=path, sha=sha,
                                           state=state)
            self._wait_entry(back)
        except InvalidArgumentError:
            pass  # replica died meanwhile: restart converges it

    def _oracle_rollback(self):
        path, sha, state = self._rollback_target()
        if state is None:
            with np.load(path, allow_pickle=False) as z:
                state = {k: z[k] for k in z.files}
        self.oracle.swap_weights(state, sha)

    def _quarantine(self, sha: str, reason: str):
        with self._lock:
            self._quarantined[sha] = reason
            self._last_error = f"{sha[:12]}: {reason}"
        self.fleet.manager.note_rollback()

    # -- background loop ----------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.poll()
                except Exception as e:  # noqa: BLE001 — keep watching
                    with self._lock:
                        self._last_error = (
                            f"poll failed: {type(e).__name__}: {e}")
                self._stop.wait(self.poll_interval_s)

        self._thread = threading.Thread(target=loop,
                                        name="fleet-refresher",
                                        daemon=True)
        self._thread.start()

    def close(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
