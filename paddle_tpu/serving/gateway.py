"""Multi-tenant serving gateway: SLO-aware admission in front of the
engine.

The ServingEngine (PR 4) ends at a bounded FIFO — under overload every
caller degrades equally.  The gateway is the production front door on top
of it:

- **Per-tenant token buckets + weighted fairness.**  Each tenant gets a
  rate/burst bucket (checked at submit — a rate-limited request costs
  nothing downstream) and a weight; admission within a priority lane is
  stride-scheduled across tenants, so a weight-2 tenant drains twice as
  fast as a weight-1 tenant while both have work queued.
- **Priority lanes with preemption.**  A high-priority arrival that finds
  every KV slot occupied evicts a lower-priority decode: the victim's
  slot KV rows + sampling state are snapshotted to host
  (`engine.preempt_slot` — the checkpoint snapshot/publish split
  generalized to a live decode), the slot serves the high request, and
  the victim resumes later (`engine.restore_run`) with output
  bit-identical to a run that was never preempted.  Preempt/restore adds
  ZERO compiled programs: snapshots are `jax.device_get` + numpy row
  writes.
- **Load shedding from live signals** (`slo.ShedPolicy`): lane depth,
  slot occupancy, the measured service-time EWMA, and the high lane's
  recent TTFT p99 — rejecting cheap-to-reject work at submit time instead
  of letting it time out expensively after queue residence + prefill.
- **Every admission outcome is a terminal Response** — shed,
  rate-limited, deadline-expired, preempted-then-cancelled, gateway
  closed: a consumer blocked in `Response.tokens()` / iteration always
  gets a terminal state, never a hang.  `submit` therefore returns a
  (possibly already-failed) Response instead of raising for policy
  outcomes.
- **An OpenAI-shaped streaming HTTP endpoint** (stdlib http.server, the
  `observability.exporters.serve_metrics` pattern): POST
  /v1/completions with `stream` support (SSE), plus /v1/models, /healthz
  and the Prometheus /metrics passthrough.  `handle()` renders any
  request port-free, so tier-1 tests exercise the exact handler payloads
  without binding a socket.

The gateway owns the engine loop: it drives `engine.step()` from its own
thread (preempt/restore must interleave with steps, single-threaded).  Do
not call `engine.start()` on a gatewayed engine.
"""
from __future__ import annotations

import json
import threading
import time
import types
from collections import deque
from typing import Dict, List, Optional

from ..core.errors import (InvalidArgumentError, ResourceExhaustedError,
                           UnavailableError)
from ..utils.monitor import stat_add
from .engine import ServingEngine, PreemptedRun
from .request import Request, Response, RequestCancelled
from .scheduler import DeadlineExceededError
from .slo import ShedPolicy, Signals, SLOTracker, TenantConfig

__all__ = ["ServingGateway", "GatewayServer", "RateLimitedError",
           "SheddedError", "serve_gateway", "PRIORITY_HIGH", "PRIORITY_LOW"]

PRIORITY_LOW = 0
PRIORITY_HIGH = 1


class RateLimitedError(ResourceExhaustedError):
    """The tenant's token bucket is empty: the request was rejected at
    submit.  Retry after the bucket refills (HTTP 429)."""
    code = "ResourceExhausted"


class SheddedError(UnavailableError):
    """The gateway shed this request to protect the latency SLO of work
    already admitted (HTTP 503).  `.reason` carries the tripped rule:
    queue_depth | est_wait | slo_pressure."""
    code = "Unavailable"

    def __init__(self, msg: str, reason: str = ""):
        super().__init__(msg)
        self.reason = reason


def _lane_name(priority: int) -> str:
    return "hi" if priority > 0 else "lo"


class _LaneEntry:
    __slots__ = ("req", "resp", "enq_at")

    def __init__(self, req: Request, resp: Response):
        self.req = req
        self.resp = resp
        self.enq_at = time.monotonic()


class _TenantState:
    __slots__ = ("name", "cfg", "bucket", "passes")

    def __init__(self, name: str, cfg: TenantConfig):
        self.name = name
        self.cfg = cfg
        self.bucket = cfg.make_bucket()
        self.passes: Dict[int, float] = {}  # priority -> stride pass


_obs_handles = None


def _obs():
    """Cached gateway observability handles (registry.reset() zeroes the
    values in place, handles stay valid)."""
    global _obs_handles
    if _obs_handles is None:
        from ..observability import metrics as _m
        _obs_handles = {
            "requests": _m.counter(
                "gateway_requests_total", "requests received by the gateway",
                labelnames=("tenant", "lane")),
            "shed": _m.counter(
                "gateway_shed_total", "requests shed at admission",
                labelnames=("reason",)),
            "rate_limited": _m.counter(
                "gateway_rate_limited_total",
                "requests rejected by a tenant token bucket",
                labelnames=("tenant",)),
            "preempt": _m.counter(
                "gateway_preempt_total",
                "low-priority decodes preempted for a high-priority "
                "arrival"),
            "resume": _m.counter(
                "gateway_resume_total", "preempted decodes resumed"),
            "depth_hi": _m.gauge(
                "gateway_lane_hi_depth", "high-priority lane queue depth"),
            "depth_lo": _m.gauge(
                "gateway_lane_lo_depth", "low-priority lane queue depth"),
            "paused": _m.gauge(
                "gateway_paused_runs", "preempted runs awaiting restore"),
            "ttft_hi": _m.histogram(
                "gateway_ttft_hi_seconds",
                "submit -> first token, high-priority lane"),
            "ttft_lo": _m.histogram(
                "gateway_ttft_lo_seconds",
                "submit -> first token, low-priority lane"),
        }
    return _obs_handles


class ServingGateway:
    """SLO-aware multi-tenant admission layer over a ServingEngine.

    ::

        eng = ServingEngine(model, max_slots=8, max_len=256)
        eng.warmup()
        gw = ServingGateway(
            eng,
            tenants={"gold": TenantConfig(rate=50, weight=4.0),
                     "free": TenantConfig(rate=5, weight=1.0,
                                          max_priority=0)},
            shed=ShedPolicy(max_lane_depth=32, ttft_slo=0.5))
        gw.start()                     # gateway drives the engine loop
        r = gw.submit(prompt, 64, tenant="gold", priority=PRIORITY_HIGH)
        for tok in r: ...              # r is terminal-on-rejection too
        gw.close()
    """

    def __init__(self, engine: ServingEngine,
                 tenants: Optional[Dict[str, TenantConfig]] = None,
                 default_tenant: Optional[TenantConfig] = None,
                 shed: Optional[ShedPolicy] = None,
                 preempt: bool = True, max_paused: Optional[int] = None,
                 model_name: str = "paddle-tpu",
                 request_timeout: float = 120.0):
        if engine._thread is not None:
            raise InvalidArgumentError(
                "engine loop already started; the gateway drives "
                "engine.step() itself — construct the engine without "
                "start()")
        self.engine = engine
        self.model_name = model_name
        self.request_timeout = float(request_timeout)
        self._default_cfg = default_tenant or TenantConfig()
        self._tenants: Dict[str, _TenantState] = {
            name: _TenantState(name, cfg)
            for name, cfg in (tenants or {}).items()}
        self.shed_policy = shed or ShedPolicy()
        self.tracker = SLOTracker()
        self._preempt_enabled = bool(preempt)
        self.max_paused = (int(max_paused) if max_paused is not None
                           else engine.max_slots * 4)
        # priority -> {tenant: deque[_LaneEntry]}
        self._lanes: Dict[int, Dict[str, deque]] = {}
        self._vtime: Dict[int, float] = {}  # per-lane stride virtual time
        self._paused: List[PreemptedRun] = []
        self._inflight: List[tuple] = []  # (resp, lane_name, [ttft_seen])
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._work = threading.Event()
        self._closed = False
        # concurrent double-close safety (see ServingEngine.close): the
        # join + fail-everything sequence runs exactly once at a time
        self._close_lock = threading.Lock()
        self._dead: Optional[BaseException] = None
        # counters surfaced by metrics() (registry handles shared with
        # Prometheus; these are the gateway-local snapshot copies)
        self._n = {"requests": 0, "admitted": 0, "shed": 0,
                   "rate_limited": 0, "preempted": 0, "resumed": 0,
                   "rejected_invalid": 0}
        # tenancy IS the prefix-cache share policy: tenants naming a
        # kv_share_group share cached KV; everyone else stays private
        groups = {name: cfg.kv_share_group
                  for name, cfg in (tenants or {}).items()
                  if cfg.kv_share_group is not None}
        set_groups = getattr(engine, "set_share_groups", None)
        if groups and set_groups is not None:
            set_groups(groups)

    # ------------------------------------------------------------------
    # submission (caller threads)
    # ------------------------------------------------------------------
    def _tenant_state(self, name: str) -> _TenantState:
        with self._lock:
            ts = self._tenants.get(name)
            if ts is None:
                ts = _TenantState(name, self._default_cfg)
                self._tenants[name] = ts
            return ts

    def _terminal(self, resp: Response, exc: BaseException) -> Response:
        resp._fail(exc)
        return resp

    def _synthetic_fail(self, exc: BaseException) -> Response:
        """Terminal Response for a request that failed validation before a
        Request object existed — the no-consumer-ever-hangs contract
        covers malformed submissions too."""
        stub = types.SimpleNamespace(id=-1, deadline=None, priority=0,
                                     tenant=None)
        return self._terminal(Response(stub), exc)

    def submit(self, prompt, max_new_tokens: int, tenant: str = "default",
               priority: int = PRIORITY_LOW, **kwargs) -> Response:
        """Admit one request.  ALWAYS returns a streaming Response; every
        admission outcome — shed, rate-limited, invalid, closed — is a
        terminal error on the Response rather than an exception, so a
        consumer can uniformly iterate / call tokens() without hanging.
        `kwargs` pass through to `ServingEngine.make_request`
        (decode_strategy, temperature, top_k, top_p, eos_token_id, seed,
        deadline).  `block`/`timeout` — engine.submit's queue-full
        backpressure knobs — are accepted and ignored: gateway admission
        is immediate (enqueue or a terminal rejection), there is no full
        queue to wait on."""
        kwargs.pop("block", None)
        kwargs.pop("timeout", None)
        if self._closed:
            return self._synthetic_fail(
                UnavailableError("gateway is closed"))
        if self._dead is not None:
            return self._synthetic_fail(UnavailableError(
                f"gateway loop died: {self._dead!r}"))
        ts = self._tenant_state(tenant)
        priority = max(0, min(int(priority), ts.cfg.max_priority))
        lane = _lane_name(priority)
        obs = _obs()
        obs["requests"].labels(tenant=tenant, lane=lane).inc()
        stat_add("STAT_gateway_requests")
        with self._lock:
            self._n["requests"] += 1
        if ts.cfg.adapter is not None:
            # the tenant's LoRA adapter rides on every one of its
            # requests; an unknown/unloaded adapter fails typed right
            # here (AdapterNotFoundError via make_request validation),
            # through the same no-consumer-ever-hangs path as any other
            # malformed submission
            kwargs.setdefault("adapter", ts.cfg.adapter)
        try:
            req, resp = self.engine.make_request(
                prompt, max_new_tokens, priority=priority, tenant=tenant,
                **kwargs)
        except Exception as e:
            with self._lock:
                self._n["rejected_invalid"] += 1
            return self._synthetic_fail(e)
        # load shedding from live signals, decided BEFORE the bucket is
        # debited: a shed request must not also burn the tenant's rate
        # budget (it was told to retry with backoff — punishing the retry
        # with a 429 would double-charge overload the tenant didn't cause)
        reason = self.shed_policy.decide(self._signals(priority), priority)
        if reason is not None:
            obs["shed"].labels(reason=reason).inc()
            stat_add("STAT_gateway_shed")
            with self._lock:
                self._n["shed"] += 1
            return self._terminal(resp, SheddedError(
                f"request {req.id} shed ({reason}): gateway over "
                "capacity — retry with backoff", reason=reason))
        # rate limit: the tenant's own budget, charged only for work that
        # passed admission policy
        if not ts.bucket.try_take():
            obs["rate_limited"].labels(tenant=tenant).inc()
            stat_add("STAT_gateway_rate_limited")
            with self._lock:
                self._n["rate_limited"] += 1
            return self._terminal(resp, RateLimitedError(
                f"tenant {tenant!r} over its rate limit "
                f"({ts.cfg.rate}/s, burst {ts.bucket.burst:g}); request "
                f"{req.id} rejected"))
        with self._lock:
            # re-check under the SAME lock _fail_everything drains with:
            # a close()/loop-death racing this submit must not let an
            # entry land in a lane nobody will ever process (the consumer
            # would hang forever — the contract this module exists for)
            if self._closed or self._dead is not None:
                closed_race = True
            else:
                closed_race = False
                tq = self._lanes.setdefault(req.priority, {})
                dq = tq.get(tenant)
                if dq is None:
                    dq = tq[tenant] = deque()
                if not dq:
                    # (re)activating tenant: jump its stride pass to the
                    # lane's virtual time so an idle spell cannot bank
                    # credit
                    vt = self._vtime.get(req.priority, 0.0)
                    ts.passes[req.priority] = max(
                        ts.passes.get(req.priority, 0.0), vt)
                dq.append(_LaneEntry(req, resp))
        if closed_race:
            return self._terminal(resp, UnavailableError(
                f"request {req.id} rejected: gateway "
                + ("closed" if self._closed
                   else f"loop died: {self._dead!r}")))
        self._update_depth_gauges()
        self._work.set()
        return resp

    # ------------------------------------------------------------------
    # signals + lane bookkeeping
    # ------------------------------------------------------------------
    def _depths(self):
        """(high_lane_depth, low_lane_depth) in ONE locked pass — this
        runs on every submit and every gauge update, and the lock is
        shared with the loop thread's lane pops."""
        with self._lock:
            hi = lo = 0
            for p, tq in self._lanes.items():
                n = sum(len(dq) for dq in tq.values())
                if p > 0:
                    hi += n
                else:
                    lo += n
            return hi, lo

    def _group_depth(self, hi: bool) -> int:
        depth_hi, depth_lo = self._depths()
        return depth_hi if hi else depth_lo

    def _signals(self, priority: int) -> Signals:
        depth_hi, depth_lo = self._depths()
        lane_depth = depth_hi if priority > 0 else depth_lo
        total = depth_hi + depth_lo
        occ = self.engine.scheduler.occupancy()
        free = self.engine.scheduler.free_slot_count()
        # a low arrival waits behind everything; a high arrival only
        # behind the high lane (it can preempt through the rest)
        ahead = depth_hi if priority > 0 else total
        return Signals(
            lane_depth=lane_depth, total_depth=total, occupancy=occ,
            free_slots=free, max_slots=self.engine.max_slots,
            ttft_p99_hi=self.tracker.ttft_p99("hi"),
            est_wait=self.tracker.est_wait(ahead, self.engine.max_slots),
            paused=len(self._paused))

    def _update_depth_gauges(self):
        obs = _obs()
        depth_hi, depth_lo = self._depths()
        obs["depth_hi"].set(depth_hi)
        obs["depth_lo"].set(depth_lo)
        obs["paused"].set(len(self._paused))

    # ------------------------------------------------------------------
    # the gateway loop (single thread; also drives engine.step())
    # ------------------------------------------------------------------
    def _sweep_lanes(self):
        """Queued entries whose caller cancelled or whose deadline expired
        get their terminal response here — they never cost a slot."""
        failed = False
        with self._lock:
            for priority, tq in self._lanes.items():
                for tenant, dq in tq.items():
                    keep = deque()
                    for e in dq:
                        if e.resp.cancelled:
                            e.resp._fail(RequestCancelled(
                                f"request {e.req.id} cancelled while "
                                "queued in the gateway"))
                            failed = True
                        elif (e.req.deadline is not None
                              and e.req.deadline.expired()):
                            stat_add("STAT_serving_deadline_expired")
                            e.resp._fail(DeadlineExceededError(
                                f"request {e.req.id} deadline "
                                f"({e.req.deadline.seconds}s) expired in "
                                "the gateway queue"))
                            failed = True
                        else:
                            keep.append(e)
                    tq[tenant] = keep
        if failed:
            self._update_depth_gauges()

    def _sweep_paused(self):
        """A preempted run can be cancelled or expire while paused; it
        must reach a terminal state without ever being restored."""
        keep = []
        for p in self._paused:
            if p.resp.cancelled:
                p.resp._fail(RequestCancelled(
                    f"request {p.req.id} cancelled while preempted"))
            elif p.req.deadline is not None and p.req.deadline.expired():
                stat_add("STAT_serving_deadline_expired")
                p.resp._fail(DeadlineExceededError(
                    f"request {p.req.id} deadline "
                    f"({p.req.deadline.seconds}s) expired while preempted"))
            else:
                keep.append(p)
        if len(keep) != len(self._paused):
            self._paused = keep
            self._update_depth_gauges()

    def _observe_inflight(self):
        """Record TTFT at first token and service time at completion for
        the SLO tracker + histograms (drives the shed policy live)."""
        obs = _obs()
        keep = []
        for resp, lane, seen in self._inflight:
            if not seen[0] and resp.first_token_at is not None:
                seen[0] = True
                ttft = resp.ttft
                self.tracker.note_ttft(lane, ttft)
                (obs["ttft_hi"] if lane == "hi"
                 else obs["ttft_lo"]).observe(ttft)
            if resp.done():
                if (resp.error is None and resp.finished_at is not None
                        and resp.first_token_at is not None):
                    # service time from FIRST TOKEN, minus time spent
                    # preempted: neither queue wait nor paused wall time
                    # may feed back into est_wait (congestion would
                    # inflate "service", which sheds more, which keeps
                    # shedding after the backlog drains)
                    self.tracker.note_service(max(0.0, (
                        resp.finished_at - resp.first_token_at
                        - getattr(resp.request, "paused_seconds", 0.0))))
            else:
                keep.append((resp, lane, seen))
        self._inflight = keep

    def _best_waiting_lane(self) -> Optional[int]:
        with self._lock:
            live = [p for p, tq in self._lanes.items()
                    if any(tq.values())]
            return max(live) if live else None

    def _pop_lane(self, priority: int):
        """Stride-fair pop across the lane's tenants: the tenant with the
        smallest pass value goes, then its pass advances by 1/weight.
        Returns (entry, tenant, previous_pass) so a failed admission can
        roll the pass back."""
        with self._lock:
            tq = self._lanes.get(priority) or {}
            candidates = [(self._tenants[t].passes.get(priority, 0.0), t)
                          for t, dq in tq.items() if dq]
            if not candidates:
                return None
            prev_pass, tenant = min(candidates)
            ts = self._tenants[tenant]
            entry = tq[tenant].popleft()
            new_pass = prev_pass + 1.0 / ts.cfg.weight
            ts.passes[priority] = new_pass
            self._vtime[priority] = max(
                self._vtime.get(priority, 0.0), new_pass)
            return entry, tenant, prev_pass

    def _admit_one(self) -> bool:
        """Place ONE unit of waiting work into a free slot: the best
        waiting lane entry, or a paused run of >= that priority (it holds
        progress and arrived earlier).  False when nothing is waiting or
        no slot is free."""
        if self.engine.scheduler.free_slot_count() <= 0:
            return False
        best_lane = self._best_waiting_lane()
        best_paused = max((p.req.priority for p in self._paused),
                          default=None)
        if best_lane is None and best_paused is None:
            return False
        if best_paused is not None and (best_lane is None
                                        or best_paused >= best_lane):
            for i, p in enumerate(self._paused):
                if p.req.priority == best_paused:
                    self._paused.pop(i)
                    break
            if self.engine.restore_run(p):
                _obs()["resume"].inc()
                stat_add("STAT_gateway_resumes")
                with self._lock:
                    self._n["resumed"] += 1
                self._update_depth_gauges()
                return True
            self._paused.insert(0, p)  # no slot after all; retry later
            return False
        popped = self._pop_lane(best_lane)
        if popped is None:
            return False
        entry, tenant, prev_pass = popped
        if not self.engine.try_admit(entry.req, entry.resp):
            # no slot (or, paged, no blocks — try_admit's block-aware
            # gate makes this ROUTINE under pool pressure): requeue at
            # the front and ROLL BACK the stride pass, so waiting on
            # capacity never eats the tenant's configured fair share
            with self._lock:
                self._lanes.setdefault(best_lane, {}).setdefault(
                    tenant, deque()).appendleft(entry)
                ts = self._tenants.get(tenant)
                if ts is not None:
                    ts.passes[best_lane] = prev_pass
            return False
        with self._lock:
            self._n["admitted"] += 1
        stat_add("STAT_gateway_admitted")
        self._inflight.append(
            (entry.resp, _lane_name(entry.req.priority), [False]))
        self._update_depth_gauges()
        return True

    def _maybe_preempt(self):
        """While a waiting arrival outranks an active decode and no slot
        is free: snapshot the weakest victim to host, free its slot, admit
        the high entry into it.  Victim choice: lowest priority first,
        then fewest tokens produced (least progress lost to pausing)."""
        if not self._preempt_enabled:
            return
        while True:
            hi = self._best_waiting_lane()
            if hi is None or hi <= 0:
                return
            if self.engine.scheduler.free_slot_count() > 0:
                return  # plain admission will take it
            if len(self._paused) >= self.max_paused:
                return
            victim_slot, best = None, None
            for slot, run in self.engine._slots.items():
                if run.req.priority < hi:
                    key = (run.req.priority, run.produced)
                    if best is None or key < best:
                        best, victim_slot = key, slot
            if victim_slot is None:
                return  # everything active outranks the arrival
            try:
                paused = self.engine.preempt_slot(victim_slot)
            except InvalidArgumentError:
                # the victim finished — or, fleet-fronted, its replica
                # died — between the scan and the preempt; failover owns
                # the dead-replica case, this loop just retries later
                return
            self._paused.append(paused)
            _obs()["preempt"].inc()
            stat_add("STAT_gateway_preemptions")
            with self._lock:
                self._n["preempted"] += 1
            self._update_depth_gauges()
            # the freed slot goes to the high lane NOW (the paused run,
            # being lower priority, cannot win it back this round)
            self._admit_one()

    def _tick(self) -> bool:
        self._sweep_lanes()
        self._sweep_paused()
        self._observe_inflight()
        did = False
        while self._admit_one():
            did = True
        self._maybe_preempt()
        did = self.engine.step() or did
        return did

    def has_work(self) -> bool:
        with self._lock:
            lanes = any(dq for tq in self._lanes.values()
                        for dq in tq.values())
        return lanes or bool(self._paused) or self.engine.has_work()

    def run_until_drained(self, timeout: Optional[float] = None):
        """Drive the gateway+engine in the caller's thread until every
        lane, paused run, and slot is empty (tests / batch use).  Not for
        use while start() is live."""
        t0 = time.monotonic()
        while self.has_work():
            self._tick()
            if timeout is not None and time.monotonic() - t0 > timeout:
                raise TimeoutError(
                    f"gateway did not drain in {timeout}s")
        # requests that completed inside the final tick's engine.step()
        # still owe their TTFT/service samples
        self._observe_inflight()

    def start(self):
        """Background gateway loop (also the engine loop — the engine's
        own start() must not be used)."""
        if self._thread is not None:
            return
        if self._closed:
            raise UnavailableError("gateway is closed")
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    did = self._tick()
                except BaseException as e:  # noqa: BLE001 — no hangs
                    self._dead = e
                    self._fail_everything(lambda req: UnavailableError(
                        f"request {req.id} aborted: gateway loop died: "
                        f"{e!r}"))
                    return
                if not did:
                    self._work.wait(0.002)
                    self._work.clear()

        self._thread = threading.Thread(target=loop,
                                        name="serving-gateway",
                                        daemon=True)
        self._thread.start()

    def _fail_everything(self, make_exc):
        """Terminal responses for every lane entry, paused run, and
        in-flight slot (gateway death/close)."""
        with self._lock:
            entries = [e for tq in self._lanes.values()
                       for dq in tq.values() for e in dq]
            self._lanes = {}
            paused, self._paused = self._paused, []
        for e in entries:
            e.resp._fail(make_exc(e.req))
        for p in paused:
            p.resp._fail(make_exc(p.req))
        self.engine._abort_all(make_exc)
        self._update_depth_gauges()

    def close(self, close_engine: bool = True):
        """Stop the loop; every outstanding request — queued, paused, or
        decoding — reaches a terminal error (never a hang).  Idempotent
        and safe under concurrent double-close (the fleet replica manager
        and the caller's own shutdown can race): the flag flips first so
        racing submits reject, and the join/drain sequence serializes
        under _close_lock."""
        self._closed = True
        self._stop.set()
        self._work.set()
        with self._close_lock:
            if self._thread is not None:
                self._thread.join(timeout=5.0)
                self._thread = None
            self._fail_everything(lambda req: RequestCancelled(
                f"request {req.id} aborted: gateway closed"
                + (" (was preempted)"
                   if getattr(req, "preempts", 0) > getattr(req, "resumes",
                                                            0)
                   else "")))
        if close_engine:
            self.engine.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    def metrics(self) -> Dict:
        def ms(v):
            return None if v is None else v * 1e3
        with self._lock:
            n = dict(self._n)
            # snapshot under the lock: _tenant_state inserts first-seen
            # tenants concurrently from caller threads
            tenants = dict(self._tenants)
        depth_hi, depth_lo = self._depths()
        return {
            **n,
            "lane_depth_hi": depth_hi,
            "lane_depth_lo": depth_lo,
            "paused": len(self._paused),
            "ttft_p99_hi_ms": ms(self.tracker.ttft_p99("hi")),
            "ttft_p99_lo_ms": ms(self.tracker.ttft_p99("lo")),
            "service_ewma_ms": ms(self.tracker.service_ewma()),
            # inf (unlimited) renders as None: json.dumps would emit the
            # non-RFC literal `Infinity` that strict parsers reject
            "tenants": {name: {
                "weight": ts.cfg.weight,
                "rate": None if ts.cfg.rate == float("inf")
                else ts.cfg.rate,
                "bucket_level": None if ts.bucket.level() == float("inf")
                else round(ts.bucket.level(), 3)}
                for name, ts in tenants.items()},
            "engine": self.engine.metrics(),
        }

    def scale_signals(self) -> Dict:
        """The cheap SLO signals an autoscaler polls every tick: current
        estimated queue wait (EWMA service time x depth / slots), lane
        depths, and the monotonic shed/admitted counters (the caller
        diffs them per tick to get a shed *rate*).  No engine round-trip
        beyond depth reads — safe to call at high frequency."""
        depth_hi, depth_lo = self._depths()
        depth = depth_hi + depth_lo
        with self._lock:
            shed = self._n["shed"]
            admitted = self._n["admitted"]
        slots = max(1, int(getattr(self.engine, "max_slots", 1) or 1))
        return {
            "est_wait_s": self.tracker.est_wait(depth, slots),
            "queue_depth": depth,
            "lane_depth_hi": depth_hi,
            "lane_depth_lo": depth_lo,
            "shed_total": shed,
            "admitted_total": admitted,
        }

    # ------------------------------------------------------------------
    # OpenAI-shaped HTTP surface (port-free handler + stdlib server)
    # ------------------------------------------------------------------
    @staticmethod
    def _http_status(exc: BaseException) -> int:
        if isinstance(exc, RateLimitedError):
            return 429
        if isinstance(exc, SheddedError):
            return 503
        if isinstance(exc, (DeadlineExceededError, TimeoutError)):
            return 504
        if isinstance(exc, RequestCancelled):
            return 499
        if isinstance(exc, (InvalidArgumentError, ValueError, TypeError,
                            KeyError)):
            return 400
        if isinstance(exc, ResourceExhaustedError):
            return 503
        return 500

    @staticmethod
    def _error_body(exc: BaseException) -> dict:
        return {"error": {"message": str(exc),
                          "type": type(exc).__name__,
                          "code": getattr(exc, "code", None)}}

    def _parse_completion(self, body: dict):
        prompt = body.get("prompt")
        if isinstance(prompt, str):
            prompt = [int(t) for t in prompt.split()]
        if not isinstance(prompt, (list, tuple)) or not prompt:
            raise ValueError(
                "prompt must be a non-empty list of token ids (or a "
                "space-separated id string); paddle_tpu serves token ids — "
                "tokenize client-side")
        kwargs = {"max_new_tokens": int(body.get("max_tokens", 16))}
        # OpenAI convention: temperature 0 (the default here) = greedy
        temp = float(body.get("temperature", 0.0))
        if temp > 0.0:
            kwargs.update(decode_strategy="sampling", temperature=temp,
                          top_p=float(body.get("top_p", 1.0)),
                          top_k=int(body.get("top_k", 0)))
            if body.get("seed") is not None:
                kwargs["seed"] = int(body["seed"])
        if body.get("eos_token_id") is not None:
            kwargs["eos_token_id"] = int(body["eos_token_id"])
        if body.get("deadline_ms") is not None:
            kwargs["deadline"] = float(body["deadline_ms"]) / 1e3
        tenant = str(body.get("user") or body.get("tenant") or "default")
        pr = body.get("priority", PRIORITY_LOW)
        priority = {"high": PRIORITY_HIGH, "low": PRIORITY_LOW}.get(
            pr, pr if isinstance(pr, int) else PRIORITY_LOW)
        stream = bool(body.get("stream", False))
        return prompt, kwargs, tenant, priority, stream

    def _completion_json(self, resp: Response, toks: List[int]) -> dict:
        reason = {"eos": "stop", "length": "length"}.get(
            resp.finish_reason, resp.finish_reason)
        plen = (len(resp.request.prompt)
                if isinstance(resp.request, Request) else 0)
        return {
            "id": f"cmpl-{resp.request.id}",
            "object": "text_completion",
            "created": int(time.time()),
            "model": self.model_name,
            "choices": [{"index": 0,
                         "text": " ".join(str(t) for t in toks),
                         "token_ids": list(toks),
                         "finish_reason": reason}],
            "usage": {"prompt_tokens": plen,
                      "completion_tokens": len(toks),
                      "total_tokens": plen + len(toks)},
        }

    def _sse_stream(self, resp: Response):
        """SSE chunk iterator for stream=true: one data: line per token,
        a finish chunk, then [DONE].  A mid-stream error becomes an error
        chunk — the consumer always sees a terminal event.  A consumer
        that stops reading (client disconnect closes the generator)
        cancels the request: an abandoned stream must not leave a slot
        decoding for nobody."""
        rid = f"cmpl-{resp.request.id}"

        def chunk(text, token_ids, finish_reason):
            return ("data: " + json.dumps({
                "id": rid, "object": "text_completion",
                "model": self.model_name,
                "choices": [{"index": 0, "text": text,
                             "token_ids": token_ids,
                             "finish_reason": finish_reason}],
            }) + "\n\n").encode()

        try:
            try:
                for tok in resp:
                    yield chunk(f"{tok} ", [int(tok)], None)
                reason = {"eos": "stop", "length": "length"}.get(
                    resp.finish_reason, resp.finish_reason)
                yield chunk("", [], reason)
            except GeneratorExit:
                raise  # consumer gone: no further yields allowed
            except BaseException as e:  # noqa: BLE001 — must terminate
                yield ("data: " + json.dumps(self._error_body(e)) + "\n\n"
                       ).encode()
            yield b"data: [DONE]\n\n"
        finally:
            if not resp.done():
                resp.cancel()

    def handle(self, method: str, path: str, body: Optional[bytes] = None):
        """(status, content_type, payload) for one HTTP request — payload
        is bytes, or an iterator of SSE byte chunks for streaming
        completions.  Callable without a socket (tier-1 stays
        port-free)."""
        route = path.split("?")[0]
        if method == "GET":
            if route == "/v1/models":
                return 200, "application/json", json.dumps({
                    "object": "list",
                    "data": [{"id": self.model_name, "object": "model",
                              "owned_by": "paddle_tpu"}]}).encode()
            if route == "/healthz":
                status = 503 if (self._closed or self._dead) else 200
                try:
                    from ..programs.store import store_stats
                    pstore = store_stats()
                except Exception:
                    pstore = None
                # fleet-fronted gateways aggregate per-replica health:
                # state, warm, step-time EWMA, heartbeat age,
                # post-warmup compiles — and for worker replicas the
                # served weights_sha + session epoch (a remote replica's
                # snapshot also carries its address and bytes shipped),
                # plus the routable count — the signals a cluster
                # scheduler needs to decide whether THIS front door
                # still has capacity behind it, and operators need to
                # see which weights each replica is actually serving
                health_fn = getattr(self.engine, "health", None)
                fleet = health_fn() if callable(health_fn) else None
                if fleet is not None and fleet.get("routable", 0) == 0:
                    status = 503
                # a refresher-fronted fleet also reports how many
                # routable replicas serve a canary-verified weights_sha:
                # replicas are up but ALL of them serve weights the
                # canary never blessed (mid-rollback, or a bad publish
                # flipped everywhere before the canary caught it) —
                # readiness must fail until verified capacity returns
                if (fleet is not None and fleet.get("routable", 0) > 0
                        and "routable_verified" in fleet
                        and fleet.get("routable_verified", 0) == 0):
                    status = 503
                # every still-routable replica has a stale heartbeat:
                # the DRIVING LOOP itself stalled (normal fencing would
                # have caught one wedged replica), so this scraper is
                # the last observer — alarm, don't reassure
                if fleet is not None and fleet.get("all_routable_stale"):
                    status = 503
                # prefix-cache effectiveness (engine-fronted; a fleet's
                # per-replica caches report through fleet metrics)
                pc = getattr(self.engine, "prefix_cache", None)
                prefix = pc.stats() if pc is not None else None
                # multi-tenant LoRA: which adapters are resident, how
                # many slots are pinned, load/eviction counters — the
                # operator's "is tenant X actually loaded here" signal
                reg = getattr(self.engine, "_lora_reg", None)
                lora = reg.stats() if reg is not None else None
                return status, "application/json", json.dumps({
                    "ok": status == 200,
                    "fleet": fleet,
                    "prefix_cache": prefix,
                    "lora": lora,
                    # readiness: warm=True means every serving program is
                    # precompiled (engine.warmup ran) — no admitted
                    # request will ever pay a trace
                    "warm": bool(getattr(self.engine, "warm", False)),
                    # the persistent program store's hit/miss/entry
                    # stats: a fleet health scraper can see whether this
                    # replica booted from the shared cache (hits > 0) or
                    # paid cold compiles (misses written)
                    "program_store": pstore,
                    "gateway": {k: v for k, v in self.metrics().items()
                                if k != "engine"}},
                    default=str).encode()
            if route in ("/metrics", "/report"):
                from ..observability.exporters import render_endpoint
                return render_endpoint(route)
            return 404, "text/plain", b"not found\n"
        if method == "POST" and route == "/v1/completions":
            try:
                parsed = json.loads((body or b"{}").decode() or "{}")
                prompt, kwargs, tenant, priority, stream = \
                    self._parse_completion(parsed)
            except Exception as e:
                return (400, "application/json",
                        json.dumps(self._error_body(e)).encode())
            resp = self.submit(prompt, tenant=tenant, priority=priority,
                               **kwargs)
            if stream:
                # rejection surfaces as a proper status even in stream
                # mode: terminal-on-submit responses are failed already
                if resp.done() and resp.error is not None:
                    return (self._http_status(resp.error),
                            "application/json",
                            json.dumps(self._error_body(
                                resp.error)).encode())
                return 200, "text/event-stream", self._sse_stream(resp)
            try:
                toks = resp.tokens(timeout=self.request_timeout)
            except BaseException as e:  # noqa: BLE001 — typed status out
                if not resp.done():
                    # handler timeout with the request still decoding:
                    # cancel it so an abandoned HTTP client cannot leave
                    # a slot burning decode cycles with no consumer
                    resp.cancel()
                return (self._http_status(e), "application/json",
                        json.dumps(self._error_body(e)).encode())
            return (200, "application/json",
                    json.dumps(self._completion_json(resp, toks)).encode())
        return 405, "text/plain", b"method not allowed\n"


class GatewayServer:
    """The OpenAI-shaped endpoint over stdlib http.server (the
    `serve_metrics` pattern): POST /v1/completions (+SSE streaming), GET
    /v1/models, /healthz, /metrics, /report."""

    def __init__(self, gateway: ServingGateway, port: int = 0,
                 addr: str = "127.0.0.1"):
        import http.server
        gw = gateway

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _respond(self, status, ctype, payload):
                if isinstance(payload, (bytes, bytearray)):
                    self.send_response(status)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                # SSE: stream chunks as the engine produces them
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Cache-Control", "no-cache")
                self.send_header("Connection", "close")
                self.end_headers()
                try:
                    for chunk in payload:
                        self.wfile.write(chunk)
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client went away mid-stream

            def do_GET(self):  # noqa: N802 (stdlib naming)
                self._respond(*gw.handle("GET", self.path))

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b""
                self._respond(*gw.handle("POST", self.path, body))

            def log_message(self, *a):  # per-request stderr noise
                pass

        self._httpd = http.server.ThreadingHTTPServer((addr, port), Handler)
        self.port = self._httpd.server_address[1]
        self.addr = addr
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="paddle_tpu-gateway-http",
                                        daemon=True)
        self._thread.start()

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def serve_gateway(gateway: ServingGateway, port: int = 8000,
                  addr: str = "127.0.0.1") -> GatewayServer:
    """Start the OpenAI-shaped endpoint; returns the server (`.close()`
    stops it; the gateway itself is left running)."""
    return GatewayServer(gateway, port=port, addr=addr)
