"""Process-isolated serving replicas: the subprocess engine worker.

PR 12's fleet fronted N IN-PROCESS engines: one driving thread steps
every replica, so a wedged step — a hang, not a raise — stalls every
tenant, and a real SIGKILL takes the whole fleet down.  This module is
the missing half of ROADMAP item 3's tail (and the TPU-native shape of
the reference framework's FleetWrapper / parameter-server deployment:
workers as separate OS processes behind an RPC, liveness decided by
timeouts, a supervisor restarting the dead):

- **The worker** (`main()` — ``python -m paddle_tpu.serving.worker``)
  boots a full ServingEngine in its own process from a json boot spec
  (model factory + engine config + optional PR-9 AOT program set, so a
  restart costs seconds and zero compiles), then drives
  ``engine.step()`` in a single-threaded loop that multiplexes a
  length-prefixed frame RPC: submit / stream-chunk / preempt / restore /
  cancel / metrics / fault / close verbs.  Every frame payload is the
  same npz wire form serving/transfer.py uses (arrays + a json header),
  and every malformed frame decodes to the typed `WireFormatError` —
  never a KeyError three layers down.
- **The heartbeat is out-of-band**: the worker atomically rewrites a
  small heartbeat file (monotonic step counter + wall clock) after
  every completed step.  The RPC socket proves the PROCESS is alive;
  only the heartbeat proves it is MAKING PROGRESS — a wedged step
  (``PDTPU_FAULT_REPLICA_WEDGE``) keeps the socket healthy while the
  heartbeat age grows, which is exactly the signal the ReplicaManager
  fences on.
- **`WorkerClient`** is the manager-side handle: it spawns the process,
  speaks the RPC from the fleet's driving thread, and implements the
  ServingEngine surface `ReplicaManager`/`FleetRouter`/`ServingGateway`
  consume (`make_request`/`try_admit`/`scheduler`/`_slots`/`step`/
  `preempt_slot`/`restore_run`/`_abort_all`/`close`/`warm`/`metrics`),
  so a subprocess replica drops into the PR-12 fleet unchanged — mixed
  in-process/subprocess fleets route, migrate, drain and roll out
  through the exact same code paths.  Runs migrate over the wire via
  the transfer codec's npz byte form; the client's local queue IS the
  admission queue (a request ships only once the worker has a free
  slot), so crash failover sees every queued request without a network
  round trip.

Threading contract (mirrors the in-process fleet): all socket I/O and
state mutation happens on the fleet's driving thread via `step()` /
RPC calls; only `scheduler.submit` (caller threads) and `close()` touch
the client elsewhere, both under their own locks.

**Network-transparent mode** (the multi-host leg of ROADMAP item 3):
``python -m paddle_tpu.serving.worker --listen HOST:PORT`` runs the
worker STANDALONE — the manager no longer forks it, it outlives any one
manager, and a `RemoteWorkerClient` attaches over real TCP.  The attach
handshake ships the boot spec plus a real weight artifact (jit.save
npz, chunked frames, per-chunk AND whole-artifact sha256 checked
against a manifest — any mismatch is a typed `WeightShipError`, never
garbage weights) and optionally a PR-9 program set, replacing the
seeded rebuild for production boots.  Liveness moves onto the wire: the
worker pushes beat frames (step counter + monotonic stamp) on a
dedicated side connection, and the manager ages them by ARRIVAL time on
its own clock — a wedged remote step fences on beat age exactly like
the local heartbeat-file path (which stays for local workers).
Partition safety is epoch-token-shaped: the manager issues a session
epoch at every (re)attach; on partition it fences on beat age and
resubmits elsewhere while the isolated worker self-aborts its residents
typed after a manager-silence timeout, and a healed worker carrying a
stale epoch is told to abort, never to resume — no split-brain
double-serving, token for token.  Retried control verbs are idempotent
(submit dedups on wid server-side, so a retried submit after a lost ack
can never double-admit), and the PDTPU_FAULT_NET_* chaos knobs (delay /
mid-frame drop / blackhole partition) prove each path.
"""
from __future__ import annotations

import io
import json
import os
import select
import shutil
import socket
import struct
import subprocess
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.errors import (FatalError, InvalidArgumentError,
                           ResourceExhaustedError, UnavailableError)
from ..utils import faults as _faults
from ..utils.monitor import stat_add
from .request import Request, Response, RequestCancelled
from .scheduler import DeadlineExceededError, QueueFullError

__all__ = ["WorkerClient", "RemoteWorkerClient", "WorkerDiedError",
           "WireFormatError", "StaleEpochError", "WeightShipError",
           "pack_frame", "unpack_frame", "build_gpt", "main",
           "WIRE_VERSION"]

WIRE_VERSION = 1
_MAX_FRAME = 1 << 30  # a tiny-model KV snapshot is KBs; 1 GiB = corruption
_LEN = struct.Struct(">Q")


class WireFormatError(InvalidArgumentError):
    """A frame could not be decoded: bad length prefix, corrupt npz,
    missing/garbled header, or a wire version this build does not speak.
    The RunTransferError stance applied to the RPC itself — fail typed
    at the boundary, never decode garbage into engine state."""
    code = "InvalidArgument"


class WorkerDiedError(UnavailableError):
    """The subprocess worker is gone or unresponsive: process exited,
    socket closed, or an RPC timed out (the wedged case).  The manager
    treats it exactly like a replica crash — fence + failover."""
    code = "Unavailable"


class StaleEpochError(UnavailableError):
    """This worker session's manager-issued epoch token was superseded
    (partition healed after a fence, a newer manager re-attached, or
    the manager went silent past its deadline).  Every resident run
    dies with this error HERE because its resubmitted twin may already
    be streaming elsewhere — aborting typed is what makes double-serving
    impossible, token for token."""
    code = "Unavailable"


class WeightShipError(InvalidArgumentError):
    """A shipped boot artifact failed verification: chunk out of order,
    per-chunk or whole-artifact sha256 mismatch, short ship, or the
    assembled weights do not fit the model.  The RunTransferError stance
    applied to weights — reject typed at the boundary, never serve
    garbage parameters."""
    code = "InvalidArgument"


# ---------------------------------------------------------------------------
# frame codec: length prefix + the transfer.py npz wire form
# ---------------------------------------------------------------------------

def pack_frame(verb: str, header: Optional[dict] = None,
               arrays: Optional[Dict[str, np.ndarray]] = None) -> bytes:
    """One RPC frame: u64 big-endian length + an npz holding every array
    plus a json header under the reserved ``header`` key (the exact
    shape `transfer.run_to_bytes` uses, so run snapshots embed without a
    second codec)."""
    h = {"v": WIRE_VERSION, "verb": str(verb)}
    if header:
        h.update(header)
    arrs = {k: np.asarray(v) for k, v in (arrays or {}).items()}
    if "header" in arrs:
        raise InvalidArgumentError("'header' is a reserved frame key")
    arrs["header"] = np.frombuffer(
        json.dumps(h, default=str).encode(), dtype=np.uint8).copy()
    buf = io.BytesIO()
    np.savez(buf, **arrs)
    payload = buf.getvalue()
    return _LEN.pack(len(payload)) + payload


def unpack_frame(payload: bytes) -> Tuple[str, dict, Dict[str, np.ndarray]]:
    """payload (sans length prefix) -> (verb, header, arrays); raises
    the typed WireFormatError on ANY decode mismatch."""
    try:
        z = np.load(io.BytesIO(payload), allow_pickle=False)
    except Exception as e:
        raise WireFormatError(f"corrupt RPC frame (npz decode): {e!r}")
    with z:
        try:
            h = json.loads(bytes(z["header"].tobytes()).decode())
        except Exception as e:
            raise WireFormatError(f"corrupt RPC frame header: {e!r}")
        if h.get("v") != WIRE_VERSION:
            raise WireFormatError(
                f"RPC wire version {h.get('v')!r} != {WIRE_VERSION} — "
                "manager and worker builds disagree")
        verb = h.get("verb")
        if not isinstance(verb, str) or not verb:
            raise WireFormatError("RPC frame carries no verb")
        arrays = {k: z[k] for k in z.files if k != "header"}
    return verb, h, arrays


class _FrameConn:
    """Length-prefixed frames over one stream socket.  Reads are
    non-blocking (select-bounded) and a single frame's ASSEMBLY is
    deadline-bounded: a peer trickling one frame byte-by-byte (the
    slowloris case `PDTPU_FAULT_NET_DELAY` injects) raises the typed
    WireFormatError instead of occupying `recv_frames` forever.  Writes
    tolerate partial sends under `send_timeout` and raise WorkerDiedError
    past it — the peer being too wedged to drain its socket buffer is a
    liveness verdict, not a reason to hang the fleet loop.  When
    `fault_index` names this endpoint's replica, every send/recv
    consults the PDTPU_FAULT_NET_* chaos knobs (delay trickle, mid-frame
    cut, blackhole partition with the socket alive)."""

    def __init__(self, sock: socket.socket, send_timeout: float = 10.0,
                 frame_deadline: Optional[float] = 30.0,
                 fault_index: Optional[int] = None):
        self._sock = sock
        self._sock.setblocking(False)
        try:
            # every send is one complete frame — Nagle can only add
            # latency here (the classic 40ms delayed-ACK stall turns an
            # incremental chunk stream into one end-of-stream lump)
            self._sock.setsockopt(socket.IPPROTO_TCP,
                                  socket.TCP_NODELAY, 1)
        except OSError:
            pass  # not a TCP socket (tests wrap socketpairs)
        self._buf = bytearray()
        self._wlock = threading.Lock()
        self._send_timeout = send_timeout
        self._frame_deadline = frame_deadline
        self._fault_index = fault_index
        self._asm_started: Optional[float] = None
        self._sent_frames = 0
        self._closed = False
        self._eof = False

    def _send_view(self, view: memoryview, what: str):
        """Push every byte of `view` under the send deadline, riding out
        partial writes (a full socket buffer hands back short sends, not
        errors)."""
        deadline = time.monotonic() + self._send_timeout
        while view:
            budget = deadline - time.monotonic()
            if budget <= 0:
                raise WorkerDiedError(
                    f"RPC send of {what} stalled "
                    f">{self._send_timeout}s — peer not draining")
            _, w, _ = select.select([], [self._sock], [], budget)
            if not w:
                continue
            try:
                n = self._sock.send(view)
            except (BlockingIOError, InterruptedError):
                continue
            except OSError as e:
                raise WorkerDiedError(f"RPC send failed: {e!r}")
            view = view[n:]

    def send(self, verb: str, header: Optional[dict] = None,
             arrays: Optional[dict] = None):
        data = pack_frame(verb, header, arrays)
        if _faults.net_partition_active(self._fault_index):
            return  # blackholed: the bytes vanish, the socket stays up
        with self._wlock:
            if self._closed:
                raise WorkerDiedError("RPC connection is closed")
            if _faults.maybe_net_drop():
                # cut mid-frame: half the bytes land, then the socket
                # dies under the peer's feet — the torn-stream case
                try:
                    self._send_view(
                        memoryview(data)[:max(1, len(data) // 2)],
                        repr(verb))
                finally:
                    self._closed = True
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                raise WorkerDiedError(
                    f"RPC send of {verb!r} cut mid-frame "
                    "(PDTPU_FAULT_NET_DROP)")
            seq = self._sent_frames
            self._sent_frames += 1
            delay = _faults.net_delay_config()
            if delay is not None and seq % delay[1] == 0:
                # slowloris: trickle the frame in tiny bursts so the
                # RECEIVER's assembly deadline is what trips
                view = memoryview(data)
                while view:
                    self._send_view(view[:64], repr(verb))
                    view = view[64:]
                    if view:
                        time.sleep(delay[0] / 1000.0)
                return
            self._send_view(memoryview(data), repr(verb))

    def recv_frames(self, max_wait: float = 0.0) -> List[Tuple]:
        """Every complete frame currently available (waiting up to
        `max_wait` for the first byte).  Raises WorkerDiedError when the
        peer closed the connection, WireFormatError when one frame's
        assembly outlives `frame_deadline` (the slow-peer hold)."""
        if _faults.net_partition_active(self._fault_index):
            # blackholed: nothing readable, but no error either — the
            # connection LOOKS idle, which is the whole point
            if max_wait > 0:
                time.sleep(min(max_wait, 0.002))
            return []
        first = True
        while not self._eof:
            try:
                r, _, _ = select.select([self._sock], [], [],
                                        max_wait if first else 0.0)
            except OSError as e:
                raise WorkerDiedError(f"RPC socket lost: {e!r}")
            first = False
            if not r:
                break
            try:
                chunk = self._sock.recv(1 << 16)
            except (BlockingIOError, InterruptedError):
                break
            except OSError as e:
                raise WorkerDiedError(f"RPC recv failed: {e!r}")
            if not chunk:
                # EOF: deliver every COMPLETE frame already buffered
                # before raising (a typed `fatal` sent right before the
                # peer closed must never be lost to the close itself);
                # the death verdict lands on the next call
                self._eof = True
                break
            self._buf.extend(chunk)
        frames = []
        while True:
            if len(self._buf) < _LEN.size:
                break
            (n,) = _LEN.unpack_from(self._buf)
            if n > _MAX_FRAME:
                raise WireFormatError(
                    f"frame length {n} exceeds the {_MAX_FRAME} cap — "
                    "corrupt stream")
            if len(self._buf) < _LEN.size + n:
                break
            payload = bytes(self._buf[_LEN.size:_LEN.size + n])
            del self._buf[:_LEN.size + n]
            frames.append(unpack_frame(payload))
        if not frames and self._eof:
            raise WorkerDiedError("RPC peer closed the connection")
        if frames:
            # progress: whatever partial tail remains is a NEW frame
            self._asm_started = None
        if self._buf:
            now = time.monotonic()
            if self._asm_started is None:
                self._asm_started = now
            elif (self._frame_deadline is not None
                  and now - self._asm_started > self._frame_deadline):
                raise WireFormatError(
                    f"partial frame stuck {now - self._asm_started:.1f}s "
                    f"(> {self._frame_deadline}s assembly deadline) — "
                    "slow peer or torn stream")
        else:
            self._asm_started = None
        return frames

    def close(self):
        with self._wlock:
            self._closed = True
            try:
                self._sock.close()
            except OSError:
                pass

    def drain_close(self, timeout: float = 5.0):
        """Error-reply half-close: stop sending, then discard inbound
        until the peer closes (bounded).  A plain close() with unread
        bytes in the kernel buffer answers the peer with RST — which
        destroys the typed `fatal` frame still in flight to it.  The
        drain keeps the stream FIN-clean so the verdict arrives."""
        with self._wlock:
            self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_WR)
        except OSError:
            pass
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                r, _, _ = select.select([self._sock], [], [], 0.1)
                if r and not self._sock.recv(1 << 16):
                    break
            except OSError:
                break
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# heartbeat side channel
# ---------------------------------------------------------------------------

class _Heartbeat:
    """Worker-side heartbeat writer: a small json file atomically
    replaced after every completed step (throttled).  The file — not the
    RPC socket — is the liveness signal: a wedged step stops the
    rewrites while the socket stays connected."""

    def __init__(self, path: str, min_interval: float = 0.02):
        self._path = path
        self._min_interval = min_interval
        self._last = 0.0

    def beat(self, steps: int, phase: str = "serve", force: bool = False):
        now = time.time()
        if not force and now - self._last < self._min_interval:
            return
        tmp = f"{self._path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w") as f:
                # `mono` (CLOCK_MONOTONIC — one timeline for every
                # process on the machine) is what age is computed from:
                # an NTP step / suspend-resume wall-clock jump must not
                # falsely wedge-fence the whole fleet.  Wall `time`
                # rides along for humans reading the file.
                f.write(json.dumps({"steps": int(steps), "time": now,
                                    "mono": time.monotonic(),
                                    "pid": os.getpid(), "phase": phase}))
            os.replace(tmp, self._path)
            self._last = now
        except OSError:
            pass  # a failed beat reads as staleness — the safe direction


def read_heartbeat(path: str) -> Optional[dict]:
    """Last complete heartbeat record, or None (no beat yet / torn
    file — os.replace makes torn reads near-impossible, but a missing
    file during boot is normal)."""
    try:
        with open(path) as f:
            return json.loads(f.read())
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# worker process: boot + serve loop
# ---------------------------------------------------------------------------

def build_gpt(seed: int = 0, **config):
    """Deterministic GPT factory for boot specs: same seed + config in
    any process reproduces bit-identical weights (jax PRNG init), so a
    restarted worker serves the exact model its predecessor did without
    shipping weights over the wire.  Real deployments point
    ``spec["model"]["factory"]`` at their own loader (restoring a
    jit.save artifact) instead."""
    import paddle_tpu as paddle
    from paddle_tpu import models
    paddle.seed(int(seed))
    model = models.GPTForPretraining(models.GPTConfig(**config))
    model.eval()
    return model


def _resolve(path: str):
    """'pkg.mod:callable' -> the callable."""
    import importlib
    mod, sep, name = path.partition(":")
    if not sep or not name:
        raise InvalidArgumentError(
            f"factory {path!r} must be 'package.module:callable'")
    return getattr(importlib.import_module(mod), name)


def _apply_weights(model, path: str) -> str:
    """Load a jit.save-style npz state dict onto `model` (the shipped /
    shared-storage weight artifact) and return the artifact's sha256.
    Any mismatch with the model is a typed WeightShipError — a worker
    must never serve half-loaded parameters."""
    from .transfer import file_sha256
    try:
        data = np.load(path, allow_pickle=False)
    except Exception as e:
        raise WeightShipError(f"weight artifact {path!r} unreadable: {e!r}")
    with data:
        state = {k: data[k] for k in data.files}
    try:
        missing, unexpected = model.set_state_dict(state)
    except Exception as e:
        raise WeightShipError(f"weight artifact does not fit the model: {e}")
    if missing or unexpected:
        raise WeightShipError(
            f"weight artifact does not match the model: "
            f"missing={sorted(missing)[:4]} "
            f"unexpected={sorted(unexpected)[:4]}")
    return file_sha256(path)


def _build_engine(spec: dict):
    """Boot spec -> (ServingEngine, weights_sha).  ``spec["weights"]``
    (an npz path — shipped over the attach handshake or on shared
    storage) replaces the factory's seeded parameters before the engine
    captures them; weights_sha is None for seeded boots."""
    from .engine import ServingEngine
    model = _resolve(spec["model"]["factory"])(
        **(spec["model"].get("kwargs") or {}))
    weights_sha = None
    if spec.get("weights"):
        weights_sha = _apply_weights(model, spec["weights"])
    draft = None
    if spec.get("draft"):
        draft = _resolve(spec["draft"]["factory"])(
            **(spec["draft"].get("kwargs") or {}))
    ekw = dict(spec.get("engine") or {})
    if ekw.get("prefill_buckets") is not None:
        ekw["prefill_buckets"] = tuple(int(b)
                                       for b in ekw["prefill_buckets"])
    if spec.get("lora"):
        from ..lora import LoRAConfig
        ekw["lora"] = LoRAConfig.from_spec(spec["lora"])
    return ServingEngine(model, draft_model=draft,
                         program_set=spec.get("program_set"),
                         **ekw), weights_sha


class _WireResponse(Response):
    """Worker-local response that additionally records per-token logps
    so stream chunks carry them across the wire (the base Response only
    keeps the cumulative sum)."""

    def __init__(self, req: Request):
        super().__init__(req)
        self.logps: List[float] = []

    def _push_token(self, tok: int, logp: float = 0.0):
        super()._push_token(tok, logp)
        self.logps.append(float(logp))


def _jsonable(obj):
    """Best-effort scalar-tree copy for status/metrics headers."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (str, int, bool)) or obj is None:
        return obj
    if isinstance(obj, float):
        return obj if np.isfinite(obj) else None
    return str(obj)


class _WorkerServer:
    """The worker's single-threaded drive loop (see module docstring).
    Local mode: `hb` writes the heartbeat file and the manager owns the
    process.  Remote mode (`listener` set): liveness is pushed as beat
    frames on `beat_conn`, the session carries a manager-issued `epoch`
    token, and losing the manager (connection or `manager_silence_s` of
    inbound silence) aborts every resident typed and returns the worker
    to its accept loop — it never exits just because one manager did."""

    def __init__(self, engine, conn: _FrameConn, hb: Optional[_Heartbeat],
                 index: int, epoch: int = 0,
                 beat_conn: Optional[_FrameConn] = None,
                 manager_silence_s: Optional[float] = None,
                 listener: Optional[socket.socket] = None,
                 weights_sha: Optional[str] = None,
                 cache: Optional[dict] = None,
                 _clock=time.monotonic):
        from ..utils import faults
        self._faults = faults
        self.engine = engine
        self.conn = conn
        self.hb = hb
        self.index = index
        self.epoch = int(epoch)
        self.beat_conn = beat_conn
        # the remote session's engine cache (remote mode only): a weight
        # swap updates its sha/key so a post-partition re-attach reuses
        # the swapped engine and ships zero bytes
        self._cache = cache
        self.manager_silence_s = (None if manager_silence_s is None
                                  else float(manager_silence_s))
        self.listener = listener
        self.weights_sha = weights_sha
        self._clock = _clock
        self._last_rx = _clock()
        self._last_beat_tx = 0.0
        self._seen_wids: set = set()  # submit dedup (exactly-once admit)
        self.pending_attach = None    # (conn, header) epoch takeover
        self.detach: Optional[str] = None
        self.streams: Dict[int, list] = {}  # wid -> [resp, n_sent]
        self.step_no = 0
        self._ewma: Optional[float] = None
        self._recent_dts: List[float] = []
        self._last_status = 0.0
        self._stopping = False

    # -- inbound verbs --------------------------------------------------
    def _handle(self, verb: str, h: dict, arrays: dict):
        if verb == "submit":
            self._on_submit(h, arrays)
        elif verb == "cancel":
            entry = self.streams.get(h.get("wid"))
            if entry is not None:
                entry[0].cancel()
        elif verb == "preempt":
            self._on_preempt(h)
        elif verb == "restore":
            self._on_restore(h, arrays)
        elif verb == "metrics":
            self.conn.send("metrics", {
                "wid": h.get("wid"),
                "metrics": _jsonable(self.engine.metrics())})
        elif verb == "fault":
            point, value = h.get("point"), h.get("value")
            if value is None:
                self._faults.disable(point)
            else:
                self._faults.enable(point, value)
        elif verb == "swap_weights":
            self._on_swap(h)
        elif verb == "load_adapter":
            self._on_load_adapter(h)
        elif verb == "close":
            self._stopping = True
        elif verb == "ping":
            pass  # liveness only: receipt already fed the silence clock
        elif verb == "abort_epoch":
            if int(h.get("epoch", -1)) == self.epoch:
                # the manager declared this session stale: a resubmitted
                # twin of every resident may already be live elsewhere
                self._abort_residents(
                    "epoch superseded (manager abort_epoch)")
                self.detach = "abort_epoch"
        else:
            self.conn.send("log", {"msg": f"unknown verb {verb!r} ignored"})

    def _on_submit(self, h: dict, arrays: dict):
        wid = int(h["wid"])
        if wid in self._seen_wids:
            # retried submit after a lost/timed-out ack: exactly-once
            # admission — re-ack, never double-admit
            self.conn.send("accepted", {"wid": wid, "epoch": self.epoch,
                                        "dup": True})
            return
        self._seen_wids.add(wid)
        try:
            req, _ = self.engine.make_request(
                np.asarray(arrays["prompt"], np.int32),
                int(h["max_new_tokens"]),
                decode_strategy=h.get("decode_strategy", "greedy_search"),
                temperature=h.get("temperature", 1.0),
                top_k=h.get("top_k", 0), top_p=h.get("top_p", 1.0),
                eos_token_id=h.get("eos_token_id"), seed=h.get("seed"),
                deadline=h.get("deadline_remaining_s"),
                priority=h.get("priority", 0), tenant=h.get("tenant"),
                spec=h.get("spec"), session=h.get("session"),
                resubmit=h.get("resubmit", False),
                adapter=h.get("adapter"))
            resp = _WireResponse(req)
            self.engine.scheduler.submit(req, resp)
        except Exception as e:
            self.conn.send("failed", {"wid": wid,
                                      "etype": type(e).__name__,
                                      "msg": str(e)[:500]})
            return
        self.streams[wid] = [resp, 0]
        self.conn.send("accepted", {"wid": wid, "epoch": self.epoch})

    def _find_slot(self, resp) -> Optional[int]:
        for slot, run in self.engine._slots.items():
            if run.resp is resp:
                return slot
        return None

    def _on_preempt(self, h: dict):
        from .transfer import encode_run, run_to_bytes
        wid = int(h["wid"])
        entry = self.streams.get(wid)
        slot = None if entry is None else self._find_slot(entry[0])
        if slot is None:
            # finished / still queued / unknown — nothing resident to move
            self.conn.send("preempted", {"wid": wid, "ok": False,
                                         "reason": "not-resident"})
            return
        # flush BEFORE snapshotting: the manager must hold every token
        # `produced` counts, or the migrated continuation would skip the
        # in-flight tail and the stream would lose tokens silently
        self._flush_one(wid, entry)
        paused = self.engine.preempt_slot(slot)
        blob = run_to_bytes(encode_run(paused, engine=self.engine))
        self.streams.pop(wid, None)
        self.conn.send("preempted", {"wid": wid, "ok": True},
                       {"run": np.frombuffer(blob, np.uint8).copy()})

    def _on_restore(self, h: dict, arrays: dict):
        from .transfer import decode_run, run_from_bytes
        wid = int(h["wid"])
        try:
            blob = run_from_bytes(arrays["run"].tobytes())
            paused = decode_run(blob, engine=self.engine)
            resp = _WireResponse(paused.req)
            paused.resp = resp
            ok = self.engine.restore_run(paused)
        except Exception as e:
            self.conn.send("restored", {"wid": wid, "ok": False,
                                        "etype": type(e).__name__,
                                        "msg": str(e)[:500]})
            return
        if ok:
            self.streams[wid] = [resp, 0]
        self.conn.send("restored", {"wid": wid, "ok": bool(ok)})

    def _on_swap(self, h: dict):
        """Continuous weight refresh: rebind the engine's served
        weights to a new artifact with ZERO recompiles
        (ServingEngine.swap_weights — the compiled programs take the
        state as a per-call argument).  Local mode: the artifact is a
        path on this host, sha256-verified before a byte reaches the
        engine.  Remote mode: the header carries a manifest and the
        bytes follow as chunk frames after the `swap_ready` ack, over
        the same verified channel the attach handshake uses.  Any
        failure — truncated file, sha mismatch, shape mismatch — is
        reported typed and leaves the OLD weights serving."""
        from .transfer import file_sha256
        wid = h.get("wid")
        sha = h.get("sha256")
        man = h.get("manifest")
        try:
            if man is not None:
                if self._cache is not None:
                    path = os.path.join(self._cache["dir"], "weights.npz")
                else:
                    path = os.path.join(
                        tempfile.mkdtemp(prefix="pdtpu_swap_"),
                        "weights.npz")
                self.conn.send("swap_ready", {"wid": wid})
                self._recv_swap_chunks(man, path)
                sha = man.get("sha256")
            else:
                path = h.get("path")
                if not path:
                    raise WeightShipError(
                        "swap_weights needs a path (local) or a "
                        "manifest (remote)")
                actual = file_sha256(path)
                if sha is not None and actual != sha:
                    raise WeightShipError(
                        f"weight artifact {path!r} sha256 {actual} != "
                        f"published {sha} — refusing corrupt weights")
                sha = actual
            with np.load(path, allow_pickle=False) as z:
                state = {k: z[k] for k in z.files}
            self.engine.swap_weights(state, sha)
        except Exception as e:  # noqa: BLE001 — typed rejection, old
            #                     weights keep serving
            self.conn.send("swapped", {"wid": wid, "ok": False,
                                       "etype": type(e).__name__,
                                       "msg": str(e)[:500]})
            return
        self.weights_sha = sha
        if self._cache is not None:
            # a post-partition re-attach carrying the NEW manifest must
            # reuse this engine and ship zero bytes
            self._cache["weights_sha"] = sha
            key = self._cache.get("key")
            if key is not None:
                self._cache["key"] = (key[0], sha, key[2])
        self.conn.send("swapped", {"wid": wid, "ok": True,
                                   "weights_sha": sha})

    def _recv_swap_chunks(self, man: dict, path: str):
        """Receive the swap artifact's chunk stream (sent only after our
        `swap_ready` ack, so no chunk can race into the serve loop's
        frame batch ahead of this read)."""
        _recv_artifacts(self.conn, {"weights": (man, path)})

    def _on_load_adapter(self, h: dict):
        """Multi-tenant LoRA hot-load: page one adapter artifact into
        the engine's registry with ZERO recompiles (the factor stacks
        are per-call program arguments, exactly like the swapped
        weights).  Local mode: the artifact is a path on this host,
        verified against the published sha256 before the registry reads
        it.  Remote mode: the header carries a manifest; if the named
        adapter is already resident with the SAME artifact sha the
        worker answers `cached` and zero bytes ship, otherwise the
        chunk stream follows our `adapter_ready` ack over the same
        verified channel the attach handshake uses.  Any failure —
        corrupt bytes, base-hash/rank mismatch, every slot pinned — is
        reported typed and leaves the registry unchanged."""
        from ..lora import AdapterIntegrityError
        from .transfer import file_sha256
        wid = h.get("wid")
        name = h.get("name")
        man = h.get("manifest")
        try:
            if getattr(self.engine, "lora", None) is None:
                raise InvalidArgumentError(
                    "worker engine was not built with lora="
                    "LoRAConfig(...) — add a 'lora' key to the boot "
                    "spec")
            reg = self.engine._lora_reg
            if man is not None:
                idx = reg.loaded().get(name)
                if (idx is not None
                        and reg.file_sha(idx) == man.get("sha256")):
                    # zero-byte re-attach: the identical artifact is
                    # already resident under this name
                    stat_add("STAT_lora_ship_reattaches")
                    self.conn.send("adapter_ready",
                                   {"wid": wid, "cached": True})
                    self.conn.send("adapter_loaded",
                                   {"wid": wid, "ok": True, "name": name,
                                    "file_sha": man.get("sha256"),
                                    "cached": True})
                    return
                if self._cache is not None:
                    d = os.path.join(self._cache["dir"], "adapters")
                else:
                    d = tempfile.mkdtemp(prefix="pdtpu_adapter_")
                os.makedirs(d, exist_ok=True)
                path = os.path.join(
                    d, f"{(man.get('sha256') or 'x')[:16]}.npz")
                self.conn.send("adapter_ready",
                               {"wid": wid, "cached": False})
                _recv_artifacts(self.conn, {"adapter": (man, path)})
            else:
                path = h.get("path")
                if not path:
                    raise InvalidArgumentError(
                        "load_adapter needs a path (local) or a "
                        "manifest (remote)")
                sha = h.get("sha256")
                if sha is not None and file_sha256(path) != sha:
                    raise AdapterIntegrityError(
                        f"adapter artifact {path!r} sha256 != published "
                        f"{sha} — refusing corrupt factors")
            file_sha = self.engine.load_adapter(name, path)
        except Exception as e:  # noqa: BLE001 — typed rejection, the
            #                     registry keeps its previous contents
            self.conn.send("adapter_loaded",
                           {"wid": wid, "ok": False,
                            "etype": type(e).__name__,
                            "msg": str(e)[:500]})
            return
        self.conn.send("adapter_loaded", {"wid": wid, "ok": True,
                                          "name": name,
                                          "file_sha": file_sha})

    # -- outbound stream/status -----------------------------------------
    def _flush_one(self, wid: int, entry: list) -> bool:
        resp, sent = entry
        toks = resp.tokens_so_far()
        if len(toks) > sent:
            self.conn.send(
                "chunk", {"wid": wid},
                {"toks": np.asarray(toks[sent:], np.int64),
                 "logps": np.asarray(resp.logps[sent:len(toks)],
                                     np.float64)})
            entry[1] = len(toks)
        if resp.done():
            if resp.error is not None:
                self.conn.send("failed",
                               {"wid": wid,
                                "etype": type(resp.error).__name__,
                                "msg": str(resp.error)[:500]})
            else:
                self.conn.send("done", {"wid": wid,
                                        "reason": resp.finish_reason})
            return True
        return False

    def _flush(self):
        for wid in list(self.streams):
            if self._flush_one(wid, self.streams[wid]):
                self.streams.pop(wid, None)

    def _maybe_status(self):
        now = time.time()
        if now - self._last_status < 0.05:
            return
        self._last_status = now
        sched = self.engine.scheduler
        dts, self._recent_dts = self._recent_dts, []
        self.conn.send(
            "status",
            {"occupancy": sched.occupancy(),
             "queue_depth": sched.queue_depth(),
             "free_slots": sched.free_slot_count(),
             "steps": self.step_no,
             "epoch": self.epoch,
             "weights_sha": self.weights_sha,
             "ewma_ms": (None if self._ewma is None
                         else self._ewma * 1e3),
             "post_warmup_compiles": self.engine.post_warmup_compiles(),
             "metrics": _jsonable(self.engine.metrics())},
            {"step_s": np.asarray(dts, np.float64)})

    def _push_beat(self, force: bool = False):
        """Remote liveness: one tiny beat frame on the side connection
        after each step (throttled).  Send failure is swallowed — a dead
        beat channel reads as staleness on the manager, which is the
        safe direction."""
        if self.beat_conn is None:
            return
        now = self._clock()
        if not force and now - self._last_beat_tx < 0.02:
            return
        self._last_beat_tx = now
        try:
            self.beat_conn.send("beat", {"steps": self.step_no,
                                         "mono": time.monotonic(),
                                         "epoch": self.epoch,
                                         "phase": "serve"})
        except (WorkerDiedError, WireFormatError, OSError):
            pass

    # -- remote-session fencing -----------------------------------------
    def _abort_residents(self, reason: str, exc_cls=None):
        """Fail every resident + queued run typed and report it to the
        manager best-effort (during a partition these frames blackhole,
        which is fine: the manager already fenced and resubmitted — what
        matters is that THIS side stops decoding, so no token is ever
        served twice)."""
        cls = exc_cls or StaleEpochError
        self.engine._abort_all(lambda req: cls(
            f"request {req.id} aborted on worker {self.index} "
            f"(epoch {self.epoch}): {reason}"))
        try:
            self._flush()
        except (WorkerDiedError, WireFormatError):
            pass
        self.streams.clear()

    def _check_manager_silence(self) -> bool:
        """Partition self-fence: nothing inbound (frames OR pings) for
        `manager_silence_s` means the manager either died or cannot
        reach us — and in both cases it has fenced this replica on beat
        age and resubmitted elsewhere, so the residents must die HERE."""
        if self.manager_silence_s is None:
            return False
        if self._clock() - self._last_rx <= self.manager_silence_s:
            return False
        self._abort_residents(
            f"manager silent >{self.manager_silence_s}s — assuming "
            "partition; the fleet has resubmitted these runs elsewhere")
        self.detach = "manager-silence"
        return True

    def _poll_listener(self) -> bool:
        """Non-blocking accept on the standalone listener: a NEW attach
        with a HIGHER epoch supersedes this session (a manager healed
        from a partition re-attaches); lower-or-equal epochs are stale
        managers and are refused with a typed fatal."""
        if self.listener is None:
            return False
        try:
            s, _ = self.listener.accept()
        except (BlockingIOError, socket.timeout, OSError):
            return False
        nc = _FrameConn(s, fault_index=self.index)
        try:
            h, _ = _wait_frame(nc, "attach", timeout=5.0)
        except (WorkerDiedError, WireFormatError):
            nc.close()
            return False
        if int(h.get("epoch", 0)) <= self.epoch:
            try:
                nc.send("fatal", {
                    "etype": "StaleEpochError",
                    "msg": (f"attach epoch {h.get('epoch')} <= live "
                            f"epoch {self.epoch} — refusing a stale "
                            "manager")})
            except (WorkerDiedError, WireFormatError):
                pass
            nc.close()
            return False
        self.pending_attach = (nc, h)
        self._abort_residents(
            f"superseded by attach epoch {h.get('epoch')}")
        self.detach = "reattach"
        return True

    # -- the loop -------------------------------------------------------
    def serve(self) -> int:
        """Drive until exit.  Return codes: 0 = clean local exit, 4 =
        engine step died, 5 = remote session over (abort residents done;
        keep the process alive and go back to the accept loop)."""
        remote = self.listener is not None
        while True:
            try:
                frames = self.conn.recv_frames(
                    0.0 if self.engine.has_work() else 0.002)
            except WorkerDiedError as e:
                if remote:
                    # standalone worker: the manager is gone but this
                    # process is not its child — abort residents typed
                    # (a resubmitted twin may already be streaming
                    # elsewhere) and go back to listening
                    self._abort_residents(f"manager connection lost ({e})")
                    self.detach = "manager-lost"
                    return 5
                # manager gone: a spawned worker never outlives its fleet
                print(f"worker exiting: manager connection lost ({e})",
                      file=sys.stderr, flush=True)
                self.engine.close()
                return 0
            except WireFormatError as e:
                # torn/trickled stream (the slowloris assembly deadline):
                # this connection is unrecoverable
                if remote:
                    self._abort_residents(f"wire error ({e})")
                    self.detach = "wire-error"
                    return 5
                print(f"worker exiting: wire error ({e})",
                      file=sys.stderr, flush=True)
                self.engine.close()
                return 4
            if frames:
                self._last_rx = self._clock()
            for verb, h, arrays in frames:
                try:
                    self._handle(verb, h, arrays)
                except WorkerDiedError as e:
                    # reply channel gone mid-handle: manager is dead
                    if remote:
                        self._abort_residents(
                            f"manager connection lost mid-frame ({e})")
                        self.detach = "manager-lost"
                        return 5
                    print(f"worker exiting: manager connection lost "
                          f"mid-frame ({e})", file=sys.stderr, flush=True)
                    self.engine.close()
                    return 0
                except Exception as e:  # noqa: BLE001
                    # a malformed/garbled frame (missing field, bad
                    # type) must cost its sender an error report, never
                    # the whole worker — the WireFormatError stance
                    # applied to frame CONTENT too
                    try:
                        self.conn.send("log", {
                            "error": f"frame {verb!r} failed: "
                                     f"{type(e).__name__}: {e}"})
                    except WorkerDiedError:
                        pass
            if self._stopping:
                if remote:
                    # close ends the SESSION, not the process — the
                    # manager does not own a standalone worker
                    self._abort_residents("manager closed the session",
                                          exc_cls=RequestCancelled)
                    try:
                        self.conn.send("bye", {})
                    except (WorkerDiedError, WireFormatError):
                        pass
                    self.detach = "close"
                    return 5
                print("worker exiting: close verb received",
                      file=sys.stderr, flush=True)
                self.engine.close()
                self._flush()
                try:
                    self.conn.send("bye", {})
                except WorkerDiedError:
                    pass
                return 0
            if self.detach is not None:  # abort_epoch landed
                return 5
            if self._check_manager_silence():
                return 5
            if self._poll_listener():
                return 5
            # the wedge fault blocks HERE forever when armed: the socket
            # stays connected, frames pile up unread, and only the
            # heartbeat (file or beat frames — below, never reached)
            # goes stale
            self._faults.maybe_wedge_replica(self.index, self.step_no)
            t0 = time.perf_counter()
            self._faults.maybe_slow_replica(self.index, self.step_no)
            try:
                self.engine.step()
            except BaseException as e:  # noqa: BLE001 — report, then die
                try:
                    self.conn.send("dying", {"etype": type(e).__name__,
                                             "msg": str(e)[:500]})
                except WorkerDiedError:
                    pass
                return 4
            dt = time.perf_counter() - t0
            self.step_no += 1
            self._ewma = (dt if self._ewma is None
                          else 0.3 * dt + 0.7 * self._ewma)
            self._recent_dts.append(dt)
            if self.hb is not None:
                self.hb.beat(self.step_no)
            self._push_beat()
            try:
                self._flush()
                self._maybe_status()
            except (WorkerDiedError, WireFormatError) as e:
                if remote:
                    self._abort_residents(f"manager send path died ({e})")
                    self.detach = "manager-lost"
                    return 5
                print(f"worker exiting: manager connection lost ({e})",
                      file=sys.stderr, flush=True)
                self.engine.close()
                return 0


def _ready_header(engine, warm: dict, epoch: int = 0,
                  weights_sha: Optional[str] = None,
                  shipped: Optional[dict] = None) -> dict:
    from .transfer import target_manifest
    h = {
        "config": {
            "max_slots": engine.max_slots,
            "max_len": engine.max_len,
            "buckets": list(engine.buckets),
            "max_queue_depth": engine.scheduler.max_queue_depth,
            "has_draft": engine.draft_model is not None,
            "kv": engine.kv,
            "pid": os.getpid(),
        },
        "manifest": target_manifest(engine),
        "warmup": {"seconds": warm.get("seconds"),
                   "programs": warm.get("programs")},
        "epoch": int(epoch),
        "weights_sha": weights_sha,
    }
    if shipped is not None:
        h["shipped"] = {k: int(v) for k, v in shipped.items()}
    return h


def _wait_frame(conn: _FrameConn, want_verb: str,
                timeout: float) -> Tuple[dict, dict]:
    """Block (bounded) until the next frame, which must be `want_verb` —
    the handshake protocol is strictly sequenced, so anything else is a
    typed protocol error."""
    deadline = time.monotonic() + timeout
    while True:
        for verb, h, arrays in conn.recv_frames(0.05):
            if verb == want_verb:
                return h, arrays
            raise WireFormatError(
                f"handshake expected {want_verb!r}, got {verb!r}")
        if time.monotonic() > deadline:
            raise WorkerDiedError(
                f"no {want_verb!r} frame within {timeout}s")


def _recv_artifacts(conn: _FrameConn, wants: dict,
                    timeout: float = 300.0) -> dict:
    """Receive the attach handshake's chunked artifact ship.  `wants`
    maps name -> (manifest-or-None, dest_path); chunks must arrive in
    order and every chunk AND the assembled file must match the
    manifest's sha256 — any mismatch is a typed WeightShipError before a
    single byte reaches an engine.  Returns name -> bytes received."""
    import hashlib
    verbs = {"weights_chunk": "weights", "program_chunk": "programs",
             "adapter_chunk": "adapter"}
    state = {}
    for name, (man, path) in wants.items():
        if man is not None:
            state[name] = {"f": open(path, "wb"), "h": hashlib.sha256(),
                           "seq": 0, "bytes": 0, "man": man}
    try:
        deadline = time.monotonic() + timeout
        done = False
        while not done:
            if time.monotonic() > deadline:
                raise WorkerDiedError(
                    f"artifact ship timed out after {timeout}s")
            for verb, h, arrays in conn.recv_frames(0.05):
                if verb == "attach_end":
                    done = True
                    break
                name = verbs.get(verb)
                if name is None:
                    continue  # e.g. a keepalive ping mid-ship
                st = state.get(name)
                if st is None:
                    raise WeightShipError(
                        f"unsolicited {verb} (artifact not requested)")
                seq = int(h.get("seq", -1))
                if seq != st["seq"]:
                    raise WeightShipError(
                        f"{name} chunk {seq} out of order "
                        f"(expected {st['seq']})")
                chunks = st["man"].get("chunks") or []
                data = arrays["data"].tobytes()
                if (seq >= len(chunks)
                        or hashlib.sha256(data).hexdigest()
                        != chunks[seq].get("sha256")):
                    raise WeightShipError(
                        f"{name} chunk {seq} sha256 mismatch — refusing "
                        "to assemble garbage weights")
                st["f"].write(data)
                st["h"].update(data)
                st["seq"] += 1
                st["bytes"] += len(data)
        out = {}
        for name, st in state.items():
            st["f"].close()
            chunks = st["man"].get("chunks") or []
            if st["seq"] != len(chunks):
                raise WeightShipError(
                    f"{name} artifact short: {st['seq']}/{len(chunks)} "
                    "chunks before attach_end")
            if st["h"].hexdigest() != st["man"].get("sha256"):
                raise WeightShipError(
                    f"{name} whole-artifact sha256 mismatch")
            out[name] = st["bytes"]
        return out
    finally:
        for st in state.values():
            try:
                st["f"].close()
            except OSError:
                pass


def _accept_beat(lsock: socket.socket, epoch: int, index: int,
                 timeout: float = 30.0) -> _FrameConn:
    """Accept the manager's dedicated beat side connection (it must
    introduce itself with a matching-epoch `beat_attach`)."""
    deadline = time.monotonic() + timeout
    lsock.settimeout(0.2)
    try:
        while time.monotonic() < deadline:
            try:
                s, _ = lsock.accept()
            except socket.timeout:
                continue
            except OSError as e:
                raise WorkerDiedError(f"beat accept failed: {e!r}")
            bc = _FrameConn(s, fault_index=index)
            try:
                h, _ = _wait_frame(bc, "beat_attach", timeout=5.0)
            except (WorkerDiedError, WireFormatError):
                bc.close()
                continue
            if int(h.get("epoch", -1)) != epoch:
                bc.close()
                continue
            return bc
        raise WorkerDiedError(
            f"no beat side-connection within {timeout}s")
    finally:
        # the serve loop's listener poll needs non-blocking accepts
        lsock.setblocking(False)


def _serve_session(lsock: socket.socket, conn: _FrameConn, attach: dict,
                   index: int, cache: dict) -> Tuple[int, Optional[tuple]]:
    """One manager session on an accepted connection: attach handshake
    (artifact ship + beat side channel + engine build/reuse), then serve
    until detach.  Returns (rc, pending_attach); rc 5 means 'session
    over, keep listening'.  The engine is CACHED across sessions keyed
    on (spec, weights sha, programs sha): a manager re-attaching after a
    partition pays zero rebuild and zero re-ship."""
    epoch = int(attach.get("epoch", 0))
    spec = dict(attach.get("spec") or {})
    wman = attach.get("weights")
    pman = attach.get("programs")
    silence = attach.get("silence_s")
    need_w = (wman is not None
              and wman.get("sha256") != cache.get("weights_sha"))
    need_p = (pman is not None
              and pman.get("sha256") != cache.get("programs_sha"))
    wpath = os.path.join(cache["dir"], "weights.npz")
    ppath = os.path.join(cache["dir"], "programs")

    def _fatal(e: BaseException) -> Tuple[int, None]:
        print(f"worker session failed: {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)
        try:
            conn.send("fatal", {"etype": type(e).__name__,
                                "msg": str(e)[:800], "epoch": epoch})
        except (WorkerDiedError, WireFormatError):
            pass
        # half-close + drain: the manager may still be mid-ship, and a
        # plain close against its unread bytes would RST the typed
        # fatal right out of its receive buffer
        conn.drain_close()
        return 5, None

    try:
        conn.send("attach_ok", {"epoch": epoch, "need_weights": need_w,
                                "need_programs": need_p})
        shipped = _recv_artifacts(conn, {
            "weights": (wman if need_w else None, wpath),
            "programs": (pman if need_p else None, ppath)})
    except (WeightShipError, WireFormatError, WorkerDiedError) as e:
        return _fatal(e)
    if wman is not None:
        spec["weights"] = wpath
    if pman is not None:
        spec["program_set"] = ppath
    key = (json.dumps(attach.get("spec") or {}, sort_keys=True,
                      default=str),
           None if wman is None else wman.get("sha256"),
           None if pman is None else pman.get("sha256"))
    engine = cache.get("engine")
    if engine is None or cache.get("key") != key:
        if engine is not None:
            try:
                engine.close()
            except Exception:
                pass
            cache.update(engine=None, key=None)
        try:
            engine, _sha = _build_engine(spec)
            warm = engine.warmup()
        except Exception as e:  # noqa: BLE001 — boot failure, typed up
            return _fatal(e)
        cache.update(
            engine=engine, key=key, warm=warm,
            weights_sha=None if wman is None else wman.get("sha256"),
            programs_sha=None if pman is None else pman.get("sha256"))
    warm = cache.get("warm") or {}
    try:
        beat_conn = _accept_beat(lsock, epoch, index)
    except (WorkerDiedError, WireFormatError) as e:
        return _fatal(e)
    try:
        conn.send("ready", _ready_header(
            engine, warm, epoch=epoch,
            weights_sha=cache.get("weights_sha"), shipped=shipped))
    except (WorkerDiedError, WireFormatError):
        beat_conn.close()
        conn.close()
        return 5, None
    server = _WorkerServer(engine, conn, None, index, epoch=epoch,
                           beat_conn=beat_conn, manager_silence_s=silence,
                           listener=lsock,
                           weights_sha=cache.get("weights_sha"),
                           cache=cache)
    server._push_beat(force=True)
    rc = server.serve()
    conn.close()
    beat_conn.close()
    return rc, server.pending_attach


def _remote_main(host: str, port: int, index: int) -> int:
    """Standalone remote worker: listen for manager attaches forever,
    serving one epoch-tokened session at a time.  The worker owns its
    own lifetime — a lost or closed manager ends the SESSION (residents
    aborted typed), never the process."""
    lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind((host, port))
    lsock.listen(4)
    print(f"worker listening on {lsock.getsockname()[0]}:"
          f"{lsock.getsockname()[1]}", flush=True)
    cache = {"key": None, "engine": None, "weights_sha": None,
             "programs_sha": None, "warm": None,
             "dir": tempfile.mkdtemp(prefix=f"pdtpu_rworker{index}_")}
    pending = None
    try:
        while True:
            if pending is not None:
                conn, attach = pending
                pending = None
            else:
                lsock.settimeout(None)
                try:
                    s, _ = lsock.accept()
                except OSError:
                    return 0
                conn = _FrameConn(s, fault_index=index)
                try:
                    attach, _ = _wait_frame(conn, "attach", timeout=30.0)
                except (WorkerDiedError, WireFormatError) as e:
                    print(f"worker: bad attach: {e}", file=sys.stderr,
                          flush=True)
                    conn.close()
                    continue
            rc, pending = _serve_session(lsock, conn, attach, index, cache)
            if rc != 5:
                return rc
    finally:
        try:
            lsock.close()
        except OSError:
            pass
        eng = cache.get("engine")
        if eng is not None:
            try:
                eng.close()
            except Exception:
                pass
        shutil.rmtree(cache["dir"], ignore_errors=True)


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        description="paddle_tpu subprocess serving worker")
    ap.add_argument("--spec",
                    help="json boot spec path (local mode; a remote "
                         "worker receives its spec over the attach "
                         "handshake)")
    ap.add_argument("--port", type=int,
                    help="manager RPC port on 127.0.0.1 (local mode)")
    ap.add_argument("--heartbeat",
                    help="out-of-band heartbeat file path (local mode)")
    ap.add_argument("--index", type=int, default=0,
                    help="worker index (fault-knob target)")
    ap.add_argument("--listen", metavar="HOST:PORT",
                    help="standalone remote mode: listen for manager "
                         "attaches instead of dialing a spawning "
                         "manager (spec + weights arrive over the wire)")
    args = ap.parse_args(argv)

    # post-mortem hook for the failure mode this module exists to
    # survive: SIGUSR1 dumps every thread's stack to the log file, so a
    # wedged worker can be diagnosed before the manager SIGKILLs it
    import faulthandler
    import signal as _signal
    faulthandler.register(_signal.SIGUSR1, file=sys.stderr)

    if args.listen:
        host, _, port = args.listen.rpartition(":")
        try:
            return _remote_main(host or "127.0.0.1", int(port),
                                args.index)
        except KeyboardInterrupt:
            return 0
    if not (args.spec and args.port and args.heartbeat):
        ap.error("local mode requires --spec, --port and --heartbeat "
                 "(or use --listen HOST:PORT for remote mode)")

    hb = _Heartbeat(args.heartbeat)
    hb.beat(0, phase="boot", force=True)
    sock = socket.create_connection(("127.0.0.1", args.port), timeout=30)
    conn = _FrameConn(sock)
    try:
        with open(args.spec) as f:
            spec = json.load(f)
        engine, weights_sha = _build_engine(spec)
        warm = engine.warmup()
        hb.beat(0, phase="warm", force=True)
    except Exception as e:  # boot failure: report typed, exit nonzero
        try:
            conn.send("fatal", {"etype": type(e).__name__,
                                "msg": str(e)[:800]})
        except Exception:
            pass
        return 3
    conn.send("ready", _ready_header(engine, warm,
                                     weights_sha=weights_sha))
    return _WorkerServer(engine, conn, hb, args.index,
                         weights_sha=weights_sha).serve()


# ---------------------------------------------------------------------------
# manager side: WorkerClient (the subprocess replica's engine proxy)
# ---------------------------------------------------------------------------

_WIRE_ERRORS = None


def _error_types():
    global _WIRE_ERRORS
    if _WIRE_ERRORS is None:
        from ..lora import (AdapterExhaustedError, AdapterIntegrityError,
                            AdapterNotFoundError)
        from .engine import NonFiniteLogitsError
        from .kv_pool import KVPoolExhaustedError
        from .transfer import RunTransferError
        _WIRE_ERRORS = {
            "AdapterNotFoundError": AdapterNotFoundError,
            "AdapterExhaustedError": AdapterExhaustedError,
            "AdapterIntegrityError": AdapterIntegrityError,
            "RequestCancelled": RequestCancelled,
            "DeadlineExceededError": DeadlineExceededError,
            "QueueFullError": QueueFullError,
            "NonFiniteLogitsError": NonFiniteLogitsError,
            "KVPoolExhaustedError": KVPoolExhaustedError,
            "RunTransferError": RunTransferError,
            "InvalidArgumentError": InvalidArgumentError,
            "UnavailableError": UnavailableError,
            "ResourceExhaustedError": ResourceExhaustedError,
            "FatalError": FatalError,
            "WireFormatError": WireFormatError,
            "StaleEpochError": StaleEpochError,
            "WeightShipError": WeightShipError,
        }
    return _WIRE_ERRORS


def _mk_error(etype: str, msg: str) -> BaseException:
    cls = _error_types().get(etype)
    if cls is None:
        return UnavailableError(f"worker reported {etype}: {msg}")
    try:
        return cls(msg)
    except Exception:
        return UnavailableError(f"worker reported {etype}: {msg}")


class _ProxyRun:
    """Manager-side mirror of one run resident on (or in flight to) the
    worker — the `.req`/`.resp`/`.produced` duck shape
    `ReplicaManager._on_crash`, `_pump_migrations` and the gateway's
    preemption-victim scan consume from `engine._slots`."""
    __slots__ = ("req", "resp", "cancel_sent")

    def __init__(self, req: Request, resp: Response):
        self.req = req
        self.resp = resp
        self.cancel_sent = False

    @property
    def produced(self) -> int:
        # delivered tokens mirror the worker's committed count closely
        # enough for victim ranking (the only consumer)
        return len(self.resp.tokens_so_far())


class _ProxyScheduler:
    """The client's local admission queue + residency mirror, speaking
    the RequestScheduler surface the fleet consumes.  The queue is
    ENTIRELY local — a request ships to the worker only when a slot
    mirror says it can admit — so `drain_pending` is complete on crash
    and queue-depth backpressure needs no round trip."""

    def __init__(self, client: "WorkerClient"):
        self._c = client
        self._pending: "deque[Tuple[Request, Response]]" = deque()
        self._lock = threading.Lock()
        self._space = threading.Condition(self._lock)

    @property
    def max_queue_depth(self) -> int:
        return self._c.max_queue_depth

    def submit(self, req: Request, resp: Response, block: bool = False,
               timeout: Optional[float] = None):
        with self._space:
            if len(self._pending) >= self.max_queue_depth and block:
                self._space.wait_for(
                    lambda: len(self._pending) < self.max_queue_depth,
                    timeout=timeout)
            if len(self._pending) >= self.max_queue_depth:
                stat_add("STAT_serving_rejects")
                raise QueueFullError(
                    f"worker replica queue full ({self.max_queue_depth} "
                    "waiting); request rejected")
            self._pending.append((req, resp))

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def occupancy(self) -> int:
        return len(self._c._slots)

    def free_slot_count(self) -> int:
        return max(0, self._c.max_slots - len(self._c._slots))

    def has_work(self) -> bool:
        with self._lock:
            return bool(self._pending) or bool(self._c._slots)

    def release(self, wid):
        self._c._slots.pop(wid, None)

    def drain_pending(self):
        with self._space:
            drained = list(self._pending)
            self._pending.clear()
            self._space.notify_all()
            return drained

    def _pop_sendable(self) -> Optional[Tuple[Request, Response]]:
        """Next queued request that is still worth shipping, failing
        cancelled/expired entries in passing (scheduler.next_admission's
        sweep, client-side)."""
        with self._space:
            while self._pending:
                req, resp = self._pending.popleft()
                self._space.notify()
                if resp.cancelled:
                    stat_add("STAT_serving_cancelled")
                    resp._fail(RequestCancelled(
                        f"request {req.id} cancelled before prefill"))
                    continue
                if req.deadline is not None and req.deadline.expired():
                    stat_add("STAT_serving_deadline_expired")
                    resp._fail(DeadlineExceededError(
                        f"request {req.id} deadline "
                        f"({req.deadline.seconds}s) expired while queued"))
                    continue
                return req, resp
            return None


class WorkerClient:
    """Spawns one subprocess engine worker and implements the
    ServingEngine surface the fleet consumes over its RPC (module
    docstring).  All methods except `scheduler.submit` and `close` must
    run on the fleet's driving thread."""

    def __init__(self, spec: dict, index: int = 0,
                 boot_timeout_s: float = 180.0,
                 rpc_timeout_s: float = 15.0,
                 verb_deadlines: Optional[Dict[str, float]] = None):
        self._init_state(spec, index, boot_timeout_s, rpc_timeout_s,
                         verb_deadlines)
        self._dir = tempfile.mkdtemp(prefix=f"pdtpu_worker{index}_")
        self.heartbeat_path = os.path.join(self._dir, "heartbeat.json")
        self.log_path = os.path.join(self._dir, "worker.log")
        spec_path = os.path.join(self._dir, "spec.json")
        with open(spec_path, "w") as f:
            json.dump(self.spec, f)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(1)
        self._listener.setblocking(False)
        port = self._listener.getsockname()[1]
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)  # never grab the TPU tunnel
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = (root + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else root)
        self._log_f = open(self.log_path, "ab")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.serving.worker",
             "--spec", spec_path, "--port", str(port),
             "--heartbeat", self.heartbeat_path,
             "--index", str(self.index)],
            stdin=subprocess.DEVNULL, stdout=self._log_f,
            stderr=subprocess.STDOUT, env=env, start_new_session=True)

    def _init_state(self, spec: dict, index: int, boot_timeout_s: float,
                    rpc_timeout_s: float,
                    verb_deadlines: Optional[Dict[str, float]]):
        """Everything both the local (spawned) and remote (attached)
        client share: the engine-surface mirrors, the admission queue,
        the RPC bookkeeping."""
        self.spec = dict(spec)
        self.index = int(index)
        self.boot_timeout_s = float(boot_timeout_s)
        self.rpc_timeout_s = float(rpc_timeout_s)
        # per-verb deadlines on every blocking RPC: a cheap telemetry
        # verb must never consume the full migration budget
        self.verb_deadlines: Dict[str, float] = {
            "metrics": min(5.0, self.rpc_timeout_s),
            "fault": min(5.0, self.rpc_timeout_s)}
        self.verb_deadlines.update(verb_deadlines or {})
        self._conn: Optional[_FrameConn] = None
        self._boot_deadline = time.monotonic() + self.boot_timeout_s
        self._boot_error: Optional[str] = None
        # engine-surface mirrors (filled by the ready handshake)
        self._warm = False
        self.max_slots = 0
        self.max_len = 0
        self.buckets: Tuple[int, ...] = ()
        self.max_queue_depth = int(
            (self.spec.get("engine") or {}).get("max_queue_depth", 64))
        self.draft_model = None  # a sentinel object once the worker has one
        self.kv = "fixed"        # crash-path duck shape; remote kv in spec
        self._manifest: Optional[dict] = None
        self.warmup_report: Optional[dict] = None
        self._slots: Dict[int, _ProxyRun] = {}
        self.scheduler = _ProxyScheduler(self)
        self._status: dict = {}
        self._step_times: List[float] = []
        self._hb_cache = (0.0, None)  # (read_at, record)
        self._rid = 0
        self._wid = 0
        self._rid_lock = threading.Lock()
        self._thread = None          # ReplicaManager.add's loop check
        self._warm_marks = None      # refresh_warm_marks duck slot
        self._closed = False
        self._dead: Optional[BaseException] = None
        self._close_lock = threading.Lock()
        self.epoch = 0               # manager-issued session token
        self.weights_sha: Optional[str] = None
        self._worker_pid: Optional[int] = None

    # -- lifecycle ------------------------------------------------------
    @property
    def pid(self) -> int:
        return self.proc.pid

    @property
    def warm(self) -> bool:
        return self._warm

    def process_alive(self) -> bool:
        return self.proc.poll() is None

    def poll_ready(self) -> bool:
        """Advance the boot handshake without blocking; True once the
        worker reported ready (warm).  Raises WorkerDiedError on boot
        failure / exit / timeout."""
        if self._warm:
            return True
        if self._conn is None:
            try:
                s, _ = self._listener.accept()
                self._conn = _FrameConn(s)
                self._listener.close()
            except (BlockingIOError, OSError):
                pass
        if self._conn is not None:
            try:
                for frame in self._conn.recv_frames(0.0):
                    self._dispatch(frame)
            except WorkerDiedError:
                pass  # fall through to the death checks below
        if self._warm:
            return True
        if self._boot_error is not None:
            raise WorkerDiedError(
                f"worker {self.index} failed to boot: {self._boot_error} "
                f"(log: {self.log_path})")
        if self.proc.poll() is not None:
            raise WorkerDiedError(
                f"worker {self.index} exited rc={self.proc.returncode} "
                f"during boot (log: {self.log_path})")
        if time.monotonic() > self._boot_deadline:
            raise WorkerDiedError(
                f"worker {self.index} did not become ready within "
                f"{self.boot_timeout_s}s (log: {self.log_path})")
        return False

    def warmup(self) -> dict:
        """Block until the worker's boot warmup finished (it warms
        itself; this just waits out the handshake)."""
        while not self.poll_ready():
            time.sleep(0.01)
        return dict(self.warmup_report or {}, worker_pid=self.pid)

    # -- frame dispatch -------------------------------------------------
    def _dispatch(self, frame):
        verb, h, arrays = frame
        if verb == "chunk":
            run = self._slots.get(h.get("wid"))
            if run is not None:
                toks = arrays["toks"].tolist()
                logps = arrays.get("logps")
                logps = (logps.tolist() if logps is not None
                         else [0.0] * len(toks))
                for tok, lp in zip(toks, logps):
                    run.resp._push_token(int(tok), float(lp))
        elif verb == "done":
            run = self._slots.pop(h.get("wid"), None)
            if run is not None:
                run.resp._finish(h.get("reason") or "length")
        elif verb == "failed":
            run = self._slots.pop(h.get("wid"), None)
            if run is not None:
                run.resp._fail(_mk_error(h.get("etype", ""),
                                         h.get("msg", "")))
        elif verb == "status":
            self._status = h
            st = arrays.get("step_s")
            if st is not None and st.size:
                self._step_times.extend(float(x) for x in st)
        elif verb == "ready":
            cfg = h.get("config") or {}
            self.max_slots = int(cfg.get("max_slots", 0))
            self.max_len = int(cfg.get("max_len", 0))
            self.buckets = tuple(int(b) for b in cfg.get("buckets", ()))
            self.max_queue_depth = int(cfg.get("max_queue_depth",
                                               self.max_queue_depth))
            if cfg.get("has_draft"):
                self.draft_model = object()  # truthy `is not None` duck
            self._manifest = h.get("manifest")
            self.warmup_report = h.get("warmup")
            self._worker_pid = cfg.get("pid")
            self.weights_sha = h.get("weights_sha", self.weights_sha)
            # drop the heartbeat cache: the last cached record predates
            # warmup (the long no-beat boot window), and the wedge fence
            # must never judge a freshly-healthy worker by it
            self._hb_cache = (0.0, None)
            self._warm = True
        elif verb == "fatal":
            self._boot_error = f"{h.get('etype')}: {h.get('msg')}"
        elif verb == "dying":
            self._dead = _mk_error(h.get("etype", ""), h.get("msg", ""))
        elif verb in ("bye", "log", "metrics", "preempted", "restored",
                      "accepted", "attach_ok", "swap_ready", "swapped",
                      "adapter_ready", "adapter_loaded"):
            pass  # bye/log informational; RPC replies consumed by _rpc;
            #       accepted acks matter only to the remote subclass

    def _rpc(self, verb: str, header: dict, arrays: Optional[dict],
             reply_verb: str,
             timeout_s: Optional[float] = None) -> Tuple[dict, dict]:
        """Send one frame and pump until its reply arrives, dispatching
        unrelated frames (chunks/status) normally.  Every blocking RPC
        runs under its own per-verb deadline (`verb_deadlines`, default
        `rpc_timeout_s`); timeout or process death -> WorkerDiedError
        (the wedged-worker verdict)."""
        if self._conn is None:
            raise WorkerDiedError(f"worker {self.index} has no connection")
        budget = (timeout_s if timeout_s is not None
                  else self.verb_deadlines.get(verb, self.rpc_timeout_s))
        self._conn.send(verb, header, arrays)
        wid = header.get("wid")
        deadline = time.monotonic() + budget
        while True:
            if self.proc.poll() is not None:
                raise WorkerDiedError(
                    f"worker {self.index} exited rc={self.proc.returncode} "
                    f"mid-RPC ({verb})")
            for frame in self._conn.recv_frames(0.01):
                v, h, a = frame
                if v == reply_verb and h.get("wid") == wid:
                    return h, a
                self._dispatch(frame)
            if time.monotonic() > deadline:
                raise WorkerDiedError(
                    f"worker {self.index} RPC {verb!r} timed out after "
                    f"{budget}s — wedged, partitioned or overloaded "
                    "beyond the liveness budget")

    # -- engine surface: admission -------------------------------------
    def make_request(self, prompt, max_new_tokens: int,
                     decode_strategy: str = "greedy_search",
                     temperature=1.0, top_k=0, top_p=1.0,
                     eos_token_id: Optional[int] = None,
                     seed: Optional[int] = None,
                     deadline: Optional[float] = None, priority: int = 0,
                     tenant: Optional[str] = None,
                     spec: Optional[bool] = None,
                     session: Optional[str] = None,
                     resubmit: bool = False,
                     adapter: Optional[str] = None):
        """ServingEngine.make_request's validation against the worker's
        handshake config — no round trip; the worker re-validates on its
        side and any disagreement comes back as a typed `failed`.
        `adapter` names a LoRA adapter in the WORKER's registry; the
        name cannot be resolved from here, so an unknown adapter fails
        the response typed (AdapterNotFoundError) at worker admission
        rather than at this call — still terminal, never a hung
        consumer."""
        if self._closed:
            raise UnavailableError("worker replica is closed")
        if self._dead is not None:
            raise UnavailableError(
                f"worker {self.index} died: {self._dead!r}")
        if not self._warm:
            raise UnavailableError(
                f"worker {self.index} is still booting")
        if decode_strategy not in ("greedy_search", "sampling"):
            raise InvalidArgumentError(
                f"serving supports 'greedy_search' or 'sampling', got "
                f"{decode_strategy!r}")
        if spec is None:
            spec = self.draft_model is not None
        elif spec and self.draft_model is None:
            raise InvalidArgumentError(
                "spec=True requires the worker engine to be built with "
                "a draft model")
        if resubmit and decode_strategy != "greedy_search":
            raise InvalidArgumentError(
                "resubmit=True (re-prefill-from-prompt crash recovery) "
                "is greedy-only: a replayed sampled stream is not "
                "covered by any engine contract — drop resubmit or use "
                "greedy_search")
        with self._rid_lock:
            rid = self._rid
            self._rid += 1
        req = Request(rid, prompt, max_new_tokens,
                      greedy=decode_strategy == "greedy_search",
                      temperature=temperature, top_k=top_k, top_p=top_p,
                      eos_token_id=eos_token_id,
                      seed=seed if seed is not None else rid,
                      deadline=deadline, priority=priority, tenant=tenant,
                      spec=bool(spec), session=session, resubmit=resubmit,
                      adapter=adapter)
        plen = req.prompt.shape[0]
        if plen > self.buckets[-1]:
            stat_add("STAT_serving_rejects")
            raise InvalidArgumentError(
                f"prompt length {plen} exceeds the largest prefill "
                f"bucket {self.buckets[-1]} (worker max_len="
                f"{self.max_len})")
        if plen + req.max_new_tokens > self.max_len:
            stat_add("STAT_serving_rejects")
            raise InvalidArgumentError(
                f"prompt ({plen}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds the worker's max_len "
                f"{self.max_len}")
        stat_add("STAT_serving_requests")
        return req, Response(req)

    def try_admit(self, req: Request, resp: Response) -> bool:
        """Ship NOW if the residency mirror has room (the gateway's
        direct-admission path; driving thread only)."""
        if self._closed or not self._warm or self._conn is None:
            return False
        if self.scheduler.free_slot_count() <= 0:
            return False
        try:
            self._ship(req, resp)
        except WorkerDiedError as e:
            # admission must answer False, not blow up the gateway loop;
            # the next fleet tick's step() re-raises and fences us
            self._dead = self._dead or e
            return False
        return True

    def _submit_header(self, req: Request, wid: int) -> dict:
        return {"wid": wid, "max_new_tokens": req.max_new_tokens,
                "decode_strategy": ("greedy_search" if req.greedy
                                    else "sampling"),
                "temperature": req.temperature, "top_k": req.top_k,
                "top_p": req.top_p, "eos_token_id": req.eos_token_id,
                "seed": req.seed,
                "deadline_remaining_s": (None if req.deadline is None
                                         else req.deadline.remaining()),
                "priority": req.priority, "tenant": req.tenant,
                "spec": bool(req.spec) if self.draft_model is not None
                else False,
                "session": req.session, "resubmit": req.resubmit,
                "adapter": req.adapter}

    def _ship(self, req: Request, resp: Response):
        wid = self._wid
        self._wid += 1
        self._conn.send("submit", self._submit_header(req, wid),
                        {"prompt": req.prompt})
        self._slots[wid] = _ProxyRun(req, resp)

    # -- engine surface: the driving tick ------------------------------
    def step(self) -> bool:
        """One pump: propagate cancels, ship queued requests into free
        slots, drain inbound frames.  Raises WorkerDiedError when the
        process is gone — the fleet tick's crash path."""
        if self._closed or self._conn is None:
            return False
        did = False
        for wid, run in list(self._slots.items()):
            if run.resp.cancelled and not run.cancel_sent:
                self._conn.send("cancel", {"wid": wid})
                run.cancel_sent = True
                did = True
        while self.scheduler.free_slot_count() > 0:
            nxt = self.scheduler._pop_sendable()
            if nxt is None:
                break
            self._ship(*nxt)
            did = True
        try:
            frames = self._conn.recv_frames(0.0)
        except WorkerDiedError:
            if self.proc.poll() is not None:
                raise WorkerDiedError(
                    f"worker {self.index} exited "
                    f"rc={self.proc.returncode} (log: {self.log_path})")
            raise
        for frame in frames:
            self._dispatch(frame)
            did = True
        if self._dead is not None:
            raise WorkerDiedError(
                f"worker {self.index} step loop died: {self._dead!r}")
        if self.proc.poll() is not None:
            raise WorkerDiedError(
                f"worker {self.index} exited rc={self.proc.returncode} "
                f"(log: {self.log_path})")
        return did

    def has_work(self) -> bool:
        return self.scheduler.has_work()

    def take_step_times(self) -> List[float]:
        """Worker-reported per-step wall times since the last call —
        the fleet health EWMA's input (pump time on this side measures
        nothing)."""
        ts, self._step_times = self._step_times, []
        return ts

    def _heartbeat(self) -> Optional[dict]:
        """Last heartbeat record, re-read at most every 50ms — the tick
        polls this per replica, and age resolution far below the fence
        threshold buys nothing for a file read per tick."""
        now = time.monotonic()
        read_at, rec = self._hb_cache
        if now - read_at > 0.05:
            rec = read_heartbeat(self.heartbeat_path)
            self._hb_cache = (now, rec)
        return rec

    def heartbeat_age(self, fresh: bool = False) -> Optional[float]:
        """Seconds since the worker's last out-of-band heartbeat write,
        or None before the first beat.  Computed on the shared
        CLOCK_MONOTONIC timeline (wall clock only as a legacy fallback)
        so an NTP step cannot falsely wedge the fleet.  `fresh=True`
        bypasses the 50ms cache — the fence decision re-reads the file
        so a cached pre-warmup record can never wedge-fence a healthy
        worker."""
        if fresh:
            self._hb_cache = (0.0, None)
        d = self._heartbeat()
        if d is None:
            return None
        try:
            if "mono" in d:
                return max(0.0, time.monotonic() - float(d["mono"]))
            return max(0.0, time.time() - float(d["time"]))
        except (TypeError, ValueError, KeyError):
            return None

    def heartbeat_steps(self) -> Optional[int]:
        d = self._heartbeat()
        try:
            return None if d is None else int(d["steps"])
        except (TypeError, ValueError, KeyError):
            return None

    # -- engine surface: migration -------------------------------------
    def transfer_manifest(self) -> dict:
        """The restore-compatibility descriptor the worker computed at
        boot — `transfer.check_compatible`'s target view of this
        replica."""
        if self._manifest is None:
            raise UnavailableError(
                f"worker {self.index} has not completed its handshake")
        return self._manifest

    def preempt_slot(self, wid) -> "object":
        """Preempt the run tracked under `wid` on the worker and decode
        its snapshot against the ORIGINAL req/resp (the consumer's
        stream object survives the move, exactly like the in-process
        path).  WorkerDiedError on RPC failure; InvalidArgumentError if
        the run finished in the race window."""
        from .transfer import decode_run, run_from_bytes
        run = self._slots.get(wid)
        if run is None:
            raise InvalidArgumentError(f"wid {wid} holds no active run")
        h, a = self._rpc("preempt", {"wid": wid}, None, "preempted")
        if not h.get("ok"):
            raise InvalidArgumentError(
                f"wid {wid} is not resident on worker {self.index} "
                f"({h.get('reason')})")
        blob = run_from_bytes(a["run"].tobytes())
        paused = decode_run(blob, req=run.req, resp=run.resp)
        self._slots.pop(wid, None)
        run.req.preempts += 1
        stat_add("STAT_serving_preemptions")
        return paused

    def restore_run(self, paused) -> bool:
        """Restore a (possibly cross-replica) snapshot onto the worker.
        False on capacity; typed RunTransferError if the worker rejects
        the snapshot as incompatible (its engine re-checks)."""
        from .transfer import RunTransferError, encode_run, run_to_bytes
        if self._closed or not self._warm or self._conn is None:
            return False
        if self.scheduler.free_slot_count() <= 0:
            return False
        blob = run_to_bytes(encode_run(paused))
        wid = self._wid
        self._wid += 1
        h, _ = self._rpc("restore", {"wid": wid},
                         {"run": np.frombuffer(blob, np.uint8).copy()},
                         "restored")
        if h.get("ok"):
            self._slots[wid] = _ProxyRun(paused.req, paused.resp)
            paused.req.resumes += 1
            paused.req.paused_seconds += (time.monotonic()
                                          - paused.preempted_at)
            stat_add("STAT_serving_resumes")
            return True
        if h.get("etype") == "RunTransferError":
            raise RunTransferError(
                f"worker {self.index} rejected the run snapshot: "
                f"{h.get('msg')}")
        return False

    # -- engine surface: telemetry -------------------------------------
    def adapter_shas(self):
        """name -> artifact sha reported in the worker's latest status
        frame (cheap cached read for fleet health snapshots)."""
        lora = (self._status.get("metrics") or {}).get("lora") or {}
        return lora.get("shas") or None

    def metrics(self) -> dict:
        m = dict(self._status.get("metrics") or {})
        m["queue_depth"] = self.scheduler.queue_depth()
        m["slot_occupancy"] = len(self._slots)
        m["worker"] = {"pid": self.pid, "index": self.index,
                       "alive": self.process_alive(),
                       "steps": self._status.get("steps"),
                       "heartbeat_age_s": self.heartbeat_age(),
                       "log": self.log_path}
        return m

    def post_warmup_compiles(self) -> int:
        if not self._warm:
            return -1
        v = self._status.get("post_warmup_compiles")
        return 0 if v is None else int(v)

    def _compile_marks(self) -> dict:
        # the worker's program registry lives in ITS process: peers'
        # warmups can never pollute it, so there is nothing to re-mark
        return {"engine": 0, "registry": {}}

    def set_fault(self, point: str, value: Optional[str]):
        """Arm/disarm a utils.faults knob INSIDE the worker process
        (env vars set after spawn don't cross the boundary)."""
        if self._conn is None:
            raise WorkerDiedError(
                f"worker {self.index} has no connection")
        self._conn.send("fault", {"point": point, "value": value})

    # -- engine surface: continuous weight refresh ---------------------
    def swap_weights(self, path: str, sha: Optional[str] = None,
                     timeout_s: float = 60.0) -> str:
        """Flip the worker's served weights to the npz artifact at
        `path` (same host — the spawned worker shares our filesystem)
        with zero recompiles.  The worker verifies `sha` against the
        file before a byte reaches its engine; any rejection comes back
        as the typed error (WeightShipError for corrupt artifacts,
        InvalidArgumentError for shape mismatches) and the worker keeps
        serving its OLD weights.  Returns the served sha.  Driving
        thread only; the fleet calls this at the replica's idle
        boundary."""
        if self._conn is None:
            raise WorkerDiedError(
                f"worker {self.index} has no connection")
        wid = self._wid
        self._wid += 1
        h, _ = self._rpc("swap_weights",
                         {"wid": wid, "path": path, "sha256": sha},
                         None, "swapped", timeout_s=timeout_s)
        if not h.get("ok"):
            raise _mk_error(h.get("etype", ""), h.get("msg", ""))
        self.weights_sha = h.get("weights_sha", sha)
        return self.weights_sha

    # -- engine surface: multi-tenant LoRA hot-load --------------------
    def load_adapter(self, name: str, path: str,
                     sha: Optional[str] = None,
                     timeout_s: float = 60.0, retries: int = 1) -> str:
        """Page the adapter artifact at `path` (same host — the spawned
        worker shares our filesystem) into the worker's registry under
        `name`, with zero recompiles.  The worker verifies the artifact
        before a factor reaches its device stacks; a corrupt read comes
        back typed (AdapterIntegrityError) and is re-shipped once
        (`retries`) — the supervised re-ship path, the registry never
        holds garbage factors.  A persistent or non-retryable failure
        (unknown base hash, rank mismatch, all slots pinned) propagates
        typed.  Returns the resident artifact's sha256.  Driving thread
        only."""
        if self._conn is None:
            raise WorkerDiedError(
                f"worker {self.index} has no connection")
        attempts = max(1, int(retries) + 1)
        for i in range(attempts):
            wid = self._wid
            self._wid += 1
            h, _ = self._rpc("load_adapter",
                             {"wid": wid, "name": name, "path": path,
                              "sha256": sha},
                             None, "adapter_loaded", timeout_s=timeout_s)
            if h.get("ok"):
                return h.get("file_sha")
            err = _mk_error(h.get("etype", ""), h.get("msg", ""))
            retryable = h.get("etype") in ("AdapterIntegrityError",
                                           "WeightShipError")
            if not retryable or i == attempts - 1:
                raise err
            stat_add("STAT_lora_ship_reships")
        raise err  # unreachable; loop always returns or raises

    # -- engine surface: teardown --------------------------------------
    def _abort_all(self, make_exc):
        for wid, run in list(self._slots.items()):
            run.resp._fail(make_exc(run.req))
        self._slots.clear()
        for req, resp in self.scheduler.drain_pending():
            resp._fail(make_exc(req))

    def kill(self):
        """SIGKILL + reap.  Idempotent: a second kill of an
        already-dead (or already-reaped) pid is a no-op."""
        try:
            self.proc.kill()  # no-op once returncode is set
        except (ProcessLookupError, OSError):
            pass
        try:
            self.proc.wait(timeout=5)
        except Exception:
            pass

    def close(self, graceful: bool = True):
        """Stop the worker and reap the process (no orphans, no
        zombies), failing anything still outstanding.  `graceful=True`
        asks the worker to exit first and gives it 2s; the fleet passes
        `graceful=False` for crashed/wedged corpses — a wedged process
        would never read the close verb and the 2s wait would stall the
        driving thread (and every healthy replica) for nothing.
        Idempotent and safe under concurrent double-close (the
        engine/gateway/fleet contract)."""
        self._closed = True
        with self._close_lock:
            if graceful:
                if self._conn is not None:
                    try:
                        self._conn.send("close", {})
                    except (WorkerDiedError, WireFormatError):
                        pass
                try:
                    self.proc.wait(timeout=2.0)
                except Exception:
                    pass
            self.kill()
            if self._conn is not None:
                self._conn.close()
            try:
                self._listener.close()
            except OSError:
                pass
            try:
                self._log_f.close()
            except OSError:
                pass
            self._abort_all(lambda req: RequestCancelled(
                f"request {req.id} aborted: worker replica closed"))
            shutil.rmtree(self._dir, ignore_errors=True)


class _NullProc:
    """Remote workers have no local child process: the base client's
    poll/kill/wait liveness checks become no-ops against this stub —
    death is decided on the wire (beat age + connection loss), never by
    a pid this host does not own."""
    pid = -1
    returncode: Optional[int] = None

    def poll(self):
        return None

    def kill(self):
        pass

    def wait(self, timeout=None):
        return None


class RemoteWorkerClient(WorkerClient):
    """Manager-side handle for a STANDALONE remote worker started with
    ``--listen HOST:PORT`` — the network-transparent half of the fleet.
    Differences from the spawned-local base:

    - **Attach, not fork**: connects over real TCP, sends an `attach`
      carrying the manager-issued `epoch` token, the boot spec, and
      manifests for the weight artifact (``spec["weights"]``, a jit.save
      npz) and optionally the program set (``spec["ship_program_set"]``)
      — then streams them as sha256-verified chunks.  The worker replies
      `attach_ok` with what it actually needs, so a re-attach onto a
      warm cached engine ships zero bytes and rebuilds nothing.
    - **Liveness on the wire**: a dedicated beat side connection carries
      the worker's step counter; `heartbeat_age` is the ARRIVAL age of
      the last beat on THIS host's monotonic clock (the worker's stamps
      belong to another machine's timeline), so the manager's wedge
      fence works unchanged with no heartbeat file at all.
    - **Partition-safe submits**: every submit is acked (`accepted`) and
      retried on ack timeout; the worker dedups on wid, so a retried
      submit after a lost ack can never double-admit.  Frames from a
      stale epoch are answered with `abort_epoch` — a healed worker is
      told to abort, never to resume.
    """

    def __init__(self, spec: dict, address: str, index: int = 0,
                 epoch: int = 1, boot_timeout_s: float = 180.0,
                 rpc_timeout_s: float = 15.0,
                 connect_timeout_s: float = 10.0,
                 manager_silence_s: float = 6.0,
                 ack_timeout_s: float = 2.0, submit_retries: int = 2,
                 verb_deadlines: Optional[Dict[str, float]] = None):
        from .transfer import artifact_manifest
        self._init_state(spec, index, boot_timeout_s, rpc_timeout_s,
                         verb_deadlines)
        host, _, port = str(address).rpartition(":")
        if not port:
            raise InvalidArgumentError(
                f"remote worker address {address!r} must be HOST:PORT")
        self.address = (host or "127.0.0.1", int(port))
        self.epoch = int(epoch)
        self.manager_silence_s = float(manager_silence_s)
        self.ack_timeout_s = float(ack_timeout_s)
        self.submit_retries = int(submit_retries)
        self.proc = _NullProc()
        self.heartbeat_path = None  # liveness is beat FRAMES, not a file
        self.log_path = f"<remote {self.address[0]}:{self.address[1]}>"
        self.bytes_shipped = 0
        self._beat_conn: Optional[_FrameConn] = None
        self._last_beat: Optional[dict] = None
        self._last_beat_rx: Optional[float] = None  # ARRIVAL mono stamp
        self._await_ack: Dict[int, list] = {}
        self._last_tx = time.monotonic()
        # shipped artifacts come OUT of the wire spec: their paths are
        # THIS host's, meaningless on the worker's filesystem
        wire_spec = dict(self.spec)
        self._weights_path = wire_spec.pop("weights", None)
        self._programs_path = None
        if wire_spec.pop("ship_program_set", False):
            self._programs_path = wire_spec.pop("program_set", None)
        self._wire_spec = wire_spec
        self._weights_man = (None if self._weights_path is None
                             else artifact_manifest(self._weights_path))
        self._programs_man = (None if self._programs_path is None
                              else artifact_manifest(self._programs_path))
        self._hs_state = "connect"
        self._connect(float(connect_timeout_s))

    # -- attach handshake ----------------------------------------------
    def _connect(self, connect_timeout_s: float):
        deadline = time.monotonic() + connect_timeout_s
        while True:
            try:
                sock = socket.create_connection(self.address, timeout=2.0)
                break
            except OSError as e:
                if time.monotonic() > deadline:
                    raise WorkerDiedError(
                        f"could not reach remote worker at "
                        f"{self.address[0]}:{self.address[1]}: {e!r}")
                time.sleep(0.1)
        self._conn = _FrameConn(sock, fault_index=self.index)
        self._conn.send("attach", {
            "epoch": self.epoch, "index": self.index,
            "silence_s": self.manager_silence_s,
            "spec": self._wire_spec,
            "weights": self._weights_man,
            "programs": self._programs_man})
        self._hs_state = "attach_sent"
        self._last_tx = time.monotonic()

    def _ship_artifacts(self, need_weights: bool, need_programs: bool):
        import hashlib
        from .transfer import iter_artifact_chunks
        for need, path, verb in (
                (need_weights, self._weights_path, "weights_chunk"),
                (need_programs, self._programs_path, "program_chunk")):
            if not need:
                continue
            if path is None:
                raise WorkerDiedError(
                    f"worker requested {verb} but the spec ships none")
            for seq, data in iter_artifact_chunks(path):
                self._conn.send(
                    verb,
                    {"seq": seq,
                     "sha256": hashlib.sha256(data).hexdigest()},
                    {"data": np.frombuffer(data, np.uint8).copy()})
                self.bytes_shipped += len(data)
        self._conn.send("attach_end", {})
        self._last_tx = time.monotonic()
        if self.bytes_shipped:
            stat_add("STAT_fleet_weight_bytes_shipped",
                     self.bytes_shipped)

    def _open_beat_conn(self):
        try:
            s = socket.create_connection(self.address, timeout=5.0)
        except OSError as e:
            raise WorkerDiedError(
                f"beat side-connection to {self.address[0]}:"
                f"{self.address[1]} failed: {e!r}")
        self._beat_conn = _FrameConn(s, fault_index=self.index)
        self._beat_conn.send("beat_attach", {"epoch": self.epoch,
                                             "index": self.index})

    def poll_ready(self) -> bool:
        if self._warm:
            return True
        try:
            for frame in self._conn.recv_frames(0.0):
                v, h, a = frame
                if v == "attach_ok" and self._hs_state == "attach_sent":
                    self._ship_artifacts(bool(h.get("need_weights")),
                                         bool(h.get("need_programs")))
                    self._open_beat_conn()
                    self._hs_state = "await_ready"
                else:
                    self._dispatch(frame)
        except WorkerDiedError as e:
            if self._boot_error is None:
                self._boot_error = f"connection lost mid-attach: {e}"
        if self._warm:
            return True
        if self._boot_error is not None:
            raise WorkerDiedError(
                f"remote worker {self.index} at {self.log_path} failed "
                f"to attach: {self._boot_error}")
        if time.monotonic() > self._boot_deadline:
            raise WorkerDiedError(
                f"remote worker {self.index} at {self.log_path} not "
                f"ready within {self.boot_timeout_s}s")
        return False

    # -- epoch-fenced dispatch -----------------------------------------
    def _dispatch(self, frame):
        verb, h, a = frame
        ep = h.get("epoch")
        if ep is not None and int(ep) != self.epoch and verb != "fatal":
            # a frame from another session epoch of this worker: tell it
            # to abort — its runs were already resubmitted elsewhere and
            # a resumed stale stream would double-serve tokens
            try:
                self._conn.send("abort_epoch", {"epoch": int(ep)})
            except (WorkerDiedError, WireFormatError):
                pass
            return
        if verb == "accepted":
            self._await_ack.pop(h.get("wid"), None)
            return
        super()._dispatch(frame)

    # -- partition-safe submits ----------------------------------------
    def _ship(self, req: Request, resp: Response):
        wid = self._wid
        self._wid += 1
        h = self._submit_header(req, wid)
        prompt = np.asarray(req.prompt, np.int32)
        # the worker dedups on wid, so a retried submit is idempotent: a
        # lost ack can cost a resend, never a double admission
        self._await_ack[wid] = [time.monotonic() + self.ack_timeout_s,
                                self.submit_retries, h, prompt]
        # register the run BEFORE the send: a submit cut mid-frame (net
        # drop) raises out of send() after the request already left the
        # scheduler — it must sit in _slots so the fleet's failover
        # sweep can resubmit it instead of orphaning the consumer
        self._slots[wid] = _ProxyRun(req, resp)
        self._conn.send("submit", h, {"prompt": prompt})
        self._last_tx = time.monotonic()

    def _pump_acks(self):
        now = time.monotonic()
        for wid in list(self._await_ack):
            if wid not in self._slots:
                # done/failed landed first: the stream already answered
                self._await_ack.pop(wid, None)
                continue
            entry = self._await_ack[wid]
            if now < entry[0]:
                continue
            if entry[1] <= 0:
                self._await_ack.pop(wid, None)
                run = self._slots.pop(wid, None)
                if run is not None:
                    run.resp._fail(WorkerDiedError(
                        f"request {run.req.id}: remote worker "
                        f"{self.index} never acknowledged submit "
                        f"wid={wid} ({self.submit_retries} retries)"))
                continue
            entry[0] = now + self.ack_timeout_s
            entry[1] -= 1
            self._conn.send("submit", entry[2], {"prompt": entry[3]})
            self._last_tx = now

    def _maybe_ping(self):
        """Keep the worker's manager-silence clock fed while idle — a
        quiet-but-connected manager must not look like a partition."""
        now = time.monotonic()
        if now - self._last_tx < self.manager_silence_s / 3.0:
            return
        self._conn.send("ping", {})
        self._last_tx = now

    def step(self) -> bool:
        if self._closed or self._conn is None:
            return False
        did = super().step()
        self._pump_acks()
        self._drain_beats()
        self._maybe_ping()
        return did

    # -- liveness on the wire ------------------------------------------
    def _drain_beats(self):
        if self._beat_conn is None:
            return
        try:
            frames = self._beat_conn.recv_frames(0.0)
        except (WorkerDiedError, WireFormatError):
            return  # a dead beat channel reads as staleness — the safe
            #         direction for a fence
        for v, h, _ in frames:
            if v != "beat":
                continue
            ep = h.get("epoch")
            if ep is not None and int(ep) != self.epoch:
                continue  # a stale session's beat proves nothing
            self._last_beat = h
            self._last_beat_rx = time.monotonic()

    def heartbeat_age(self, fresh: bool = False) -> Optional[float]:
        """Age of the last beat FRAME, clocked on ARRIVAL (this host's
        monotonic clock — the worker's stamps belong to another
        machine's timeline).  None before the first beat, exactly like
        the file path during boot.  The file path's 50ms cache has no
        analogue: draining a socket is cheap."""
        self._drain_beats()
        if self._last_beat_rx is None:
            return None
        return max(0.0, time.monotonic() - self._last_beat_rx)

    def heartbeat_steps(self) -> Optional[int]:
        self._drain_beats()
        try:
            return (None if self._last_beat is None
                    else int(self._last_beat["steps"]))
        except (TypeError, ValueError, KeyError):
            return None

    def process_alive(self) -> bool:
        # no pid to poll across a network: the session being open and
        # un-dead IS aliveness; staleness is heartbeat_age's verdict
        return not self._closed and self._dead is None

    # -- continuous weight refresh over the wire -----------------------
    def swap_weights(self, path: str, sha: Optional[str] = None,
                     timeout_s: float = 120.0) -> str:
        """Ship the artifact at `path` to the remote worker and flip it
        in, zero recompiles.  Two phases: the manifest goes first and
        the chunk stream starts only after the worker's `swap_ready`
        ack, so no chunk can land inside an unrelated frame batch.
        Every chunk and the assembled file are sha256-verified on the
        worker; a corrupt artifact is refused there (typed
        WeightShipError here) with the old weights still serving."""
        import hashlib
        from .transfer import artifact_manifest, iter_artifact_chunks
        if self._conn is None:
            raise WorkerDiedError(
                f"worker {self.index} has no connection")
        man = artifact_manifest(path)
        if sha is not None and man.get("sha256") != sha:
            raise WeightShipError(
                f"weight artifact {path!r} sha256 {man.get('sha256')} "
                f"!= published {sha} — refusing to ship a corrupt "
                "artifact")
        sha = man.get("sha256")
        wid = self._wid
        self._wid += 1
        self._rpc("swap_weights",
                  {"wid": wid, "sha256": sha, "manifest": man},
                  None, "swap_ready", timeout_s=timeout_s)
        for seq, data in iter_artifact_chunks(path):
            self._conn.send(
                "weights_chunk",
                {"seq": seq, "sha256": hashlib.sha256(data).hexdigest()},
                {"data": np.frombuffer(data, np.uint8).copy()})
            self.bytes_shipped += len(data)
        self._conn.send("attach_end", {})
        self._last_tx = time.monotonic()
        # wait for the verdict, pumping unrelated frames normally
        deadline = time.monotonic() + timeout_s
        while True:
            for frame in self._conn.recv_frames(0.01):
                v, h, a = frame
                if v == "swapped" and h.get("wid") == wid:
                    if not h.get("ok"):
                        raise _mk_error(h.get("etype", ""),
                                        h.get("msg", ""))
                    self.weights_sha = h.get("weights_sha", sha)
                    return self.weights_sha
                self._dispatch(frame)
            if time.monotonic() > deadline:
                raise WorkerDiedError(
                    f"remote worker {self.index} swap_weights timed out "
                    f"after {timeout_s}s")

    # -- multi-tenant LoRA: adapter hot-load over the wire --------------
    def load_adapter(self, name: str, path: str,
                     sha: Optional[str] = None,
                     timeout_s: float = 120.0, retries: int = 1) -> str:
        """Ship the adapter artifact at `path` to the remote worker and
        page it into the registry, zero recompiles.  Manifest-first:
        the chunk stream starts only after the worker's `adapter_ready`
        ack; if the worker already holds the identically-hashed
        artifact under `name` it answers `cached: True` and ZERO bytes
        ship (the re-attach path).  Every chunk and the assembled file
        are sha256-verified on the worker; a corrupt chunk or a
        poisoned read is refused there typed and re-shipped once
        (`retries`) — garbage factors never reach the registry."""
        import hashlib
        from .transfer import artifact_manifest, iter_artifact_chunks
        if self._conn is None:
            raise WorkerDiedError(
                f"worker {self.index} has no connection")
        man = artifact_manifest(path)
        if sha is not None and man.get("sha256") != sha:
            raise WeightShipError(
                f"adapter artifact {path!r} sha256 {man.get('sha256')} "
                f"!= published {sha} — refusing to ship a corrupt "
                "artifact")
        sha = man.get("sha256")
        attempts = max(1, int(retries) + 1)
        for i in range(attempts):
            wid = self._wid
            self._wid += 1
            rh, _ = self._rpc("load_adapter",
                              {"wid": wid, "name": name, "sha256": sha,
                               "manifest": man},
                              None, "adapter_ready", timeout_s=timeout_s)
            if not rh.get("cached"):
                for seq, data in iter_artifact_chunks(path):
                    self._conn.send(
                        "adapter_chunk",
                        {"seq": seq,
                         "sha256": hashlib.sha256(data).hexdigest()},
                        {"data": np.frombuffer(data, np.uint8).copy()})
                    self.bytes_shipped += len(data)
                    stat_add("STAT_lora_ship_bytes", len(data))
                self._conn.send("attach_end", {})
                self._last_tx = time.monotonic()
            # wait for the verdict, pumping unrelated frames normally
            err = None
            deadline = time.monotonic() + timeout_s
            while err is None:
                for frame in self._conn.recv_frames(0.01):
                    v, h, a = frame
                    if v == "adapter_loaded" and h.get("wid") == wid:
                        if h.get("ok"):
                            return h.get("file_sha", sha)
                        err = _mk_error(h.get("etype", ""),
                                        h.get("msg", ""))
                        break
                    self._dispatch(frame)
                if err is None and time.monotonic() > deadline:
                    raise WorkerDiedError(
                        f"remote worker {self.index} load_adapter "
                        f"timed out after {timeout_s}s")
            retryable = isinstance(err, (WeightShipError,)) or (
                type(err).__name__ == "AdapterIntegrityError")
            if not retryable or i == attempts - 1:
                raise err
            stat_add("STAT_lora_ship_reships")
        raise err  # unreachable; loop always returns or raises

    @property
    def pid(self) -> int:
        return -1 if self._worker_pid is None else int(self._worker_pid)

    # -- teardown -------------------------------------------------------
    def kill(self):
        """No SIGKILL crosses a network: drop both connections.  The
        worker sees manager-loss (or manager silence) and self-aborts
        its residents typed — the fence holds without owning the
        process."""
        for c in (self._conn, self._beat_conn):
            if c is not None:
                c.close()

    def close(self, graceful: bool = True):
        """Detach from the worker (the manager does not own a standalone
        process: `close` ends the SESSION — the worker aborts residents
        and goes back to listening).  Idempotent."""
        self._closed = True
        with self._close_lock:
            if graceful and self._conn is not None:
                try:
                    self._conn.send("close", {})
                except (WorkerDiedError, WireFormatError):
                    pass
            self.kill()
            self._abort_all(lambda req: RequestCancelled(
                f"request {req.id} aborted: remote worker replica "
                "detached"))


if __name__ == "__main__":
    sys.exit(main())
