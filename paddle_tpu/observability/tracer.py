"""Host-side span tracer: nestable, thread-aware, ring-buffered.

Reference: platform/profiler.h RecordEvent + the CUPTI DeviceTracer's
GenProfile chrome-trace path (platform/device_tracer.cc).  Dapper-style
span model: every span carries a thread id and an explicit parent (the
innermost open span on its thread unless overridden), so the chrome
export nests correctly even when the serving engine, the checkpoint
writer and the training loop all record concurrently.

Replaces `utils/profiler.py`'s module-global `_records`/`_events` (which
were mutated without a lock from serving-engine threads); that module is
now a lock-correct compat shim over this tracer.

The device half stays jax.profiler: `span(..., annotate=True)` opens a
`jax.profiler.TraceAnnotation` alongside the host span so host spans line
up with the XLA device timeline in TensorBoard/perfetto.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["Span", "Tracer", "get_tracer", "span"]

DEFAULT_MAX_EVENTS = 200_000  # bound host memory (same cap profiler.py had)


class Span:
    """One open (then closed) host span.  Use as a context manager or call
    `end()` explicitly (the RecordEvent idiom)."""

    __slots__ = ("name", "tracer", "span_id", "parent_id", "tid", "t0",
                 "dur", "args", "_annotation", "_ended")

    def __init__(self, tracer: "Tracer", name: str,
                 parent: Optional["Span"] = None, annotate: bool = False,
                 args: Optional[dict] = None):
        self.tracer = tracer
        self.name = name
        self.span_id = next(tracer._ids)
        self.tid = threading.get_ident()
        self.args = args
        self.dur = None
        self._ended = False
        stack = tracer._stack()
        explicit = parent is not None
        if not explicit and stack:
            parent = stack[-1]
        self.parent_id = parent.span_id if parent is not None else None
        stack.append(self)
        self._annotation = None
        if annotate:
            try:  # jax optional here: the tracer itself is pure host
                import jax
                self._annotation = jax.profiler.TraceAnnotation(name)
                self._annotation.__enter__()
            except Exception:
                self._annotation = None
        self.t0 = time.perf_counter()

    def end(self):
        if self._ended:
            return
        self._ended = True
        now = time.perf_counter()
        self.dur = now - self.t0
        if self._annotation is not None:
            try:
                self._annotation.__exit__(None, None, None)
            except Exception:
                pass
        stack = self.tracer._stack()
        if self in stack:  # pop through abandoned children
            while stack and stack[-1] is not self:
                stack.pop()
            stack.pop()
        self.tracer._record(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.end()
        return False


class _LightSpan:
    """Hot-path span: name + wall time only — no span id, no TLS parenting
    stack, no TraceAnnotation.  Used by the per-op profiler hook, where a
    full Span's bookkeeping would cost ~2x more per dispatch; still
    recorded through the same lock into the same ring/aggregates (thread
    ids included), with span_id/parent_id = None."""

    __slots__ = ("tracer", "name", "t0")

    def __init__(self, tracer: "Tracer", name: str):
        self.tracer = tracer
        self.name = name

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t0 = self.t0
        dur = time.perf_counter() - t0
        tracer = self.tracer
        with tracer._lock:
            rec = tracer._agg.get(self.name)
            if rec is None:
                rec = tracer._agg[self.name] = [0, 0.0]
            rec[0] += 1
            rec[1] += dur
            tracer._ring.append((self.name, t0, dur, threading.get_ident(),
                                 None, None, None))
        return False


class Tracer:
    """Bounded span recorder + per-name aggregates, all under one lock."""

    def __init__(self, max_events: int = DEFAULT_MAX_EVENTS):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=int(max_events))
        self._agg: Dict[str, list] = {}  # name -> [count, total_s]
        self._ids = itertools.count(1)
        self._tls = threading.local()

    def _stack(self) -> list:
        s = getattr(self._tls, "stack", None)
        if s is None:
            s = self._tls.stack = []
        return s

    def _record(self, sp: Span):
        with self._lock:
            rec = self._agg.get(sp.name)
            if rec is None:
                rec = self._agg[sp.name] = [0, 0.0]
            rec[0] += 1
            rec[1] += sp.dur
            self._ring.append((sp.name, sp.t0, sp.dur, sp.tid, sp.span_id,
                               sp.parent_id, sp.args))

    # -- recording -----------------------------------------------------------
    def span(self, name: str, parent: Optional[Span] = None,
             annotate: bool = False, args: Optional[dict] = None) -> Span:
        return Span(self, name, parent=parent, annotate=annotate, args=args)

    def light_span(self, name: str) -> _LightSpan:
        """Minimal-overhead span for per-op hot paths (see _LightSpan)."""
        return _LightSpan(self, name)

    def current_span(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    # -- reading -------------------------------------------------------------
    def aggregates(self) -> Dict[str, list]:
        """{name: [count, total_seconds]} — the profiler.summary shape."""
        with self._lock:
            return {k: list(v) for k, v in self._agg.items()}

    def events(self) -> List[tuple]:
        """Snapshot of the ring: (name, t0, dur, tid, id, parent_id, args)."""
        with self._lock:
            return list(self._ring)

    def __len__(self):
        with self._lock:
            return len(self._ring)

    @property
    def max_events(self) -> int:
        return self._ring.maxlen

    def set_max_events(self, n: int):
        with self._lock:
            self._ring = deque(self._ring, maxlen=int(n))

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._agg.clear()

    # -- export --------------------------------------------------------------
    def chrome_trace(self) -> dict:
        """catapult JSON document (the DeviceTracer GenProfile analogue —
        host side; the XLA device timeline comes from jax.profiler)."""
        events = []
        for name, t0, dur, tid, sid, parent, args in self.events():
            ev = {"name": name, "ph": "X", "cat": "host",
                  "ts": t0 * 1e6, "dur": dur * 1e6,
                  "pid": os.getpid(), "tid": tid,
                  "args": dict(args or {}, span_id=sid,
                               parent_id=parent)}
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_chrome_trace(self, path: str) -> str:
        doc = self.chrome_trace()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f)
        return path


_default_tracer = Tracer()


def get_tracer() -> Tracer:
    return _default_tracer


def span(name: str, parent: Optional[Span] = None, annotate: bool = False,
         args: Optional[dict] = None) -> Span:
    """Open a span on the default tracer (context manager)."""
    return _default_tracer.span(name, parent=parent, annotate=annotate,
                                args=args)
