"""Typed metrics registry: Counter / Gauge / Histogram with label sets.

Reference: paddle/fluid/platform/monitor.h (StatRegistry + the STAT_ADD /
STAT_SUB / STAT_RESET macros) — a flat int64 registry every subsystem
bumps.  This is its production-shaped successor: typed metrics with label
sets, per-metric locks (the serving engine increments from its loop thread
while callers scrape from theirs), histogram quantiles, and collector
callbacks so hot-path counters that live elsewhere (core.op's dispatch
cache dict) surface in snapshots without paying registry locks per
dispatch.  `utils.monitor` is now a compat shim over this registry.

Deliberately stdlib-only and import-light: the registry is constructed at
`import paddle_tpu` time and must not pull the op/layer stack.
"""
from __future__ import annotations

import bisect
import threading
from typing import Callable, Dict, Iterable, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "get_registry", "counter", "gauge", "histogram",
           "DEFAULT_BUCKETS"]

# Prometheus-style latency buckets (seconds), wide enough for both a ~µs
# dispatch span and a multi-second checkpoint publish.
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _label_key(labelnames: Tuple[str, ...], labels: dict) -> Tuple[str, ...]:
    if set(labels) != set(labelnames):
        raise ValueError(
            f"metric expects labels {labelnames}, got {tuple(labels)}")
    return tuple(str(labels[n]) for n in labelnames)


class _Metric:
    """Shared label-child plumbing.  Each metric owns its lock (the
    registry-level sharding: two threads bumping different metrics never
    contend)."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Tuple[str, ...] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        # RLock: samples() reads children under the lock and Histogram._read
        # re-enters it for a consistent per-child snapshot
        self._lock = threading.RLock()
        self._children: Dict[Tuple[str, ...], object] = {}

    def labels(self, **labels):
        key = _label_key(self.labelnames, labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._new_child()
                self._children[key] = child
        return _BoundMetric(self, key, child)

    def _child(self, key: Tuple[str, ...] = ()):
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._new_child()
                self._children[key] = child
            return child

    def clear(self):
        """Drop every child (value reset)."""
        with self._lock:
            self._children.clear()

    def samples(self):
        """[(label_values_tuple, value_or_histdict)] snapshot."""
        with self._lock:
            return [(k, self._read(c)) for k, c in
                    sorted(self._children.items())]


class _BoundMetric:
    """A metric bound to one label-value tuple (result of .labels())."""

    __slots__ = ("_metric", "_key", "_child")

    def __init__(self, metric, key, child):
        self._metric = metric
        self._key = key
        self._child = child

    def __getattr__(self, name):
        fn = getattr(type(self._metric), "_" + name, None)
        if fn is None:
            raise AttributeError(name)
        metric, child = self._metric, self._child
        return lambda *a, **k: fn(metric, child, *a, **k)


class Counter(_Metric):
    """Monotone counter.  inc() with a negative amount raises — use a Gauge
    for up/down values (the STAT compat shim does)."""

    kind = "counter"

    def _new_child(self):
        return [0.0]

    def _read(self, child):
        return child[0]

    def _inc(self, child, amount=1.0):
        if amount < 0:
            raise ValueError(f"Counter {self.name} cannot decrease "
                             f"(inc {amount}); use a Gauge")
        with self._lock:
            child[0] += amount

    def inc(self, amount: float = 1.0):
        self._inc(self._child(), amount)

    def value(self, **labels) -> float:
        key = _label_key(self.labelnames, labels) if labels else ()
        return self._read(self._child(key))


class Gauge(_Metric):
    """Up/down instantaneous value."""

    kind = "gauge"

    def _new_child(self):
        return [0.0]

    def _read(self, child):
        return child[0]

    def _inc(self, child, amount=1.0):
        with self._lock:
            child[0] += amount

    def _dec(self, child, amount=1.0):
        with self._lock:
            child[0] -= amount

    def _set(self, child, value):
        with self._lock:
            child[0] = float(value)

    def inc(self, amount: float = 1.0):
        self._inc(self._child(), amount)

    def dec(self, amount: float = 1.0):
        self._dec(self._child(), amount)

    def set(self, value: float):
        self._set(self._child(), value)

    def value(self, **labels) -> float:
        key = _label_key(self.labelnames, labels) if labels else ()
        return self._read(self._child(key))


class _HistChild:
    __slots__ = ("counts", "sum", "count", "min", "max")

    def __init__(self, n_buckets):
        self.counts = [0] * (n_buckets + 1)  # +1 = +Inf overflow
        self.sum = 0.0
        self.count = 0
        self.min = None
        self.max = None


class Histogram(_Metric):
    """Fixed-bucket histogram with cumulative-bucket export and quantile
    estimation (linear interpolation inside the landing bucket)."""

    kind = "histogram"

    def __init__(self, name, help="", labelnames=(), buckets=None):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))

    def _new_child(self):
        return _HistChild(len(self.buckets))

    def _read(self, child):
        with self._lock:
            counts = list(child.counts)
            s, n = child.sum, child.count
            mn, mx = child.min, child.max
        return {"buckets": self.buckets, "counts": counts, "sum": s,
                "count": n, "min": mn, "max": mx}

    def _observe(self, child, value):
        value = float(value)
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            child.counts[i] += 1
            child.sum += value
            child.count += 1
            if child.min is None or value < child.min:
                child.min = value
            if child.max is None or value > child.max:
                child.max = value

    def observe(self, value: float):
        self._observe(self._child(), value)

    def time(self):
        """Context manager observing the block's wall time in seconds."""
        return _HistTimer(self)

    def snapshot(self, **labels) -> dict:
        key = _label_key(self.labelnames, labels) if labels else ()
        return self._read(self._child(key))

    def quantile(self, q: float, **labels) -> Optional[float]:
        """Estimated q-quantile (0..1) from the bucket counts, or None when
        empty.  Linear interpolation inside the landing bucket, whose lower
        edge is the PREVIOUS bucket bound (advanced across empty buckets
        too — a stale edge would bias bimodal tails low), clamped to the
        recorded min/max."""
        snap = self.snapshot(**labels)
        n = snap["count"]
        if not n:
            return None
        if q <= 0:
            return snap["min"]
        if q >= 1:
            return snap["max"]
        rank = q * n
        cum = 0.0
        mn = snap["min"] if snap["min"] is not None else 0.0
        mx = snap["max"] if snap["max"] is not None else mn
        lower = None  # lower edge of the current bucket
        for i, c in enumerate(snap["counts"]):
            upper = self.buckets[i] if i < len(self.buckets) else mx
            if c and cum + c >= rank:
                lo = max(lower, mn) if lower is not None else mn
                hi = min(upper, mx)
                if hi < lo:
                    return lo
                frac = (rank - cum) / c
                return lo + (hi - lo) * frac
            cum += c
            lower = upper
        return mx


class _HistTimer:
    __slots__ = ("_hist", "_t0")

    def __init__(self, hist):
        self._hist = hist

    def __enter__(self):
        import time
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        import time
        self._hist.observe(time.perf_counter() - self._t0)
        return False


class MetricsRegistry:
    """Name -> metric registry.  get-or-create constructors are
    type-checked: asking for an existing name with a different kind or
    label set raises instead of silently splitting the series."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        self._collectors: list = []

    # -- constructors --------------------------------------------------------
    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or (tuple(labelnames)
                                              != m.labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind} "
                        f"with labels {m.labelnames}")
                return m
            m = cls(name, help, tuple(labelnames), **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    # -- collectors ----------------------------------------------------------
    def register_collector(self, fn: Callable[[], Iterable[dict]]):
        """Register a zero-arg callable returning sample dicts
        ({name, kind, help?, value, labels?}) evaluated at snapshot/export
        time — how hot-path counters that must not pay a lock per bump
        (core.op's dispatch-cache dict) surface here."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def unregister_collector(self, fn):
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def _collected(self):
        with self._lock:
            collectors = list(self._collectors)
        out = []
        for fn in collectors:
            try:
                out.extend(fn())
            except Exception:
                continue  # a broken collector must not break the scrape
        return out

    # -- access / export -----------------------------------------------------
    def get(self, name) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self):
        with self._lock:
            return sorted(self._metrics)

    def remove(self, name):
        with self._lock:
            self._metrics.pop(name, None)

    def snapshot(self) -> dict:
        """{name: {kind, help, labelnames, samples: [(labels, value)]}} +
        collector-supplied series."""
        with self._lock:
            metrics = list(self._metrics.values())
        out = {}
        for m in metrics:
            out[m.name] = {"kind": m.kind, "help": m.help,
                           "labelnames": m.labelnames,
                           "samples": m.samples()}
        for s in self._collected():
            name = s["name"]
            ent = out.setdefault(name, {"kind": s.get("kind", "gauge"),
                                        "help": s.get("help", ""),
                                        "labelnames": (), "samples": []})
            labels = tuple(str(v) for v in (s.get("labels") or ()))
            ent["samples"].append((labels, s["value"]))
        return out

    def reset(self):
        """Zero every metric's children (registrations and collector hooks
        survive; cached metric handles stay valid)."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.clear()


_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _default_registry


def counter(name, help="", labelnames=()) -> Counter:
    return _default_registry.counter(name, help, labelnames)


def gauge(name, help="", labelnames=()) -> Gauge:
    return _default_registry.gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=None) -> Histogram:
    return _default_registry.histogram(name, help, labelnames, buckets)
