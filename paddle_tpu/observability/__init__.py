"""paddle_tpu.observability — the unified telemetry subsystem.

Reference: the platform observability slice — platform/profiler.h
RecordEvent, the CUPTI DeviceTracer chrome-trace path
(platform/device_tracer.cc) and the platform/monitor.h StatRegistry —
rebuilt as one first-class package every runtime component reports
through:

- **metrics**  — typed Counter/Gauge/Histogram registry with label sets,
  per-metric locks, and collector callbacks for hot-path counters that
  live elsewhere (the dispatch cache).  `utils.monitor`'s STAT_* verbs
  are a compat shim over it.
- **tracer**   — nestable host spans with thread ids and explicit
  parents, a bounded ring buffer, chrome://tracing export, and
  jax.profiler trace-annotation passthrough so host spans line up with
  the XLA device timeline.  `utils.profiler` is a compat shim over it.
- **programs** — the compiled-program registry: every jit /
  dispatch-cache / TrainStep / serving compile records compile
  wall-time, XLA cost-analysis FLOPs + bytes, and argument/donated/
  output buffer bytes, queryable by program name.
- **exporters** — Prometheus text exposition over a stdlib HTTP
  endpoint, a JSONL file sink, and `report()`: ONE report shape that
  subsumes the profiler table, `monitor.stats()`,
  `ServingEngine.metrics()` and `Predictor.profile_report()`.

Quick use:

    from paddle_tpu import observability as obs
    with obs.span("load_batch"):
        ...
    obs.counter("my_events_total").inc()
    print(obs.prometheus_text())
    rep = obs.report()          # dispatch cache, dataloader, checkpoint,
                                # train, serving, compiled programs
    srv = obs.serve_metrics(9464)   # GET /metrics, /report
"""
from __future__ import annotations

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,  # noqa: F401
                      counter, gauge, get_registry, histogram)
from .tracer import Span, Tracer, get_tracer, span  # noqa: F401
from .programs import (ProgramRegistry, TrackedJit, aot_fallbacks,  # noqa: F401
                       get_program_registry, note_compile, track)
from .exporters import (JsonlSink, MetricsServer, prometheus_text,  # noqa: F401
                        render_endpoint, report, serve_metrics)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "counter", "gauge",
    "histogram", "get_registry",
    "Span", "Tracer", "get_tracer", "span",
    "ProgramRegistry", "TrackedJit", "get_program_registry", "note_compile",
    "track", "aot_fallbacks",
    "JsonlSink", "MetricsServer", "prometheus_text", "render_endpoint",
    "report", "serve_metrics",
    "export_chrome_trace", "reset",
]


def export_chrome_trace(path: str) -> str:
    """Write the default tracer's ring as a chrome://tracing JSON file."""
    return get_tracer().export_chrome_trace(path)


def reset():
    """Zero metrics, clear spans and the program registry (tests, or a
    live `FLAGS_reset_stats`-style wipe)."""
    get_registry().reset()
    get_tracer().clear()
    get_program_registry().clear()
