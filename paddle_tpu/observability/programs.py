"""Compiled-program registry: every big XLA program, queryable by name.

Promotes the machinery `probes/hbm_probe.py` uses ad hoc (lower -> compile
-> cost_analysis "bytes accessed"/"flops") to a first-class API: each
jit / dispatch-cache / TrainStep / serving compile records its compile
wall-time, XLA cost-analysis FLOPs + bytes accessed, and the executable's
argument/output/donated(alias)/temp buffer bytes, keyed by program name.

Two entry points:

- `track(name, jitted)` wraps a `jax.jit` result.  On a new input
  signature it compiles via the AOT path (`lower().compile()`) — the same
  single compilation `jitted(...)` would have paid, but with the compiled
  object in hand for cost/memory analysis — caches the executable per
  signature, and records the compile.  Signature mismatches or AOT
  failures fall back to the wrapped jitted callable, so tracking can
  never change program semantics.  `PDTPU_OBS_PROGRAMS=0` makes `track`
  return the jitted fn untouched.
- `note_compile(name, seconds, ...)` records a compile observed elsewhere
  (the eager dispatch cache times its miss path and reports here without
  paying an extra lowering per op signature).
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, Optional

__all__ = ["ProgramRegistry", "get_program_registry", "track",
           "note_compile", "TrackedJit", "aot_fallbacks",
           "peak_live_bytes", "trace_peak_live"]

_log = logging.getLogger("paddle_tpu.observability.programs")


def _count_aot_fallback():
    """programs_aot_fallback_total: every permanent AOT->passthrough
    downgrade is counted — a TrackedJit that silently stops produce
    cost/memory telemetry used to be invisible (ISSUE 9 satellite)."""
    try:
        from .metrics import counter
        counter("programs_aot_fallback_total",
                "TrackedJit programs permanently fallen back from the "
                "AOT compile path (no cost/memory analysis recorded)"
                ).inc()
    except Exception:
        pass  # telemetry must never break dispatch


def _tracking_enabled() -> bool:
    return os.environ.get("PDTPU_OBS_PROGRAMS", "1").lower() not in (
        "0", "off", "false", "no")


def _cost_dict(compiled) -> dict:
    """Flatten cost_analysis + memory_analysis of a jax Compiled object
    into plain floats; every field is best-effort (backends differ)."""
    out = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            out["flops"] = float(ca.get("flops", 0.0))
            out["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        for field, key in (("argument_size_in_bytes", "argument_bytes"),
                           ("output_size_in_bytes", "output_bytes"),
                           ("alias_size_in_bytes", "donated_bytes"),
                           ("temp_size_in_bytes", "temp_bytes"),
                           ("generated_code_size_in_bytes", "code_bytes")):
            v = getattr(ma, field, None)
            if v is not None:
                out[key] = float(v)
    except Exception:
        pass
    if "argument_bytes" in out and "donated_bytes" in out:
        # buffers live across the call = arguments not donated + outputs
        out["live_bytes"] = (out["argument_bytes"] - out["donated_bytes"]
                             + out.get("output_bytes", 0.0)
                             + out.get("temp_bytes", 0.0))
    return out


# ---------------------------------------------------------------------------
# peak-live-bytes estimator (backend-independent)
#
# XLA's Compiled.memory_analysis() is liveness-aware on TPU but on the CPU
# backend `temp_size_in_bytes` reports the un-reused buffer total — it does
# not move when `jax.checkpoint` drops residuals, so it cannot gate an
# activation-recompute win in CPU CI.  This walks the post-AD jaxpr in
# program order, tracking birth (eqn outputs) and death (last use) of every
# value: the running maximum is the peak bytes simultaneously live.  remat/
# pjit/custom-vjp sub-jaxprs contribute their internal transient peak at
# their call site, so a checkpointed stage is charged for its recompute
# window instead of for residuals it no longer saves.


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        try:
            n *= int(d)
        except TypeError:
            return 0  # symbolic dim: skip
    return n * dtype.itemsize


_SUBJAXPR_PARAMS = ("jaxpr", "call_jaxpr", "fun_jaxpr", "cond_jaxpr",
                    "body_jaxpr")


def _sub_jaxprs(eqn):
    for key in _SUBJAXPR_PARAMS:
        sub = eqn.params.get(key)
        if sub is None:
            continue
        yield getattr(sub, "jaxpr", sub)  # ClosedJaxpr -> Jaxpr
    for sub in eqn.params.get("branches", ()) or ():
        yield getattr(sub, "jaxpr", sub)


# elementwise / layout prims whose single-use outputs XLA fuses into their
# consumer (producer-consumer loop fusion) — such a value never owns an HBM
# buffer, so charging it would systematically overestimate exactly the
# recompute interiors this estimator exists to compare
_FUSIBLE_PRIMS = frozenset({
    "convert_element_type", "reduce_precision", "add", "sub", "mul", "div",
    "max", "min", "neg", "abs", "sign", "exp", "log", "log1p", "expm1",
    "rsqrt", "sqrt", "tanh", "logistic", "pow", "integer_pow", "clamp",
    "select_n", "eq", "ne", "lt", "le", "gt", "ge", "and", "or", "not",
    "xor", "is_finite", "broadcast_in_dim", "reshape", "squeeze",
    "expand_dims", "stop_gradient", "copy",
})
# a single-use fusible value must be consumed within this many eqns to be
# treated as fused (XLA fuses within a region, not across a whole program;
# long-span values are real buffers — e.g. residuals crossing fwd -> bwd)
_FUSE_WINDOW = 8

# single-operand dtype/layout prims XLA ALWAYS duplicates into consumer
# fusions (a convert/broadcast is re-emitted per consumer rather than
# materialized, at ANY use count or span): their outputs read through to
# the source buffer — uses of the output count as uses of the source, and
# the output itself never owns bytes.  Without this, every f32 upcast of a
# bf16 activation shared by a recomputed forward and its backward is
# charged as a full f32 copy — double-counting exactly the values inside
# jax.checkpoint interiors this estimator exists to measure.
_READTHROUGH_PRIMS = frozenset({
    "convert_element_type", "reduce_precision", "broadcast_in_dim",
    "reshape", "squeeze", "expand_dims", "stop_gradient", "copy",
})


def peak_live_bytes(jaxpr) -> int:
    """Estimated peak bytes simultaneously live while executing `jaxpr`
    (a Jaxpr or ClosedJaxpr) in program order.  An estimate, not a buffer
    assignment: donation/aliasing is not modelled (both legs of an A/B
    carry it equally), call-like eqns are charged io + internal transient
    peak, and single-consumer short-span elementwise values are treated as
    fused into their consumer (see _FUSIBLE_PRIMS)."""
    import jax  # noqa: F401  (jaxpr classes ride on instances)
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    eqns = jaxpr.eqns

    def _is_var(v):
        # Literals are unhashable and occupy no buffer
        return hasattr(v, "count") and hasattr(v, "aval")

    # read-through aliasing: out -> ultimate source var for dtype/layout
    # no-op prims (see _READTHROUGH_PRIMS)
    alias: Dict[object, object] = {}

    def _root(v):
        while v in alias:
            v = alias[v]
        return v

    for eqn in eqns:
        if (eqn.primitive.name in _READTHROUGH_PRIMS
                and len(eqn.outvars) == 1 and len(eqn.invars) == 1
                and _is_var(eqn.invars[0])):
            alias[eqn.outvars[0]] = _root(eqn.invars[0])

    last_use: Dict[object, int] = {}
    n_uses: Dict[object, int] = {}
    for idx, eqn in enumerate(eqns):
        if eqn.outvars and eqn.outvars[0] in alias:
            continue  # the aliasing eqn itself is a no-op, not a use
        for v in eqn.invars:
            if _is_var(v):
                r = _root(v)
                last_use[r] = idx
                n_uses[r] = n_uses.get(r, 0) + 1
    for v in jaxpr.outvars:
        if _is_var(v):
            r = _root(v)
            last_use[r] = len(eqns)
            n_uses[r] = n_uses.get(r, 0) + 1
    sizes: Dict[object, int] = {}
    live = 0
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        if _is_var(v) and v not in sizes:
            sizes[v] = _aval_bytes(v.aval)
            live += sizes[v]
    peak = live
    for idx, eqn in enumerate(eqns):
        fusible = eqn.primitive.name in _FUSIBLE_PRIMS
        born = 0
        for v in eqn.outvars:
            if _is_var(v) and v not in sizes:
                if v in alias:
                    sizes[v] = 0  # reads through to its (charged) source
                elif (fusible and n_uses.get(v, 0) == 1
                        and last_use.get(v, idx) - idx <= _FUSE_WINDOW):
                    sizes[v] = 0  # fuses into its sole nearby consumer
                else:
                    sizes[v] = _aval_bytes(v.aval)
                born += sizes[v]
        live += born
        inner = 0
        for sub in _sub_jaxprs(eqn):
            io = sum(_aval_bytes(v.aval)
                     for v in list(sub.invars) + list(sub.outvars)
                     if _is_var(v))
            inner = max(inner, peak_live_bytes(sub) - io)
        peak = max(peak, live + max(inner, 0))
        for v in set(_root(v) for v in eqn.invars if _is_var(v)):
            if last_use.get(v) == idx:
                live -= sizes.get(v, 0)
        for v in eqn.outvars:
            if _is_var(v) and v not in alias and last_use.get(v, -1) < idx:
                live -= sizes.get(v, 0)  # dead on arrival (unused output)
    return peak


def trace_peak_live(jitted, *args, **kwargs) -> int:
    """peak_live_bytes of a jax.jit-wrapped callable at this signature
    (traces without compiling; TrackedJit instances are unwrapped)."""
    if isinstance(jitted, TrackedJit):
        jitted = jitted._jitted
    return peak_live_bytes(jitted.trace(*args, **kwargs).jaxpr)


class ProgramRegistry:
    """name -> {compiles, compile_seconds_total, last_compile_ms, cost...}"""

    def __init__(self):
        self._lock = threading.Lock()
        self._programs: Dict[str, dict] = {}

    def note_compile(self, name: str, seconds: float,
                     cost: Optional[dict] = None,
                     meta: Optional[dict] = None):
        with self._lock:
            rec = self._programs.get(name)
            if rec is None:
                rec = self._programs[name] = {
                    "name": name, "compiles": 0,
                    "compile_seconds_total": 0.0, "last_compile_ms": None,
                    "first_compiled_at": time.time()}
            rec["compiles"] += 1
            rec["compile_seconds_total"] += float(seconds)
            rec["last_compile_ms"] = float(seconds) * 1e3
            if cost:
                rec.update(cost)
            if meta:
                rec.setdefault("meta", {}).update(meta)

    def note_meta(self, name: str, meta: dict):
        """Attach/overwrite metadata WITHOUT counting a compile (the AOT
        fallback marker on an already-recorded program)."""
        with self._lock:
            rec = self._programs.get(name)
            if rec is None:
                rec = self._programs[name] = {
                    "name": name, "compiles": 0,
                    "compile_seconds_total": 0.0, "last_compile_ms": None,
                    "first_compiled_at": time.time()}
            rec.setdefault("meta", {}).update(meta)

    def get(self, name: str) -> Optional[dict]:
        with self._lock:
            rec = self._programs.get(name)
            return dict(rec) if rec is not None else None

    def names(self):
        with self._lock:
            return sorted(self._programs)

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._programs.items()}

    def total_compile_seconds(self) -> float:
        with self._lock:
            return sum(v["compile_seconds_total"]
                       for v in self._programs.values())

    def clear(self):
        with self._lock:
            self._programs.clear()


_default_programs = ProgramRegistry()


def get_program_registry() -> ProgramRegistry:
    return _default_programs


def note_compile(name: str, seconds: float, cost: Optional[dict] = None,
                 meta: Optional[dict] = None):
    _default_programs.note_compile(name, seconds, cost, meta)


def _sig_leaf(x):
    aval = getattr(x, "aval", None)
    if aval is not None:
        return (tuple(aval.shape), str(aval.dtype),
                bool(getattr(aval, "weak_type", False)))
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:  # np.ndarray
        return (tuple(shape), str(dtype), False)
    # python scalar: jax treats it as a weak-typed constant input whose
    # aval does not depend on the value — key by type only
    return ("py", type(x).__name__)


class TrackedJit:
    """Wrapper over a `jax.jit` callable that records every compile into
    the program registry (wall time + cost/memory analysis) by compiling
    through the AOT path once per input signature.

    Passes unknown attributes (`lower`, `eval_shape`, ...) through to the
    wrapped jitted fn, so call sites that lower explicitly
    (probes/hbm_probe.py does `step._build(...).lower(...)`) are
    unaffected."""

    def __init__(self, name: str, jitted, registry: ProgramRegistry = None):
        self._name = name
        self._jitted = jitted
        self._registry = registry or _default_programs
        self._exe = {}      # sig -> compiled executable
        self._last = None   # most recent executable (steady-state fast path)
        self._direct = False  # permanent fallback after an AOT failure

    def __call__(self, *args, **kwargs):
        # Executables validate input avals BEFORE donating or executing
        # anything and raise TypeError on mismatch (ValueError for pytree
        # structure) — so trying the last-used executable first is safe
        # and makes the steady state pay zero signature computation.  Any
        # OTHER exception comes from real execution and must propagate:
        # re-running the wrapped jit then could replay a donated-buffer
        # program and mask the original error.
        if self._direct:
            return self._jitted(*args, **kwargs)
        if self._last is not None:
            try:
                return self._last(*args, **kwargs)
            except (TypeError, ValueError):
                pass  # different signature: take the keyed path
        import jax
        flat, treedef = jax.tree_util.tree_flatten((args, kwargs))
        sig = (treedef, tuple(_sig_leaf(x) for x in flat))
        exe = self._exe.get(sig)
        if exe is None:
            t0 = time.perf_counter()
            try:
                exe = self._jitted.lower(*args, **kwargs).compile()
            except Exception as e:
                # not AOT-able (symbolic shapes, backend quirk): permanent
                # pass-through; estimate this compile from the first call
                self._fallback("aot-compile", e)
                out = self._jitted(*args, **kwargs)
                self._registry.note_compile(
                    self._name, time.perf_counter() - t0,
                    meta={"aot": False,
                          "fallback_error": f"{type(e).__name__}: {e}"[:300]})
                return out
            dt = time.perf_counter() - t0
            self._registry.note_compile(self._name, dt, _cost_dict(exe),
                                        meta={"aot": True})
            self._exe[sig] = exe
        self._last = exe
        try:
            return exe(*args, **kwargs)
        except TypeError as e:
            # aval-validation mismatch (raised before donation/execution):
            # our signature key was too coarse for this call pattern — run
            # the safe path and stop tracking; semantics over telemetry
            self._fallback("signature", e)
            self._registry.note_meta(
                self._name,
                {"aot": False,
                 "fallback_error": f"{type(e).__name__}: {e}"[:300]})
            self._exe.clear()
            self._last = None
            return self._jitted(*args, **kwargs)

    def _fallback(self, why: str, exc: BaseException):
        self._direct = True
        _count_aot_fallback()
        _log.debug("TrackedJit %r: permanent AOT fallback (%s)",
                   self._name, why, exc_info=exc)

    # -- AOT warmup / export hooks (paddle_tpu.programs) -------------------
    def warm(self, *args, **kwargs) -> bool:
        """Compile for this signature WITHOUT executing (TrainStep/engine
        warmup: priming must not apply an update or donate live buffers).
        Returns True when a compile happened, False when already warm or
        not AOT-able (the first real call then takes the normal path)."""
        if self._direct:
            return False
        import jax
        flat, treedef = jax.tree_util.tree_flatten((args, kwargs))
        sig = (treedef, tuple(_sig_leaf(x) for x in flat))
        if sig in self._exe:
            return False
        t0 = time.perf_counter()
        try:
            exe = self._jitted.lower(*args, **kwargs).compile()
        except Exception as e:
            self._fallback("warmup", e)
            self._registry.note_compile(
                self._name, time.perf_counter() - t0,
                meta={"aot": False,
                      "fallback_error": f"{type(e).__name__}: {e}"[:300]})
            return False
        self._registry.note_compile(self._name, time.perf_counter() - t0,
                                    _cost_dict(exe), meta={"aot": True})
        self._exe[sig] = exe
        self._last = exe
        return True

    def compiled_for(self, *args, **kwargs):
        """The compiled executable for this signature (compiling if
        needed), or None when not AOT-able — the program-set exporter
        reuses a warm engine's executables instead of recompiling."""
        if self._direct:
            return None
        import jax
        flat, treedef = jax.tree_util.tree_flatten((args, kwargs))
        sig = (treedef, tuple(_sig_leaf(x) for x in flat))
        exe = self._exe.get(sig)
        if exe is None:
            self.warm(*args, **kwargs)
            exe = self._exe.get(sig)
        return exe

    def __getattr__(self, attr):
        return getattr(self._jitted, attr)


def track(name: str, jitted, registry: ProgramRegistry = None):
    """Wrap a jitted callable for compile tracking (identity when
    PDTPU_OBS_PROGRAMS=0)."""
    if not _tracking_enabled():
        return jitted
    return TrackedJit(name, jitted, registry)


def aot_fallbacks(registry: ProgramRegistry = None) -> list:
    """Names of programs that permanently fell back from the AOT path —
    the report line that makes a silent telemetry downgrade visible."""
    snap = (registry or _default_programs).snapshot()
    return sorted(n for n, rec in snap.items()
                  if (rec.get("meta") or {}).get("aot") is False)
