"""Exporters: Prometheus text exposition, JSONL sink, the unified report.

- `prometheus_text()` renders the metrics registry (plus collector-fed
  series, e.g. the dispatch cache) in Prometheus text exposition format
  0.0.4.  `serve_metrics(port)` exposes it over a stdlib HTTP endpoint
  (`/metrics`, and `/report` as JSON); rendering is separated from the
  socket so tests exercise the exact handler payload without binding a
  port.
- `JsonlSink` appends periodic `report()` snapshots to a JSONL file from
  a daemon thread (the VisualDL-style flight recorder for post-mortems).
- `report()` is THE unified report: one pass over metrics registry,
  tracer aggregates, compiled-program registry and the dispatch cache,
  with derived sections for the runtime subsystems (dataloader /
  checkpoint / train / serving) that used to each print their own format.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Optional

from .metrics import Histogram, get_registry
from .programs import get_program_registry
from .tracer import get_tracer

__all__ = ["prometheus_text", "serve_metrics", "MetricsServer",
           "JsonlSink", "report", "render_endpoint"]


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

def _esc(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n").replace(
        '"', '\\"')


def _labels_str(labelnames, values, extra=None) -> str:
    pairs = [f'{n}="{_esc(v)}"' for n, v in zip(labelnames, values)]
    if extra:
        pairs += [f'{n}="{_esc(v)}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def prometheus_text(registry=None) -> str:
    """Render the registry in Prometheus text exposition format."""
    registry = registry or get_registry()
    lines = []
    snap = registry.snapshot()
    for name in sorted(snap):
        ent = snap[name]
        kind = ent["kind"]
        prom_kind = kind if kind in ("counter", "gauge", "histogram") \
            else "untyped"
        if ent.get("help"):
            lines.append(f"# HELP {name} {_esc(ent['help'])}")
        lines.append(f"# TYPE {name} {prom_kind}")
        labelnames = ent.get("labelnames", ())
        for values, v in ent["samples"]:
            if isinstance(v, dict) and "buckets" in v:  # histogram
                cum = 0
                for bound, c in zip(v["buckets"], v["counts"]):
                    cum += c
                    lines.append(
                        f"{name}_bucket"
                        f"{_labels_str(labelnames, values, [('le', _fmt(bound))])}"
                        f" {cum}")
                cum += v["counts"][-1]
                lines.append(
                    f"{name}_bucket"
                    f"{_labels_str(labelnames, values, [('le', '+Inf')])}"
                    f" {cum}")
                lines.append(f"{name}_sum"
                             f"{_labels_str(labelnames, values)} "
                             f"{_fmt(v['sum'])}")
                lines.append(f"{name}_count"
                             f"{_labels_str(labelnames, values)} {cum}")
            else:
                lines.append(f"{name}{_labels_str(labelnames, values)} "
                             f"{_fmt(v)}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# unified report
# ---------------------------------------------------------------------------

def _hist_summary(name: str) -> Optional[dict]:
    m = get_registry().get(name)
    if not isinstance(m, Histogram):
        return None
    snap = m.snapshot()
    if not snap["count"]:
        return {"count": 0}
    return {"count": snap["count"], "sum_s": snap["sum"],
            "mean_ms": snap["sum"] / snap["count"] * 1e3,
            "p50_ms": (m.quantile(0.5) or 0.0) * 1e3,
            "p90_ms": (m.quantile(0.9) or 0.0) * 1e3,
            "p99_ms": (m.quantile(0.99) or 0.0) * 1e3,
            "min_ms": (snap["min"] or 0.0) * 1e3,
            "max_ms": (snap["max"] or 0.0) * 1e3}


def _gauge_value(name: str):
    m = get_registry().get(name)
    try:
        return m.value() if m is not None else None
    except Exception:
        return None


def report() -> dict:
    """One report for the whole runtime — subsumes the profiler table,
    `monitor.stats()`, `ServingEngine.metrics()` and
    `Predictor.profile_report()`'s divergent shapes."""
    from ..utils import monitor

    # dispatch cache (hot-path dict, surfaced via its collector too)
    try:
        from ..core import op as _op
        cs = _op.dispatch_cache_stats()
        total = cs["hits"] + cs["misses"]
        dispatch = dict(cs, hit_rate=(cs["hits"] / total if total else None))
    except Exception:
        dispatch = {}

    stats = monitor.stats()
    train = {
        "step_seconds": _hist_summary("train_step_seconds"),
        "data_wait_seconds": _hist_summary("dataloader_data_wait_seconds"),
        "checkpoint_stall_seconds":
            _hist_summary("checkpoint_save_stall_seconds"),
        "guard_bad_steps": stats.get("STAT_guarded_bad_steps", 0),
        "guard_rollbacks": stats.get("STAT_guarded_rollbacks", 0),
    }
    dataloader = {
        "data_wait_seconds": _hist_summary("dataloader_data_wait_seconds"),
        "queue_depth": _gauge_value("dataloader_queue_depth"),
        "batches": stats.get("STAT_dataloader_batch_count", 0),
        "bytes": stats.get("STAT_dataloader_bytes", 0),
        "worker_respawns": stats.get("STAT_dataloader_worker_respawns", 0),
    }
    checkpoint = {
        "save_stall_seconds": _hist_summary("checkpoint_save_stall_seconds"),
        "async_in_flight": _gauge_value("checkpoint_async_in_flight"),
        "bytes_written": stats.get("STAT_checkpoint_bytes_written", 0),
        "saves": stats.get("STAT_checkpoint_saves", 0),
        "async_writes": stats.get("STAT_checkpoint_async_writes", 0),
    }
    pc_hits = _gauge_value("prefix_cache_hits_total") or 0
    pc_misses = _gauge_value("prefix_cache_misses_total") or 0
    pc_total = pc_hits + pc_misses
    serving = {
        "ttft_seconds": _hist_summary("serving_ttft_seconds"),
        "inter_token_seconds": _hist_summary("serving_inter_token_seconds"),
        "slot_occupancy": _gauge_value("serving_slot_occupancy"),
        "queue_depth": _gauge_value("serving_queue_depth"),
        "queue_full_rejections": stats.get("STAT_serving_rejects", 0),
        "tokens_out": stats.get("STAT_serving_tokens", 0),
        "requests": stats.get("STAT_serving_requests", 0),
        # prefix cache: block-level prompt reuse across admissions
        "prefix_cache_hits": pc_hits,
        "prefix_cache_misses": pc_misses,
        "prefix_cache_hit_rate": (pc_hits / pc_total if pc_total
                                  else None),
        "prefix_cache_evictions":
            _gauge_value("prefix_cache_evictions_total") or 0,
        "prefix_cache_cow_copies":
            _gauge_value("prefix_cache_cow_copies_total") or 0,
        # multi-tenant LoRA on the serving path: registry residency and
        # page-in/out churn (`adapter_active` mirrors the
        # lora_adapters_loaded gauge; ship_retries counts artifact
        # re-ships after a corrupt/failed transfer)
        "adapter_loads": stats.get("STAT_lora_adapter_loads", 0),
        "adapter_evictions": stats.get("STAT_lora_adapter_evictions", 0),
        "adapter_ship_retries": stats.get("STAT_lora_ship_reships", 0),
        "adapter_active": _gauge_value("lora_adapters_loaded") or 0,
    }
    fleet = {
        "replicas_up": _gauge_value("fleet_replicas_up"),
        "failovers": stats.get("STAT_fleet_failovers", 0),
        "migrated_runs": stats.get("STAT_fleet_migrated_runs", 0),
        "resubmits": stats.get("STAT_fleet_resubmits", 0),
        "lost_runs": stats.get("STAT_fleet_lost_runs", 0),
        "reroutes": stats.get("STAT_fleet_reroutes", 0),
        "drains": stats.get("STAT_fleet_drains", 0),
        # subprocess workers (process isolation): live worker processes,
        # heartbeat-age fences (wedges), supervised restarts and
        # budget exhaustions
        "worker_processes": _gauge_value("fleet_worker_processes"),
        "wedges": stats.get("STAT_fleet_wedges", 0),
        "worker_restarts": stats.get("STAT_fleet_worker_restarts", 0),
        "restarts_exhausted": stats.get("STAT_fleet_restarts_exhausted",
                                        0),
        # remote (network-attached) workers: boot-handshake artifact
        # shipping volume — which weights a replica serves is per-replica
        # in /healthz (weights_sha/epoch in each snapshot)
        "weight_bytes_shipped": stats.get(
            "STAT_fleet_weight_bytes_shipped", 0),
    }
    # train->serve loop: continuous weight refresh (canary-gated flips,
    # quarantining rollbacks) + SLO-driven elastic membership
    elastic = {
        "target_replicas": _gauge_value("fleet_target_replicas"),
        "weight_refreshes": stats.get("STAT_fleet_weight_refreshes", 0),
        "rollbacks": stats.get("STAT_fleet_rollbacks", 0),
        "scale_ups": stats.get("STAT_fleet_scale_up", 0),
        "scale_downs": stats.get("STAT_fleet_scale_down", 0),
    }
    gateway = {
        "ttft_hi_seconds": _hist_summary("gateway_ttft_hi_seconds"),
        "ttft_lo_seconds": _hist_summary("gateway_ttft_lo_seconds"),
        "lane_depth_hi": _gauge_value("gateway_lane_hi_depth"),
        "lane_depth_lo": _gauge_value("gateway_lane_lo_depth"),
        "paused_runs": _gauge_value("gateway_paused_runs"),
        "requests": stats.get("STAT_gateway_requests", 0),
        "admitted": stats.get("STAT_gateway_admitted", 0),
        "shed": stats.get("STAT_gateway_shed", 0),
        "rate_limited": stats.get("STAT_gateway_rate_limited", 0),
        "preemptions": stats.get("STAT_gateway_preemptions", 0),
        "resumes": stats.get("STAT_gateway_resumes", 0),
    }
    # multi-tenant LoRA: registry residency + artifact shipping volume
    lora = {
        "adapters_loaded": _gauge_value("lora_adapters_loaded"),
        "adapter_loads": stats.get("STAT_lora_adapter_loads", 0),
        "adapter_evictions": stats.get("STAT_lora_adapter_evictions", 0),
        "rejects": stats.get("STAT_lora_rejects", 0),
        "ship_bytes": stats.get("STAT_lora_ship_bytes", 0),
        "ship_reattaches": stats.get("STAT_lora_ship_reattaches", 0),
        "ship_retries": stats.get("STAT_lora_ship_reships", 0),
    }
    gathered = stats.get("STAT_embedding_rows_gathered", 0)
    unique = stats.get("STAT_embedding_rows_unique", 0)
    pf_hits = stats.get("STAT_embedding_prefetch_hits", 0)
    pf_misses = stats.get("STAT_embedding_prefetch_misses", 0)
    embedding = {
        "prefetch_wait_seconds":
            _hist_summary("embedding_prefetch_wait_seconds"),
        "device_table_bytes": _gauge_value("embedding_device_table_bytes"),
        "rows_gathered": gathered,
        "rows_unique": unique,
        "dedup_ratio": (gathered / unique) if unique else None,
        "prefetch_hits": pf_hits,
        "prefetch_misses": pf_misses,
        "prefetch_hit_rate": (pf_hits / (pf_hits + pf_misses)
                              if (pf_hits + pf_misses) else None),
        "host_to_device_bytes":
            stats.get("STAT_embedding_host_to_device_bytes", 0),
        "device_to_host_bytes":
            stats.get("STAT_embedding_device_to_host_bytes", 0),
        "corrupt_rows_detected":
            stats.get("STAT_embedding_corrupt_rows_detected", 0),
        "serving_rejects": stats.get("STAT_embedding_serving_rejects", 0),
    }
    # program lifecycle: the persistent program store + the AOT-fallback
    # line (a TrackedJit that silently downgraded used to be invisible)
    try:
        from ..programs.store import store_stats
        program_store = store_stats()
    except Exception:
        program_store = None
    from .programs import aot_fallbacks as _aot_fallbacks
    fallbacks = _aot_fallbacks()

    return {
        "generated_at": time.time(),
        "dispatch_cache": dispatch,
        "dataloader": dataloader,
        "checkpoint": checkpoint,
        "train": train,
        "serving": serving,
        "gateway": gateway,
        "fleet": fleet,
        "elastic": elastic,
        "lora": lora,
        "embedding": embedding,
        "programs": get_program_registry().snapshot(),
        "program_store": program_store,
        "programs_aot_fallbacks": fallbacks,
        "spans": get_tracer().aggregates(),
        "stats": stats,
        "metrics": get_registry().snapshot(),
    }


# ---------------------------------------------------------------------------
# HTTP endpoint (stdlib only)
# ---------------------------------------------------------------------------

def render_endpoint(path: str):
    """(status, content_type, body) for a metrics-endpoint path — the
    handler body, callable without a socket (tier-1 stays port-free)."""
    if path.split("?")[0] in ("/metrics", "/"):
        return (200, "text/plain; version=0.0.4; charset=utf-8",
                prometheus_text().encode())
    if path.split("?")[0] == "/report":
        return (200, "application/json",
                json.dumps(report(), default=str).encode())
    return 404, "text/plain", b"not found\n"


class MetricsServer:
    """`/metrics` (Prometheus) + `/report` (JSON) over http.server."""

    def __init__(self, port: int = 0, addr: str = "127.0.0.1"):
        import http.server

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib naming)
                status, ctype, body = render_endpoint(self.path)
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence per-scrape stderr noise
                pass

        self._httpd = http.server.ThreadingHTTPServer((addr, port), Handler)
        self.port = self._httpd.server_address[1]
        self.addr = addr
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="paddle_tpu-metrics",
                                        daemon=True)
        self._thread.start()

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def serve_metrics(port: int = 9464, addr: str = "127.0.0.1") -> MetricsServer:
    """Start the metrics endpoint; returns the server (`.close()` stops)."""
    return MetricsServer(port=port, addr=addr)


# ---------------------------------------------------------------------------
# JSONL sink
# ---------------------------------------------------------------------------

class JsonlSink:
    """Periodic `report()` snapshots appended to a JSONL file.

    flush() writes one line now; a daemon thread writes every
    `interval_seconds` (None = manual-only).  Lines are self-contained
    JSON objects, so a crashed run's file is readable up to the last
    complete line."""

    def __init__(self, path: str, interval_seconds: Optional[float] = 30.0,
                 full_metrics: bool = False):
        self.path = path
        self.interval = interval_seconds
        self._full = full_metrics
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        if interval_seconds is not None:
            self._thread = threading.Thread(target=self._loop,
                                            name="paddle_tpu-jsonl-sink",
                                            daemon=True)
            self._thread.start()

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.flush()
            except Exception:
                pass  # a full disk must not kill the run

    def flush(self) -> str:
        rec = report()
        if not self._full:  # keep lines compact: drop the raw dumps
            rec.pop("metrics", None)
            rec.pop("spans", None)
        line = json.dumps(rec, default=str)
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line + "\n")
        return self.path

    def close(self, final_flush: bool = True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if final_flush:
            try:
                self.flush()
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
