"""paddle_tpu.parallel — mesh/sharding-based parallelism.

TPU-native replacement for the reference's meta-optimizer program rewriting
(SURVEY.md §2.2-2.3): parallelism = mesh axes + PartitionSpecs + one jitted
SPMD train step; XLA inserts all collectives.
"""
from .mesh import (create_mesh, set_mesh, get_mesh, axis_size,  # noqa: F401
                   sharding, replicated, AXES)
from .strategy import (DistributedStrategy, HybridConfig,  # noqa: F401
                       ShardingConfig, RecomputeConfig, AMPConfig,
                       GradientMergeConfig)
from .sharding import (tp_spec, param_specs, shardings_of,  # noqa: F401
                       apply_fsdp)
from .train_step import ShardedTrainStep  # noqa: F401
from .localsgd import LocalSGDTrainStep  # noqa: F401
