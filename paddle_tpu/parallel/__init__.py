"""parallel primitives namespace — see paddle_tpu.distributed."""
