"""Device-mesh management — the TPU-native replacement for the reference's
ring-id-keyed NCCL communicator registry (platform/collective_helper.h:63):
instead of bootstrapping per-ring communicators over TCP
(c_gen_nccl_id/c_comm_init, operators/collective/), a single
`jax.sharding.Mesh` names the parallelism axes and XLA inserts/schedules all
collectives over ICI/DCN.

Canonical axis names: "dp" (data), "pp" (pipeline stages), "ep" (experts /
MoE), "tp" (tensor / intra-layer model), "sp" (sequence / context).  A mesh
axis of size 1 simply disables that parallelism dimension.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "pp", "ep", "tp", "sp")

_GLOBAL_MESH: Optional[Mesh] = None


def create_mesh(axes: Optional[Dict[str, int]] = None,
                devices: Optional[Sequence] = None, **axis_sizes) -> Mesh:
    """Build a Mesh from {"dp": 2, "tp": 4, ...}; unlisted axes get size 1.

    Axis order is fixed (dp, pp, tp, sp) with dp outermost — tp/sp vary
    fastest so they land on the most tightly coupled (ICI-adjacent) devices,
    the analogue of putting the hierarchical-allreduce inner ring on NVLink
    (distributed_strategy.proto:128).
    """
    from ..core.errors import enforce
    sizes = dict(axes or {})
    sizes.update(axis_sizes)
    for a in sizes:
        enforce(a in AXES, f"unknown mesh axis {a!r}; valid: {AXES}")
    devices = list(devices if devices is not None else jax.devices())
    n = int(np.prod([sizes.get(a, 1) for a in AXES]))
    enforce(n <= len(devices),
            f"mesh wants {n} devices but only {len(devices)} available")
    shape = tuple(sizes.get(a, 1) for a in AXES)
    arr = np.array(devices[:n]).reshape(shape)
    # arm eager dispatch's placement harmonization: once a mesh exists,
    # eager ops may mix mesh-sharded and single-device operands (core.op
    # skips that per-input scan until this is called — the cheap-path gate)
    from ..core import op as _op
    _op.note_multi_device()
    return Mesh(arr, AXES)


def set_mesh(mesh: Optional[Mesh]):
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh
    if mesh is not None:
        # externally built meshes (jax.sharding.Mesh direct) must also arm
        # eager placement harmonization
        from ..core import op as _op
        _op.note_multi_device()


def get_mesh(create_default: bool = False) -> Optional[Mesh]:
    global _GLOBAL_MESH
    if _GLOBAL_MESH is None and create_default:
        _GLOBAL_MESH = create_mesh({"dp": len(jax.devices())})
    return _GLOBAL_MESH


def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1)


def sharding(mesh: Mesh, *spec) -> NamedSharding:
    """NamedSharding helper: sharding(mesh, 'dp', None) -> rows over dp."""
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
