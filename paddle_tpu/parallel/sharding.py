"""Parameter-sharding rules: Megatron-style tensor parallelism + ZeRO/FSDP
as PartitionSpecs.

Reference equivalents:
- TP: absent in the reference (SURVEY.md §2.3 — nothing splits a matmul);
  on TPU it is free via operand sharding, so it's included.
- ZeRO sharding: Fleet ShardingOptimizer's program rewrite
  (meta_optimizers/sharding_optimizer.py:96-118 — param→rank assignment +
  inserted c_broadcast/c_allreduce).  Here the same memory win is a
  PartitionSpec on params/optimizer states; XLA GSPMD inserts the
  all-gathers/reduce-scatters the rewrite used to insert by hand.

Linear weights are (in_features, out_features) [paddle layout], so:
- column-parallel (split output): P(None, "tp")  — qkv / ffn_in
- row-parallel  (split input):    P("tp", None)  — out proj / ffn_out
- embeddings (vocab, hidden):     P("tp", None)  — vocab-sharded
"""
from __future__ import annotations

import re
from typing import Callable, Dict, Optional

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# name-pattern → spec builders; first match wins.  Patterns cover the
# in-tree model zoo (models/bert.py, models/gpt.py) and the generic
# nn.MultiHeadAttention/TransformerEncoder naming.
_COL_W = re.compile(
    r"(qkv|ffn_in|linear1|q_proj|k_proj|v_proj)\.weight$")
_COL_B = re.compile(
    r"(qkv|ffn_in|linear1|q_proj|k_proj|v_proj)\.bias$")
_ROW_W = re.compile(
    r"(\bout\b|proj|ffn_out|linear2|out_proj)\.weight$")
_EMB_W = re.compile(r"(word|position|token_type|task_type)_embeddings\.weight$")
# MoELayer expert weights: leading dim is the expert axis (nn/layer/moe.py
# names them experts_w1/b1/w2/b2; gate stays replicated)
_EXPERT = re.compile(r"experts?_(w1|b1|w2|b2)$|\.experts\.")


def row_spec(axis: str, ndim: int = 2) -> P:
    """Row-sharded spec: leading dim over `axis`, the rest replicated —
    the layout of embedding.ShardedEmbedding tables (and their row-wise
    optimizer-moment leaves via state_sharding_like)."""
    return P(*((axis,) + (None,) * (ndim - 1)))


def ep_spec(name: str, shape) -> Optional[P]:
    """Expert-parallel PartitionSpec: shard the leading (expert) dim."""
    if _EXPERT.search(name) and len(shape) >= 1:
        return P(*(("ep",) + (None,) * (len(shape) - 1)))
    return None


def tp_spec(name: str, shape) -> Optional[P]:
    """Tensor-parallel PartitionSpec for a parameter, or None (replicate)."""
    if _COL_W.search(name) and len(shape) == 2:
        return P(None, "tp")
    if _COL_B.search(name) and len(shape) == 1:
        return P("tp")
    if _ROW_W.search(name) and len(shape) == 2:
        return P("tp", None)
    if _EMB_W.search(name) and len(shape) == 2:
        return P("tp", None)
    return None


def _divisible(dim: int, mesh: Mesh, axis: str) -> bool:
    n = mesh.shape.get(axis, 1)
    return n > 1 and dim % n == 0


def apply_fsdp(spec: Optional[P], shape, mesh: Mesh, axis: str = "dp"
               ) -> Optional[P]:
    """Additionally shard the largest un-sharded dim over `axis` (ZeRO-3).

    P(None, 'tp') on (H, 3H) -> P('dp', 'tp'); P() on (V, H) -> P('dp', None).
    Dims that don't divide evenly stay replicated (XLA requires even tiles
    only per-shard padding; keep it simple and skip).
    """
    entries = list(spec) if spec is not None else [None] * len(shape)
    while len(entries) < len(shape):
        entries.append(None)
    # choose the largest free dim that divides
    best, best_dim = -1, -1
    for i, (e, d) in enumerate(zip(entries, shape)):
        if e is None and _divisible(d, mesh, axis) and d > best_dim:
            best, best_dim = i, d
    if best < 0:
        return spec
    entries[best] = axis
    return P(*entries)


def param_specs(names_shapes: Dict[str, tuple], mesh: Mesh,
                tensor_parallel: bool = False, fsdp: bool = False,
                custom_rule: Optional[Callable] = None,
                expert_parallel: bool = False) -> Dict[str, P]:
    """Resolve a PartitionSpec per parameter name."""
    specs = {}
    for name, shape in names_shapes.items():
        spec = None
        if custom_rule is not None:
            spec = custom_rule(name, shape)
        if spec is None and expert_parallel and mesh.shape.get("ep", 1) > 1:
            spec = ep_spec(name, shape)
            if spec is not None and not _divisible(shape[0], mesh, "ep"):
                spec = None
        if spec is None and tensor_parallel and mesh.shape.get("tp", 1) > 1:
            spec = tp_spec(name, shape)
            # tp spec only valid if the sharded dim divides
            if spec is not None:
                ok = all(e is None or _divisible(d, mesh, e)
                         for e, d in zip(tuple(spec) + (None,) * len(shape),
                                         shape))
                if not ok:
                    spec = None
        if fsdp:
            spec = apply_fsdp(spec, shape, mesh)
        specs[name] = spec if spec is not None else P()
    return specs


def shardings_of(specs: Dict[str, P], mesh: Mesh
                 ) -> Dict[str, NamedSharding]:
    return {k: NamedSharding(mesh, v) for k, v in specs.items()}


def state_sharding_like(param_shape, param_sharding: NamedSharding, leaf
                        ) -> NamedSharding:
    """Optimizer-state leaves inherit their parameter's sharding when shapes
    match (adam moments) and are replicated otherwise (beta-pow scalars)."""
    if hasattr(leaf, "shape") and tuple(leaf.shape) == tuple(param_shape):
        return param_sharding
    return NamedSharding(param_sharding.mesh, P())
