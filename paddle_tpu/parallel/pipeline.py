"""Pipeline parallelism: GPipe and 1F1B schedules over the "pp" mesh axis.

Reference: PipelineOptimizer splits the program by device_guard annotations,
inserts send_v2/recv_v2 p2p ops, and runs a fwd-all-then-bwd-all microbatch
loop in C++ SectionWorker (python/paddle/fluid/optimizer.py:3693,3713-3731;
paddle/fluid/framework/section_worker.cc:44,61-110).

TPU-native: no program splitting.  Identical transformer blocks are stacked
on a leading axis sharded P("pp"); the tick loop is a `lax.fori_loop` whose
stage→stage handoff is `lax.ppermute` over ICI, all inside one `shard_map`
under `jit`.

Two schedules:
- "gpipe" (default): fwd-all-then-bwd-all.  Because ppermute/psum are
  differentiable, `jax.grad` of the pipelined forward IS the backward
  pipeline — the reference's hand-built SectionWorker bwd falls out of
  autodiff.  The head/loss runs AFTER the loop over all microbatches at
  once: collected outputs are `psum_scatter`ed across pp so every rank
  head-computes only n_micro/n_stages microbatches (a p-fold dedup vs
  broadcasting; falls back to broadcast when pp doesn't divide n_micro).
- "1f1b": one-forward-one-backward (the schedule the reference's
  interleaved SectionWorker family targets).  Hand-scheduled combined
  ticks: tick t runs fwd of microbatch (t - stage), seeds the head vjp on
  the stage that just finished, and runs bwd of microbatch
  (t - 2(p-1) + stage) by RECOMPUTING the stage from a stashed input
  activation (FlashAttention-style recompute-bwd, `jax.vjp` per stage per
  tick).  PER-LAYER activations in flight are bounded by the stash (2p-1
  microbatch inputs) instead of growing with n_micro like
  autodiff-of-GPipe.  (The embedded inputs, their cotangent buffer and
  the parameter-grad accumulators still scale with n_micro/model size —
  the bound covers the dominant per-stage trajectory term only.)

Layout: model blocks must be structurally identical (true for GPTBlock /
BertLayer).  n_layers = n_stages * layers_per_stage; leaf shapes go from
(n_layers, ...) to (n_stages, layers_per_stage, ...) with axis 0 sharded.
"""
from __future__ import annotations

import re
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor, unwrap
from ..jit import functional_call, state_arrays
from ..nn.layer_base import Layer


def stack_block_params(state: Dict[str, jax.Array], block_re: str
                       ) -> tuple:
    """Split a flat state dict into (stacked_blocks, rest).

    block_re must capture the layer index as group 1, e.g.
    r"gpt\\.blocks\\.(\\d+)\\.(.*)" — remaining suffix as group 2.
    stacked_blocks maps suffix -> array with leading layer axis.
    """
    pat = re.compile(block_re)
    per_layer: Dict[int, Dict[str, jax.Array]] = {}
    rest = {}
    for k, v in state.items():
        m = pat.match(k)
        if m:
            per_layer.setdefault(int(m.group(1)), {})[m.group(2)] = v
        else:
            rest[k] = v
    if not per_layer:
        raise ValueError(f"no params matched block pattern {block_re!r}")
    n = len(per_layer)
    suffixes = sorted(per_layer[0])
    stacked = {s: jnp.stack([per_layer[i][s] for i in range(n)])
               for s in suffixes}
    return stacked, rest


def unstack_block_params(stacked: Dict[str, jax.Array], prefix_fmt: str
                         ) -> Dict[str, jax.Array]:
    """Inverse of stack_block_params: prefix_fmt like 'gpt.blocks.{}.{}'."""
    out = {}
    for suffix, arr in stacked.items():
        for i in range(arr.shape[0]):
            out[prefix_fmt.format(i, suffix)] = arr[i]
    return out


class PipelinedTrainStep:
    """GPipe train step for block-stacked transformer LMs (GPT family).

    step(input_ids, labels) -> loss.  Mesh must carry a "pp" axis; "dp" is
    composed automatically (batch axis sharded over dp inside the same
    shard_map).  Embedding/head params are replicated across stages.
    """

    def __init__(self, model: Layer, optimizer, mesh: Mesh,
                 block_re: str, block_module: Layer,
                 embed_fn: Callable, head_loss_fn: Callable,
                 n_micro: int = 4, remat: bool = True,
                 schedule: str = "gpipe"):
        if schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"unknown pipeline schedule {schedule!r}")
        self.model = model
        self.optimizer = optimizer
        self.mesh = mesh
        self.block_re = block_re
        self.block_module = block_module
        self.embed_fn = embed_fn
        self.head_loss_fn = head_loss_fn
        self.n_micro = n_micro
        self.remat = remat
        self.schedule = schedule
        self.n_stages = mesh.shape["pp"]
        self.dp = mesh.shape.get("dp", 1)
        self._compiled = None
        self._opt_state = None
        sd = model.state_dict()
        self._trainable = {k for k, v in sd.items()
                           if getattr(v, "trainable", False)}

    # -- param plumbing ------------------------------------------------------
    def _split_state(self):
        state = state_arrays(self.model)
        stacked, rest = stack_block_params(state, self.block_re)
        n_layers = next(iter(stacked.values())).shape[0]
        if n_layers % self.n_stages:
            raise ValueError(
                f"{n_layers} layers not divisible by {self.n_stages} stages")
        lps = n_layers // self.n_stages
        staged = {k: v.reshape((self.n_stages, lps) + v.shape[1:])
                  for k, v in stacked.items()}
        # per-suffix trainability: a stacked leaf is updated only if every
        # layer's entry is a trainable Parameter (buffers and frozen params
        # stay fixed, matching TrainStep/ShardedTrainStep semantics)
        pat = re.compile(self.block_re)
        by_suffix = {}
        for k in state:
            m = pat.match(k)
            if m:
                by_suffix.setdefault(m.group(2), []).append(k in self._trainable)
        self._staged_trainable = {s: all(v) for s, v in by_suffix.items()}
        return staged, rest, lps

    def _block_apply(self, params_one_layer, h):
        """Run one block functionally: params_one_layer maps suffix->array."""
        out = functional_call(self.block_module, params_one_layer,
                              Tensor(h), training=True)
        return out

    def _run_stage(self, params, h, key, lps):
        """One stage's lps-layer scan (shared by both schedules — the rng
        fold and remat policy MUST be identical between them)."""
        from ..core import rng as _rng

        def layer(h, xs):
            p, i = xs
            with _rng.key_ctx(jax.random.fold_in(key, i)):
                out = self._block_apply(p, h)
            return unwrap(out), None
        body = jax.checkpoint(layer) if self.remat else layer
        h, _ = lax.scan(body, h, (params, jnp.arange(lps)))
        return h

    # -- pipelined loss ------------------------------------------------------
    def _pipeline_loss(self, staged, rest, ids, labels, rng_key, lps):
        """Runs INSIDE shard_map: staged leaves arrive as (1, lps, ...) —
        this stage's params; ids/labels are this dp-shard's microbatched
        inputs (n_micro, mb, s)."""
        from ..core import rng as _rng
        staged = {k: v[0] for k, v in staged.items()}  # drop pp block dim
        n_micro = self.n_micro
        n_stages = self.n_stages
        stage = lax.axis_index("pp")

        def run_stage(h, key):
            return self._run_stage(staged, h, key, lps)

        with _rng.key_ctx(jax.random.fold_in(rng_key, 2 ** 20)):
            embedded = self.embed_fn(rest, ids)  # (n_micro, mb, s, h)
        mb_shape = embedded.shape[1:]
        # loop carries become device-varying (ppermute/axis_index); build them
        # as fresh invariant zeros then mark varying over every mesh axis so
        # shard_map's VMA check accepts the fori_loop carry typing
        axes = tuple(self.mesh.axis_names)
        buf = lax.pcast(jnp.zeros(mb_shape, embedded.dtype), axes,
                        to="varying")
        outs = lax.pcast(jnp.zeros(embedded.shape, embedded.dtype), axes,
                         to="varying")
        T = n_micro + n_stages - 1

        def tick(t, carry):
            buf, outs = carry
            # stage 0 injects microbatch t (clamped); others consume buf
            inj = lax.dynamic_index_in_dim(
                embedded, jnp.clip(t, 0, n_micro - 1), axis=0,
                keepdims=False)
            h_in = jnp.where(stage == 0, inj, buf)
            key = jax.random.fold_in(rng_key, t * (n_stages + 1) + stage)
            h_out = run_stage(h_in, key)
            # last stage finished microbatch (t - n_stages + 1): record it
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            valid = (t - (n_stages - 1)) >= 0
            cur = lax.dynamic_index_in_dim(outs, out_idx, axis=0,
                                           keepdims=False)
            outs = lax.dynamic_update_index_in_dim(
                outs, jnp.where(valid, h_out, cur), out_idx, axis=0)
            # hand off to next stage (ring; last->0 wraps, ignored by stage 0)
            buf = lax.ppermute(
                h_out, "pp",
                [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return (buf, outs)

        buf, outs = lax.fori_loop(0, T, tick, (buf, outs),
                                  unroll=False)
        masked = jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs))
        if n_micro % n_stages == 0:
            # scatter the collected outputs across pp: every rank runs the
            # head+loss on n_micro/p microbatches instead of all of them
            # (the r1 weakness: head compute was replicated on every rank)
            shard = lax.psum_scatter(masked, "pp", scatter_dimension=0,
                                     tiled=True)
            mpp = n_micro // n_stages
            lbl = lax.dynamic_slice_in_dim(labels, stage * mpp, mpp, axis=0)
            flat_h = shard.reshape((-1,) + shard.shape[2:])
            flat_l = lbl.reshape((-1,) + lbl.shape[2:])
            with _rng.key_ctx(jax.random.fold_in(rng_key, 2 ** 20 + 1)):
                loss = self.head_loss_fn(rest, flat_h, flat_l)
            loss = lax.psum(loss, "pp") / n_stages
        else:  # fallback: broadcast and compute everywhere
            outs = lax.psum(masked, "pp")
            flat_h = outs.reshape((-1,) + outs.shape[2:])
            flat_l = labels.reshape((-1,) + labels.shape[2:])
            with _rng.key_ctx(jax.random.fold_in(rng_key, 2 ** 20 + 1)):
                loss = self.head_loss_fn(rest, flat_h, flat_l)
        return lax.pmean(loss, "dp")

    # -- 1F1B: hand-scheduled fwd/bwd interleave with recompute backward ----
    def _pipeline_1f1b(self, staged, rest, ids, labels, rng_key, lps):
        """Runs INSIDE shard_map.  Returns (loss, g_staged, g_rest) — the
        backward is hand-built (jax.vjp per stage per tick over a stashed
        input activation), so in-flight activation memory is bounded by the
        2p-1 stash slots instead of the whole fwd trajectory."""
        from ..core import rng as _rng
        staged = {k: v[0] for k, v in staged.items()}  # drop pp block dim
        m = self.n_micro
        p = self.n_stages
        stage = lax.axis_index("pp")
        is_last = stage == p - 1
        is_first = stage == 0
        fwd_perm = [(i, (i + 1) % p) for i in range(p)]
        bwd_perm = [(i, (i - 1) % p) for i in range(p)]

        def run_stage(params, h, key):
            return self._run_stage(params, h, key, lps)

        def head_vjp(h, lbl, key):
            def fn(r, hh):
                with _rng.key_ctx(key):
                    return self.head_loss_fn(r, hh, lbl)
            loss, pull = jax.vjp(fn, rest, h)
            d_rest, dh = pull(jnp.ones((), loss.dtype) / m)
            return loss, d_rest, dh

        with _rng.key_ctx(jax.random.fold_in(rng_key, 2 ** 20)):
            embedded, embed_pull = jax.vjp(
                lambda r: self.embed_fn(r, ids), rest)
        mb_shape = embedded.shape[1:]
        axes = tuple(self.mesh.axis_names)

        def vary(x):
            return lax.pcast(x, axes, to="varying")

        n_slots = 2 * p - 1
        zeros_g_staged = jax.tree_util.tree_map(
            lambda v: vary(jnp.zeros_like(v, dtype=jnp.float32)), staged)
        zeros_g_rest = jax.tree_util.tree_map(
            lambda v: vary(jnp.zeros_like(v, dtype=jnp.float32)), rest)
        carry0 = dict(
            fwd_buf=vary(jnp.zeros(mb_shape, embedded.dtype)),
            bwd_buf=vary(jnp.zeros(mb_shape, jnp.float32)),
            stash=vary(jnp.zeros((n_slots,) + mb_shape, embedded.dtype)),
            d_emb=vary(jnp.zeros(embedded.shape, jnp.float32)),
            g_staged=zeros_g_staged,
            g_rest=zeros_g_rest,
            loss=vary(jnp.zeros((), jnp.float32)),
        )
        T = m + 2 * (p - 1)

        def stage_key(j):
            return jax.random.fold_in(rng_key, j * p + stage)

        def tick(t, c):
            # ---- forward: stage s runs microbatch f = t - s ----
            f = t - stage
            f_ok = jnp.logical_and(f >= 0, f < m)
            f_c = jnp.clip(f, 0, m - 1)
            inj = lax.dynamic_index_in_dim(embedded, jnp.clip(t, 0, m - 1),
                                           axis=0, keepdims=False)
            h_in = jnp.where(is_first, inj, c["fwd_buf"])
            # stash the input activation for the recompute backward
            slot_f = f_c % n_slots
            old = lax.dynamic_index_in_dim(c["stash"], slot_f, axis=0,
                                           keepdims=False)
            stash = lax.dynamic_update_index_in_dim(
                c["stash"], jnp.where(f_ok, h_in, old), slot_f, axis=0)
            h_out = run_stage(staged, h_in, stage_key(f_c))
            # ---- head: the mb that just LEFT the last stage seeds its bwd
            fl = t - (p - 1)
            fl_ok = jnp.logical_and(fl >= 0, fl < m)
            fl_c = jnp.clip(fl, 0, m - 1)
            lbl = lax.dynamic_index_in_dim(labels, fl_c, axis=0,
                                           keepdims=False)
            hkey = jax.random.fold_in(rng_key, 2 ** 20 + 1 + fl_c)
            loss_f, d_rest_f, dh_f = head_vjp(h_out, lbl, hkey)
            take_head = jnp.logical_and(is_last, fl_ok)
            loss = c["loss"] + jnp.where(take_head, loss_f / m, 0.0)
            g_rest = jax.tree_util.tree_map(
                lambda a, d: a + jnp.where(take_head, d, 0.0),
                c["g_rest"], d_rest_f)
            # ---- backward: stage s runs microbatch j = t - 2(p-1) + s ----
            j = t - 2 * (p - 1) + stage
            j_ok = jnp.logical_and(j >= 0, j < m)
            j_c = jnp.clip(j, 0, m - 1)
            h_in_b = lax.dynamic_index_in_dim(stash, j_c % n_slots, axis=0,
                                              keepdims=False)
            dh_in = jnp.where(is_last, dh_f, c["bwd_buf"])
            _, stage_pull = jax.vjp(
                lambda pr, hh: run_stage(pr, hh, stage_key(j_c)),
                staged, h_in_b)
            d_params, dh_prev = stage_pull(dh_in.astype(h_out.dtype))
            g_staged = jax.tree_util.tree_map(
                lambda a, d: a + jnp.where(j_ok, d, 0.0),
                c["g_staged"], d_params)
            # stage 0's input cotangent is the embedding grad for mb j
            old_de = lax.dynamic_index_in_dim(c["d_emb"], j_c, axis=0,
                                              keepdims=False)
            dep = jnp.where(jnp.logical_and(is_first, j_ok),
                            dh_prev.astype(jnp.float32), old_de)
            d_emb = lax.dynamic_update_index_in_dim(c["d_emb"], dep, j_c,
                                                    axis=0)
            # ---- handoffs ----
            return dict(
                fwd_buf=lax.ppermute(h_out, "pp", fwd_perm),
                bwd_buf=lax.ppermute(dh_prev.astype(jnp.float32), "pp",
                                     bwd_perm),
                stash=stash, d_emb=d_emb, g_staged=g_staged, g_rest=g_rest,
                loss=loss)

        c = lax.fori_loop(0, T, tick, carry0, unroll=False)
        # embedding pullback (valid d_emb only on stage 0; zeros elsewhere)
        (g_rest_embed,) = embed_pull(c["d_emb"].astype(embedded.dtype))
        g_rest = jax.tree_util.tree_map(
            lambda a, b: a + b.astype(jnp.float32), c["g_rest"], g_rest_embed)
        # reduce: rest grads live partly on stage 0 (embed) and stage p-1
        # (head) -> psum over pp; all grads dp-averaged
        g_rest = jax.tree_util.tree_map(
            lambda g: lax.pmean(lax.psum(g, "pp"), "dp"), g_rest)
        g_staged = jax.tree_util.tree_map(
            lambda g: lax.pmean(g, "dp"), c["g_staged"])
        loss = lax.pmean(lax.psum(c["loss"], "pp"), "dp")
        return loss, g_staged, g_rest

    # -- compiled step -------------------------------------------------------
    def _build(self, staged_sh, rest_sh, lps):
        opt = self.optimizer
        mesh = self.mesh
        n_micro = self.n_micro
        trainable = self._trainable

        staged_spec = jax.tree_util.tree_map(lambda _: P("pp"), staged_sh)
        rest_spec = jax.tree_util.tree_map(lambda _: P(), rest_sh)

        def loss_fn(staged, rest, ids, labels, rng_key):
            fn = jax.shard_map(
                lambda s, r, i, l, k: self._pipeline_loss(
                    s, r, i, l, k, lps),
                mesh=mesh,
                in_specs=(staged_spec, rest_spec,
                          P(None, "dp"), P(None, "dp"), P()),
                out_specs=P(),
                # the loss is psum("pp")+pmean("dp")-reduced — replicated in
                # value; the VMA type system can't prove it, so skip the check
                check_vma=False)
            return fn(staged, rest, ids, labels, rng_key)

        def loss_and_grads_1f1b(staged, rest, ids, labels, rng_key):
            def body(s, r, i, l, k):
                loss, g_staged, g_rest = self._pipeline_1f1b(
                    s, r, i, l, k, lps)
                # re-add the pp block dim shard_map expects for P("pp") outs
                g_staged = jax.tree_util.tree_map(lambda g: g[None], g_staged)
                return loss, g_staged, g_rest
            fn = jax.shard_map(
                body, mesh=mesh,
                in_specs=(staged_spec, rest_spec,
                          P(None, "dp"), P(None, "dp"), P()),
                out_specs=(P(), staged_spec, rest_spec),
                check_vma=False)
            return fn(staged, rest, ids, labels, rng_key)

        from ..optimizer.functional import apply_updates, decay_flags
        # staged keys are block-relative suffixes ("qkv.bias"), which still
        # carry the bias/norm markers apply_decay_param_fun filters on
        decay_staged = decay_flags(opt, staged_sh)
        decay_rest = decay_flags(opt, rest_sh)

        def step(staged, rest, opt_state, step_no, lr, rng_key, ids, labels):
            # microbatch the global batch: (B, S) -> (n_micro, mb, S)
            b = ids.shape[0]
            mb = b // n_micro
            ids_m = ids.reshape((n_micro, mb) + ids.shape[1:])
            lbl_m = labels.reshape((n_micro, mb) + labels.shape[1:])
            if self.schedule == "1f1b":
                loss, g_staged, g_rest = loss_and_grads_1f1b(
                    staged, rest, ids_m, lbl_m, rng_key)
                g_staged = jax.tree_util.tree_map(
                    lambda g, v: g.astype(v.dtype), g_staged, staged)
                g_rest = jax.tree_util.tree_map(
                    lambda g, v: g.astype(v.dtype), g_rest, rest)
            else:
                loss, (g_staged, g_rest) = jax.value_and_grad(
                    loss_fn, argnums=(0, 1))(staged, rest, ids_m, lbl_m,
                                             rng_key)
            opt_staged, opt_rest = opt_state
            g_staged = {k: v for k, v in g_staged.items()
                        if self._staged_trainable.get(k, True)}
            new_staged, new_opt_staged = apply_updates(
                opt, staged, g_staged, opt_staged, lr, step_no, decay_staged)
            g_rest = {k: v for k, v in g_rest.items() if k in trainable}
            new_rest, new_opt_rest = apply_updates(
                opt, rest, g_rest, opt_rest, lr, step_no, decay_rest)
            return new_staged, new_rest, (new_opt_staged, new_opt_rest), loss

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def init(self):
        staged, rest, lps = self._split_state()
        self._lps = lps
        # place: staged over pp, rest replicated
        staged = {k: jax.device_put(v, NamedSharding(self.mesh, P("pp")))
                  for k, v in staged.items()}
        rest = {k: jax.device_put(v, NamedSharding(self.mesh, P()))
                for k, v in rest.items()}
        self._staged, self._rest = staged, rest
        opt_staged = {k: self.optimizer.init_state(v)
                      for k, v in staged.items()}
        opt_rest = {k: self.optimizer.init_state(v)
                    for k, v in rest.items() if k in self._trainable}
        self._opt_state = (opt_staged, opt_rest)

    def __call__(self, input_ids, labels):
        if self._opt_state is None:
            self.init()
        if self._compiled is None:
            self._compiled = self._build(self._staged, self._rest, self._lps)
        self.optimizer._step_count += 1
        from ..core import rng as _rng
        rep = NamedSharding(self.mesh, P())
        lr = jax.device_put(jnp.asarray(self.optimizer.get_lr(), jnp.float32),
                            rep)
        step_no = jax.device_put(
            jnp.asarray(self.optimizer._step_count, jnp.int32), rep)
        rng_key = jax.device_put(_rng.next_key(), rep)
        ids = jax.device_put(unwrap(input_ids), rep)
        labels = jax.device_put(unwrap(labels), rep)
        self._staged, self._rest, self._opt_state, loss = self._compiled(
            self._staged, self._rest, self._opt_state, step_no, lr, rng_key,
            ids, labels)
        return Tensor(loss)

    def memory_stats(self, input_ids, labels):
        """AOT-compile the step for this batch and return XLA's buffer
        assignment (CompiledMemoryStats) WITHOUT executing — the measured
        form of the 1F1B claim that in-flight activations are bounded by
        the 2p-1 stash instead of the whole GPipe trajectory.

        temp_bytes is the peak of XLA's temp allocation (activations,
        stashes, scan carries); argument/output/alias bytes cover
        params+opt state and are schedule-independent.
        """
        if self._opt_state is None:
            self.init()
        if self._compiled is None:
            self._compiled = self._build(self._staged, self._rest, self._lps)
        rep = NamedSharding(self.mesh, P())
        lr = jax.device_put(jnp.asarray(self.optimizer.get_lr(), jnp.float32),
                            rep)
        step_no = jax.device_put(jnp.asarray(1, jnp.int32), rep)
        # fixed dummy key: a diagnostic must not advance the training RNG
        # stream (it never executes the step, only compiles it)
        rng_key = jax.device_put(jax.random.PRNGKey(0), rep)
        ids = jax.device_put(unwrap(input_ids), rep)
        labels = jax.device_put(unwrap(labels), rep)
        compiled = self._compiled.lower(
            self._staged, self._rest, self._opt_state, step_no, lr, rng_key,
            ids, labels).compile()
        ma = compiled.memory_analysis()
        return {
            "temp_bytes": int(ma.temp_size_in_bytes),
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
        }

    def sync_to_model(self):
        """Write pipeline params back into the Layer (for save/eval)."""
        sd = self.model.state_dict()
        flat = dict(self._rest)
        stacked = {k: v.reshape((-1,) + v.shape[2:])
                   for k, v in self._staged.items()}
        pat = re.compile(self.block_re)
        for k, t in sd.items():
            m = pat.match(k)
            if m:
                arr = stacked[m.group(2)][int(m.group(1))]
            else:
                arr = flat[k]
            # fetch off the mesh so eager single-device ops can consume it
            t._set_data(jnp.asarray(jax.device_get(arr)))


def gpt_pipeline_step(model, optimizer, mesh, n_micro=4, remat=True,
                      schedule="gpipe"):
    """Wire a models.GPTForPretraining into PipelinedTrainStep."""
    from ..models.gpt import GPTBlock
    from ..nn import functional as F
    cfg = model.gpt.config
    block = GPTBlock(cfg)

    def embed_fn(rest, ids_m):
        # ids_m: (n_micro, mb, s) — embed all microbatches at once
        n_micro, mb, s = ids_m.shape
        flat = ids_m.reshape(n_micro * mb, s)
        pos = jnp.arange(s, dtype=jnp.int32)
        we = rest["gpt.word_embeddings.weight"]
        pe = rest["gpt.position_embeddings.weight"]
        h = we[flat] + pe[pos][None, :, :]
        # embedding dropout, matching GPTModel.embed (caller provides key_ctx)
        p = cfg.hidden_dropout_prob
        if p > 0.0:
            from ..core import rng as _rng
            keep = jax.random.bernoulli(_rng.next_key(), 1.0 - p, h.shape)
            h = jnp.where(keep, h / (1.0 - p), 0.0)
        return h.reshape(n_micro, mb, s, -1)

    def head_loss_fn(rest, h, labels):
        g = rest["gpt.ln_f.weight"]
        b = rest["gpt.ln_f.bias"]
        mu = h.mean(-1, keepdims=True)
        var = ((h - mu) ** 2).mean(-1, keepdims=True)
        h = (h - mu) / jnp.sqrt(var + 1e-5) * g + b
        logits = jnp.einsum("bsh,vh->bsv", h,
                            rest["gpt.word_embeddings.weight"])
        logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32),
                                 axis=-1)[..., 0]
        return -ll.mean()

    return PipelinedTrainStep(
        model, optimizer, mesh,
        block_re=r"gpt\.blocks\.(\d+)\.(.*)",
        block_module=block,
        embed_fn=embed_fn, head_loss_fn=head_loss_fn,
        n_micro=n_micro, remat=remat, schedule=schedule)
