"""LocalSGD training step.

Reference: python/paddle/distributed/fleet/meta_optimizers/localsgd_optimizer.py:1
— every worker applies its LOCAL gradient for k_steps steps (no allreduce),
then workers synchronize by averaging parameters.  Cuts collective traffic by
k at the price of staleness; with SGD and k=1 it is mathematically identical
to synchronous data parallelism.

TPU-native design: each dp replica's divergent weights are one slice of a
leading replica axis — every param is stored stacked as (dp, *shape) sharded
P("dp"), so "a worker's copy" is just its device's shard.  The local step
runs inside shard_map (no implicit GSPMD gradient reduction can happen), and
the periodic sync is a single fused pmean over the stacked axis.  The
adaptive variant (begin syncing every step once k_steps decays) can be had
by passing k_steps=1.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor, unwrap
from ..jit import state_arrays, forward_loss
from .mesh import get_mesh


class LocalSGDTrainStep:
    """step(*batch) -> mean loss across replicas.

    Params live stacked (dp, *shape); `sync()` (called automatically every
    k_steps) averages them across replicas.  `model.state_dict()` is kept
    holding replica 0's view after every call so eval code sees one model.
    """

    def __init__(self, model, loss_fn: Callable, optimizer, k_steps: int = 4,
                 mesh: Optional[Mesh] = None, amp_level=None,
                 amp_dtype="bfloat16"):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.k_steps = int(k_steps)
        self.mesh = mesh or get_mesh(create_default=True)
        self.dp = self.mesh.shape["dp"]
        self._amp = amp_level
        self._amp_dtype = amp_dtype
        sd = model.state_dict()
        self._trainable = {k for k, v in sd.items()
                           if getattr(v, "trainable", False)}
        self._stack_sharding = NamedSharding(self.mesh, P("dp"))
        self._batch_sharding = NamedSharding(self.mesh, P("dp"))
        self._stacked = None     # name -> (dp, *shape)
        self._opt_state = None
        self._compiled = None
        self._since_sync = 0

    # -- placement -----------------------------------------------------------
    def _place(self):
        state = state_arrays(self.model)
        self._stacked = {
            k: jax.device_put(jnp.broadcast_to(v, (self.dp,) + v.shape),
                              self._stack_sharding)
            for k, v in state.items()}
        self._opt_state = {
            k: jax.tree_util.tree_map(
                lambda s: jax.device_put(
                    jnp.broadcast_to(s, (self.dp,) + s.shape),
                    self._stack_sharding),
                self.optimizer.init_state(state[k]))
            for k in self._trainable}

    # -- compiled local step -------------------------------------------------
    def _build(self, n_batch):
        from ..optimizer.functional import apply_updates, decay_flags
        opt = self.optimizer
        trainable = self._trainable
        decay = decay_flags(opt, trainable)
        mesh = self.mesh

        def local(params, opt_state, step_no, lr, rng_key, batch):
            # one replica's view: drop the stacked axis
            params = {k: v[0] for k, v in params.items()}
            opt_state = jax.tree_util.tree_map(lambda s: s[0], opt_state)
            key = jax.random.fold_in(rng_key, jax.lax.axis_index("dp"))

            def loss_of(tp):
                full = dict(params)
                full.update(tp)
                return forward_loss(self.model, self.loss_fn, full, batch,
                                    key, self._amp, self._amp_dtype)

            tp = {k: v for k, v in params.items() if k in trainable}
            loss, grads = jax.value_and_grad(loss_of)(tp)
            new_params, new_opt = apply_updates(
                opt, params, grads, opt_state, lr, step_no, decay)
            new_params = {k: v[None] for k, v in new_params.items()}
            new_opt = jax.tree_util.tree_map(lambda s: s[None], new_opt)
            return new_params, new_opt, jax.lax.pmean(loss, "dp")

        step = shard_map(
            local, mesh=mesh,
            in_specs=(P("dp"), P("dp"), P(), P(), P(),
                      tuple(P("dp") for _ in range(n_batch))),
            out_specs=(P("dp"), P("dp"), P()),
            check_rep=False)
        return jax.jit(step, donate_argnums=(0, 1))

    def _build_sync(self):
        def sync(stacked, opt_state):
            avg = {k: jnp.mean(v.astype(jnp.float32), axis=0,
                               keepdims=True).astype(v.dtype)
                   for k, v in stacked.items()}
            avg = {k: jnp.broadcast_to(v, stacked[k].shape)
                   for k, v in avg.items()}
            return avg, opt_state
        return jax.jit(sync, donate_argnums=(0,),
                       out_shardings=(self._stack_sharding, None))

    def sync(self):
        """Average parameters across replicas (the LocalSGD allreduce) and
        refresh the model's tensors with the synced weights."""
        if self._stacked is None:
            return
        if getattr(self, "_compiled_sync", None) is None:
            self._compiled_sync = self._build_sync()
        self._stacked, self._opt_state = self._compiled_sync(
            self._stacked, self._opt_state)
        self._since_sync = 0
        # eval view refreshed only at sync points: between syncs replicas
        # legitimately diverge and a per-step slice copy would be waste
        sd = self.model.state_dict()
        for k, v in self._stacked.items():
            sd[k]._set_data(v[0])

    def __call__(self, *batch):
        if self._stacked is None:
            self._place()
        if self._compiled is None:
            self._compiled = self._build(len(batch))
        self.optimizer._step_count += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        step_no = jnp.asarray(self.optimizer._step_count, jnp.int32)
        from ..core import rng as _rng
        key = _rng.next_key()
        raw = tuple(jax.device_put(unwrap(b), self._batch_sharding)
                    for b in batch)
        self._stacked, self._opt_state, loss = self._compiled(
            self._stacked, self._opt_state, step_no, lr, key, raw)
        self._since_sync += 1
        if self._since_sync >= self.k_steps:
            self.sync()
        return Tensor(loss)

    # -- checkpointing (same layout as ShardedTrainStep's) -------------------
    def save_checkpoint(self, directory: str, step=None, extra_meta=None):
        from ..distributed import checkpoint as dck
        if self._stacked is None:
            self._place()
        return dck.save_train_state(
            directory, self._stacked, self._opt_state,
            step if step is not None else self.optimizer._step_count,
            extra_meta, optimizer=self.optimizer)

    def restore_checkpoint(self, directory: str):
        from ..distributed import checkpoint as dck
        if self._stacked is None:
            self._place()
        shardings = {
            "params": {k: self._stack_sharding for k in self._stacked},
            "opt": jax.tree_util.tree_map(
                lambda _: self._stack_sharding, self._opt_state)}
        res = dck.restore_sharded(directory, mesh=self.mesh,
                                  shardings=shardings)
        if res is None:
            return None
        tree, step, extra = res
        self._stacked = tree["params"]
        self._opt_state = dck.merge_opt_state(self._opt_state,
                                              tree.get("opt", {}))
        meta = dck.restore_train_extras(self.optimizer, step, extra)
        sd = self.model.state_dict()
        for k, v in self._stacked.items():
            sd[k]._set_data(v[0])
        return meta
