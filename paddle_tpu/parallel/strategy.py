"""DistributedStrategy — TPU-native version of the reference's
framework/distributed_strategy.proto:112-138 (amp/recompute/sharding/
pipeline/... feature flags consumed by Fleet meta-optimizers).  Here it is a
plain dataclass: instead of rewriting programs, the flags select mesh axis
sizes + sharding rules + jit transform options in parallel.train_step.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass
class HybridConfig:
    """hybrid_configs equivalent: degree per parallelism dimension."""
    dp_degree: int = -1      # -1: fill with remaining devices
    pp_degree: int = 1
    mp_degree: int = 1       # tensor/model parallel ("tp" axis)
    sp_degree: int = 1       # sequence/context parallel ("sp" axis)
    ep_degree: int = 1       # expert parallel ("ep" axis, MoE)


@dataclass
class ShardingConfig:
    """sharding_configs: ZeRO stage (reference sharding_optimizer.py:33)."""
    stage: int = 2           # 1: opt states, 2: +grads, 3: +params (FSDP)
    degree: int = -1         # defaults to dp degree


@dataclass
class RecomputeConfig:
    checkpoints: Optional[list] = None


@dataclass
class AMPConfig:
    dtype: str = "bfloat16"   # bf16 is the TPU-native AMP dtype
    level: str = "O1"
    init_loss_scaling: float = 32768.0
    use_dynamic_loss_scaling: bool = True


@dataclass
class GradientMergeConfig:
    k_steps: int = 1
    avg: bool = True


@dataclass
class DistributedStrategy:
    """Reference: python/paddle/distributed/fleet/base/distributed_strategy.py."""
    amp: bool = False
    amp_configs: AMPConfig = field(default_factory=AMPConfig)
    recompute: bool = False
    recompute_configs: RecomputeConfig = field(default_factory=RecomputeConfig)
    sharding: bool = False
    sharding_configs: ShardingConfig = field(default_factory=ShardingConfig)
    pipeline: bool = False
    pp_micro_batches: int = 4
    gradient_merge: bool = False
    gradient_merge_configs: GradientMergeConfig = field(
        default_factory=GradientMergeConfig)
    hybrid_configs: HybridConfig = field(default_factory=HybridConfig)
    tensor_parallel: bool = False
    sequence_parallel: bool = False
    expert_parallel: bool = False
    localsgd: bool = False
    localsgd_configs: Optional[dict] = None
    lars: bool = False
    lamb: bool = False
    dgc: bool = False
    fp16_allreduce: bool = False
    find_unused_parameters: bool = False
    # custom param-sharding rule: fn(name, shape) -> PartitionSpec or None
    sharding_rule: Optional[Callable] = None

    def mesh_axes(self, n_devices: int) -> dict:
        """Resolve axis sizes for create_mesh given the device count."""
        h = self.hybrid_configs
        pp = h.pp_degree if self.pipeline else 1
        tp = h.mp_degree if self.tensor_parallel else 1
        sp = h.sp_degree if self.sequence_parallel else 1
        ep = h.ep_degree if self.expert_parallel else 1
        fixed = pp * tp * sp * ep
        if n_devices % fixed:
            raise ValueError(
                f"pp*ep*tp*sp={fixed} does not divide device count "
                f"{n_devices}")
        dp = h.dp_degree if h.dp_degree > 0 else n_devices // fixed
        if dp * fixed > n_devices:
            raise ValueError(
                f"dp*pp*ep*tp*sp={dp * fixed} exceeds device count "
                f"{n_devices}")
        return {"dp": dp, "pp": pp, "ep": ep, "tp": tp, "sp": sp}
