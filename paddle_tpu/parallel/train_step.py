"""ShardedTrainStep — one compiled SPMD training step over a device mesh.

This single class replaces the reference's entire program-rewriting
parallelism stack (SURVEY.md §2.2 meta-optimizers):
- GraphExecutionOptimizer's inserted c_allreduce_sum per grad  → batch
  sharded P("dp"): XLA emits the gradient psum itself.
- ShardingOptimizer's param→rank broadcast/allreduce rewrite
  (sharding_optimizer.py:96-118)                               → FSDP
  PartitionSpecs on params/opt states; GSPMD inserts all-gather /
  reduce-scatter.
- Megatron-style TP (absent in reference, free on TPU)         → column/row
  PartitionSpecs from parallel.sharding.
- RecomputeOptimizer (backward.py:689)                         → jax.checkpoint.
- GradientMergeOptimizer (optimizer.py:4969)                   → lax.scan over
  microbatches accumulating grads.
- AMP meta-optimizer                                           → bf16 autocast
  inside the jitted step.
All of it is one jax.jit with in/out shardings + donation.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import recompute as _recompute
from ..core.tensor import Tensor, unwrap
from ..jit import functional_call, state_arrays
from ..nn.layer_base import Layer
from . import sharding as shd
from .mesh import get_mesh
from .strategy import DistributedStrategy


class ShardedTrainStep:
    """step(*batch) -> loss; params/opt states live sharded on the mesh."""

    def __init__(self, model: Layer, loss_fn: Callable, optimizer,
                 strategy: Optional[DistributedStrategy] = None,
                 mesh: Optional[Mesh] = None,
                 batch_spec=None, guard: bool = False,
                 accum_steps: int = 1):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        # compiled finiteness guard (see jit.guard_select): bad steps are
        # skipped on-device; (grad_norm, ok) ride out on last_guard
        self._guard = bool(guard)
        self.last_guard = None
        self.strategy = strategy or DistributedStrategy()
        self.mesh = mesh or get_mesh(create_default=True)
        st = self.strategy
        self._remat = st.recompute
        self._amp = st.amp
        self._amp_dtype = st.amp_configs.dtype
        self._k_steps = (st.gradient_merge_configs.k_steps
                         if st.gradient_merge else 1)
        # accum_steps: the TrainStep-shaped spelling of the gradient-merge
        # meta-optimizer — K microbatches scanned in-program with f32
        # accumulators and one update (same knob, friendlier name)
        if int(accum_steps) < 1:
            raise ValueError("ShardedTrainStep: accum_steps must be >= 1")
        if int(accum_steps) > 1:
            if st.gradient_merge and self._k_steps != int(accum_steps):
                raise ValueError(
                    "ShardedTrainStep: accum_steps and "
                    "strategy.gradient_merge_configs.k_steps disagree "
                    f"({accum_steps} vs {self._k_steps})")
            self._k_steps = int(accum_steps)
        self.accum_steps = self._k_steps
        sd = model.state_dict()
        self._trainable = {k for k, v in sd.items()
                           if getattr(v, "trainable", False)}
        fsdp = st.sharding and st.sharding_configs.stage >= 3
        self._zero12 = st.sharding and st.sharding_configs.stage in (1, 2)
        # bf16-compressed explicit gradient allreduce: pure-DP only (the
        # reference's fp16_allreduce likewise composes with collective DP,
        # not sharding/TP)
        self._fp16_allreduce = bool(st.fp16_allreduce)
        if self._fp16_allreduce and (fsdp or st.tensor_parallel
                                     or st.sequence_parallel or st.pipeline):
            raise ValueError(
                "fp16_allreduce composes with plain DP (optionally ZeRO-1/2)"
                " only — disable sharding stage 3 / tensor_parallel /"
                " sequence_parallel / pipeline")
        self.param_specs = shd.param_specs(
            {k: tuple(v.shape) for k, v in sd.items()}, self.mesh,
            tensor_parallel=st.tensor_parallel, fsdp=fsdp,
            custom_rule=st.sharding_rule,
            expert_parallel=st.expert_parallel)
        self.param_shardings = shd.shardings_of(self.param_specs, self.mesh)
        # batch elements shard over dp on axis 0 (+ sp on seq axis 1 when
        # sequence parallel)
        if batch_spec is None:
            batch_spec = (P("dp", "sp") if st.sequence_parallel else P("dp"))
        self._batch_sharding = NamedSharding(self.mesh, batch_spec)
        self._compiled = None
        self._opt_state = None
        self._placed = False

    # -- placement -----------------------------------------------------------
    def place_params(self):
        """Move model params onto the mesh with their shardings (the analogue
        of ParallelExecutor::BCastParamsToDevices, parallel_executor.cc:637)."""
        sd = self.model.state_dict()
        for k, t in sd.items():
            t._set_data(jax.device_put(t._data, self.param_shardings[k]))
        self._placed = True

    def _opt_shardings(self, opt_state):
        sd = self.model.state_dict()
        out = {}
        for k, st in opt_state.items():
            pshard = self.param_shardings[k]
            pshape = tuple(sd[k].shape)
            if self._zero12:
                # ZeRO-1/2: moments sharded over dp even though params
                # aren't (the ShardingOptimizer memory win)
                mesh = self.mesh
                spec = shd.apply_fsdp(self.param_specs[k], pshape, mesh)
                pshard = NamedSharding(mesh, spec if spec is not None else P())
            out[k] = {n: shd.state_sharding_like(pshape, pshard, leaf)
                      for n, leaf in st.items()}
        return out

    # -- compiled step -------------------------------------------------------
    def _forward_loss(self, state, batch, rng_key=None):
        # NOTE: no return_buffer_updates here — BatchNorm running stats
        # stay frozen under the SHARDED step (per-replica batch stats
        # would need a cross-replica mean, the SyncBatchNorm contract;
        # single-device TrainStep folds them functionally since ISSUE 1)
        from ..jit import forward_loss
        return forward_loss(self.model, self.loss_fn, state, batch, rng_key,
                            "O1" if self._amp else None, self._amp_dtype)

    def _build(self, opt_shardings):
        from ..optimizer.functional import apply_updates, decay_flags
        opt = self.optimizer
        trainable = self._trainable
        decay = decay_flags(opt, trainable)
        k_steps = self._k_steps
        avg = (self.strategy.gradient_merge_configs.avg
               if self.strategy.gradient_merge else True)

        def grads_of_implicit(params, batch, rng_key):
            def loss_of(tp):
                full = dict(params)
                full.update(tp)
                return self._forward_loss(full, batch, rng_key)
            train_params = {k: v for k, v in params.items() if k in trainable}
            fn = _recompute.checkpoint(loss_of) if self._remat else loss_of
            return jax.value_and_grad(fn)(train_params)

        def grads_of_explicit(params, batch, rng_key):
            """Per-replica local grads via shard_map, a dtype-compressed
            explicit pmean over dp (fp16_allreduce meta-optimizer,
            fp16_allreduce_optimizer.py:1; bf16 is the TPU wire format).

            DDP semantics like the reference's collective mode: gradients
            are AVERAGED across replicas.  For mean-reduced losses this is
            identical to the implicit global-loss gradient; a sum-reduced
            loss differs by a factor of dp (exactly as it would under the
            reference's scaled-loss + allreduce)."""
            from jax.experimental.shard_map import shard_map

            def local(params, batch):
                key = jax.random.fold_in(rng_key,
                                         jax.lax.axis_index("dp"))

                def loss_of(tp):
                    full = dict(params)
                    full.update(tp)
                    return self._forward_loss(full, batch, key)
                tp0 = {k: v for k, v in params.items() if k in trainable}
                fn = _recompute.checkpoint(loss_of) if self._remat else loss_of
                loss, g = jax.value_and_grad(fn)(tp0)
                g = jax.tree_util.tree_map(
                    lambda x: jax.lax.pmean(
                        x.astype(jnp.bfloat16), "dp").astype(jnp.float32),
                    g)
                return jax.lax.pmean(loss, "dp"), g

            return shard_map(
                local, mesh=self.mesh,
                in_specs=(P(), tuple(P("dp") for _ in batch)),
                out_specs=(P(), P()), check_rep=False)(params, batch)

        grads_of = (grads_of_explicit if self._fp16_allreduce
                    else grads_of_implicit)

        guard = self._guard
        from ..utils import faults as _faults

        def step(params, opt_state, step_no, lr, rng_key, batch):
            if k_steps > 1:
                # gradient merge: split batch into k microbatches, scan
                def micro(carry, mb_and_i):
                    mb, i = mb_and_i
                    acc, _ = carry
                    loss, g = grads_of(params, mb,
                                       jax.random.fold_in(rng_key, i))
                    acc = jax.tree_util.tree_map(jnp.add, acc, g)
                    return (acc, loss), None
                split = tuple(
                    b.reshape((k_steps, b.shape[0] // k_steps) + b.shape[1:])
                    for b in batch)
                zero = {k: jnp.zeros(params[k].shape, jnp.float32)
                        for k in trainable}
                (grads, loss), _ = jax.lax.scan(
                    micro, (zero, jnp.zeros((), jnp.float32)),
                    (split, jnp.arange(k_steps)))
                if avg:
                    grads = jax.tree_util.tree_map(
                        lambda g: g / k_steps, grads)
            else:
                loss, grads = grads_of(params, batch, rng_key)
            # trace-time gated fault injection: identity unless armed
            grads = _faults.poison_grads(grads, step_no)
            new_params, new_opt = apply_updates(
                opt, params, grads, opt_state, lr, step_no, decay)
            if guard:
                from ..jit import guard_select
                new_params, new_opt, gnorm, ok = guard_select(
                    params, opt_state, new_params, new_opt, loss, grads)
                return new_params, new_opt, loss, gnorm, ok
            return new_params, new_opt, loss

        n_batch = self._n_batch
        in_shardings = (self.param_shardings, opt_shardings, None, None, None,
                        (self._batch_sharding,) * n_batch)
        out_shardings = (self.param_shardings, opt_shardings, None)
        if guard:
            out_shardings += (None, None)
        from ..observability import track
        return track(f"sharded_train_step:{type(self.model).__name__}",
                     jax.jit(step, in_shardings=in_shardings,
                             out_shardings=out_shardings,
                             donate_argnums=(0, 1)))

    def init_opt_state(self, state):
        return {k: self.optimizer.init_state(v) for k, v in state.items()
                if k in self._trainable}

    def _ensure_opt_shardings(self):
        """Derive optimizer-state shardings from shapes only (eval_shape) —
        no throwaway device allocation on the restore path."""
        if getattr(self, "_opt_state_shardings", None) is None:
            state = state_arrays(self.model)
            shapes = jax.eval_shape(self.init_opt_state, state)
            self._opt_state_shardings = self._opt_shardings(shapes)
        return self._opt_state_shardings

    def warmup(self, *batch) -> dict:
        """AOT-compile the sharded step for this sample batch WITHOUT
        applying an update (mirrors `TrainStep.warmup`): params are
        placed on the mesh, the optimizer state is materialized, the
        step is built and compiled — but no gradients flow, no state
        changes, and the RNG stream is not consumed.  With the
        persistent program store enabled, one worker's warmup makes the
        whole fleet's first step a disk hit."""
        import time as _time
        t0 = _time.perf_counter()
        if not self._placed:
            self.place_params()
        state = state_arrays(self.model)
        if self._opt_state is None:
            raw = self.init_opt_state(state)
            shardings = self._ensure_opt_shardings()
            self._opt_state = jax.device_put(raw, shardings)
        if self._compiled is None:
            self._n_batch = len(batch)
            self._compiled = self._build(self._opt_state_shardings)
        raw_batch = tuple(jax.device_put(unwrap(b), self._batch_sharding)
                          for b in batch)
        from ..jit import warm_step_program
        did = warm_step_program(self._compiled, state, self._opt_state,
                                self.optimizer, raw_batch)
        return {"seconds": _time.perf_counter() - t0, "compiled": did}

    def __call__(self, *batch):
        from ..jit import _step_hist
        from ..observability import span as _span
        with _span("sharded_train_step"), _step_hist().time():
            return self._call_inner(*batch)

    def _call_inner(self, *batch):
        if not self._placed:
            self.place_params()
        state = state_arrays(self.model)
        if self._opt_state is None:
            raw = self.init_opt_state(state)
            shardings = self._ensure_opt_shardings()
            self._opt_state = jax.device_put(raw, shardings)
        if self._compiled is None:
            self._n_batch = len(batch)
            self._compiled = self._build(self._opt_state_shardings)
        self.optimizer._step_count += 1
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        step_no = jnp.asarray(self.optimizer._step_count, jnp.int32)
        from ..core import rng as _rng
        rng_key = _rng.next_key()
        raw_batch = tuple(jax.device_put(unwrap(b), self._batch_sharding)
                          for b in batch)
        out = self._compiled(
            state, self._opt_state, step_no, lr, rng_key, raw_batch)
        if self._guard:
            new_state, self._opt_state, loss, gnorm, ok = out
            self.last_guard = (gnorm, ok)
        else:
            new_state, self._opt_state, loss = out
        sd = self.model.state_dict()
        for k, v in new_state.items():
            sd[k]._set_data(v)
        return Tensor(loss)

    # -- checkpointing -------------------------------------------------------
    def save_checkpoint(self, directory: str, step: Optional[int] = None,
                        extra_meta: Optional[dict] = None,
                        scaler=None, data_cursor=None) -> str:
        """Snapshot sharded params + optimizer state without host gather
        (each process writes only its own shards).  `scaler` adds the
        GradScaler loss-scaling state to the extras so an AMP resume does
        not restart dynamic loss scaling from init; `data_cursor` records
        the data-iterator position."""
        from ..distributed import checkpoint as dck
        if not self._placed:
            self.place_params()
        state = state_arrays(self.model)
        if self._opt_state is None:
            self._opt_state = jax.device_put(self.init_opt_state(state),
                                             self._ensure_opt_shardings())
        if self._k_steps > 1:
            extra_meta = dict(extra_meta or {})
            extra_meta.setdefault("accum_steps", self._k_steps)
        return dck.save_train_state(
            directory, state, self._opt_state,
            step if step is not None else self.optimizer._step_count,
            extra_meta, optimizer=self.optimizer, scaler=scaler,
            data_cursor=data_cursor)

    def restore_checkpoint(self, directory: str,
                           scaler=None) -> Optional[dict]:
        """Restore the newest checkpoint onto this step's shardings; resumes
        the optimizer step count + rng stream (+ GradScaler state when
        `scaler` is given). Returns meta or None."""
        from ..distributed import checkpoint as dck
        if not self._placed:
            self.place_params()
        res = dck.restore_sharded(
            directory, mesh=self.mesh,
            shardings={"params": self.param_shardings,
                       "opt": self._ensure_opt_shardings()})
        if res is None:
            return None
        meta, restored_opt = dck.apply_train_state(
            self.model, self.optimizer, res, scaler=scaler)
        fresh = jax.device_put(
            self.init_opt_state(state_arrays(self.model)),
            self._ensure_opt_shardings())
        self._opt_state = dck.merge_opt_state(fresh, restored_opt)
        return meta

    # -- introspection -------------------------------------------------------
    def describe_shardings(self) -> Dict[str, str]:
        return {k: str(v) for k, v in self.param_specs.items()}
