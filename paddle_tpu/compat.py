"""Fluid-1.x top-level compatibility surface + stragglers.

Reference: python/paddle/__init__.py re-exports a handful of fluid-era
names next to the 2.0 API (elementwise_*/reduce_* math aliases,
fill_constant/create_global_var/data graph builders, LoDTensor handles,
monkey_patch_* bootstrap hooks).  Users migrating from the reference hit
these immediately, so they exist here with 2.0-native semantics: LoD is
subsumed by masked-dense tensors, `data` returns an InputSpec (tracing is
the program capture), and the monkey-patchers are no-ops (Tensor carries
its operators natively).
"""
from __future__ import annotations

import jax.numpy as jnp

from .core.tensor import Tensor, unwrap
from .core import dtype as _dt

__all__ = [
    "tensordot", "has_inf", "has_nan", "elementwise_floordiv",
    "elementwise_mod", "elementwise_pow", "reduce_max", "reduce_min",
    "reduce_mean", "reduce_prod", "reduce_sum", "fill_constant",
    "create_global_var", "data", "LoDTensor", "LoDTensorArray",
    "get_tensor_from_selected_rows", "monkey_patch_math_varbase",
    "monkey_patch_variable",
]


def tensordot(x, y, axes=2, name=None):
    """paddle.tensordot (reference python/paddle/tensor/manipulation.py)."""
    if isinstance(axes, Tensor):
        axes = axes.tolist()
    if isinstance(axes, (list, tuple)) and len(axes) == 2 \
            and isinstance(axes[0], (list, tuple)):
        axes = (tuple(axes[0]), tuple(axes[1]))
    from .core.op import dispatch
    return dispatch("tensordot",
                    lambda a, b: jnp.tensordot(a, b, axes=axes), x, y)


def has_inf(x, name=None):
    from .core.op import dispatch
    return dispatch("has_inf", lambda v: jnp.any(jnp.isinf(v)), x)


def has_nan(x, name=None):
    from .core.op import dispatch
    return dispatch("has_nan", lambda v: jnp.any(jnp.isnan(v)), x)


# fluid 1.x elementwise_*/reduce_* spellings over the 2.0 ops
def elementwise_floordiv(x, y, name=None):
    from .tensor.math import floor_divide
    return floor_divide(x, y)


def elementwise_mod(x, y, name=None):
    from .tensor.math import mod
    return mod(x, y)


def elementwise_pow(x, y, name=None):
    from .tensor.math import pow as _pow
    return _pow(x, y)


def _reduce(fn_name, x, dim=None, keep_dim=False, name=None):
    from . import tensor as T
    fn = getattr(T, fn_name)
    return fn(x, axis=dim, keepdim=keep_dim)


def reduce_max(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _reduce("max", input, dim, keep_dim)


def reduce_min(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _reduce("min", input, dim, keep_dim)


def reduce_mean(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _reduce("mean", input, dim, keep_dim)


def reduce_prod(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _reduce("prod", input, dim, keep_dim)


def reduce_sum(input, dim=None, keep_dim=False, name=None):  # noqa: A002
    return _reduce("sum", input, dim, keep_dim)


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    from .tensor.creation import full
    return full(shape, value, dtype=dtype)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """A mutable named global tensor (reference layers/tensor.py
    create_global_var) — here simply a trainable=False Tensor."""
    from .tensor.creation import full
    t = full(shape, value, dtype=dtype)
    t.stop_gradient = not persistable
    if name:
        t.name = name
    return t


def data(name, shape, dtype="float32", lod_level=0):
    """Static-graph input declaration.  Tracing replaces Program
    construction, so this returns a paddle.static.InputSpec (usable with
    jit.save / to_static input_spec)."""
    from .jit import InputSpec
    return InputSpec(shape, dtype, name)


# LoD handles: LoD itself is subsumed by masked-dense tensors +
# paddle_tpu.nn.functional.sequence (SURVEY §2.1); the names remain so
# isinstance checks and annotations keep working.
LoDTensor = Tensor


class LoDTensorArray(list):
    """reference: fluid LoDTensorArray — a list of tensors."""


def get_tensor_from_selected_rows(x, name=None):
    """Densify a row-sparse gradient (reference:
    operators/get_tensor_from_selected_rows_op)."""
    from .core.selected_rows import RowSparseGrad
    if isinstance(x, RowSparseGrad):
        return Tensor(x.to_dense())
    return x if isinstance(x, Tensor) else Tensor(unwrap(x))


def monkey_patch_math_varbase():
    """no-op: Tensor defines its operators natively."""


def monkey_patch_variable():
    """no-op: tracing replaces Variable."""


def crop_tensor(x, shape=None, offsets=None, name=None):
    """fluid spelling of paddle.crop (crop_tensor_op)."""
    from .tensor.manipulation import crop
    return crop(x, shape, offsets)


def enable_dygraph(place=None):
    """no-op: eager IS the default execution mode here."""


def disable_dygraph():
    from . import enable_static
    enable_static()


def in_dygraph_mode():
    from . import in_dynamic_mode
    return in_dynamic_mode()
