"""acp-compatible auto checkpoint (reference:
fluid/incubate/checkpoint/auto_checkpoint.py:598 train_epoch_range and its
EDL env contract).

Reference env contract honored here:
  PADDLE_RUNNING_ENV=PADDLE_EDL_AUTO_CHECKPOINT   enables auto checkpoint
  PADDLE_EDL_HDFS_CHECKPOINT_PATH                 checkpoint directory
  PADDLE_JOB_ID / PADDLE_EDL_ONLY_FOR_CE_TEST     job namespacing
Outside that env the iterator degrades to a plain epoch range exactly like
the reference (which warns and `_normal_yield`s).  The save side is the
TrainStep/CheckpointManager machinery (distributed/checkpoint.py) — pass
`manager=` to bind one explicitly, or let the env build it.
"""
from __future__ import annotations

import os
import signal as _signal
import threading
import warnings

from ...distributed import checkpoint as _ck
from ...utils.monitor import stat_add as _stat_add

CONST_ACP_ENV = "PADDLE_RUNNING_ENV"
CONST_ACP_VALUE = "PADDLE_EDL_AUTO_CHECKPOINT"
CONST_CHECKPOINT_PATH = "PADDLE_EDL_HDFS_CHECKPOINT_PATH"
CONST_JOB_ID = "PADDLE_JOB_ID"


class PreemptionHandler:
    """Convert SIGTERM/SIGINT into a flag the training loop observes.

    A preempted TPU slot gets SIGTERM and a short grace period (the EDL
    contract the reference's auto-checkpoint assumes); an unhandled SIGTERM
    kills the run mid-step and loses everything since the last periodic
    save.  Installing this handler turns the signal into
    `handler.preempted() == True`: the loop checkpoints and exits cleanly
    at the next step boundary.

        with PreemptionHandler() as pre:
            for batch in loader:
                step(*batch)
                if pre.preempted():
                    step.save_checkpoint(ckpt_dir)
                    break

    Signal handlers are process-global: install from the main thread (a
    Python restriction); `uninstall()` / context exit restores whatever was
    there before.  `callback` (if given) runs inside the signal handler —
    keep it async-signal-safe-ish (set flags, no locks).
    """

    def __init__(self, signals=(_signal.SIGTERM, _signal.SIGINT),
                 callback=None):
        self._signals = tuple(signals)
        self._callback = callback
        self._flag = threading.Event()
        self._prev = {}
        self._installed = False
        self._stat_pending = False

    def _on_signal(self, signum, frame):
        # async-signal-safe: set the flag only.  No locks here — stat_add
        # takes monitor._lock, and if the signal lands while the main
        # thread holds that very lock (it's bumped per batch/save), the
        # handler would self-deadlock the grace period.  The stat is
        # recorded lock-free and folded in at the first preempted() read.
        self._flag.set()
        self._stat_pending = True
        if self._callback is not None:
            self._callback(signum)

    def install(self) -> "PreemptionHandler":
        if self._installed:
            return self
        for s in self._signals:
            self._prev[s] = _signal.signal(s, self._on_signal)
        self._installed = True
        return self

    def uninstall(self):
        if not self._installed:
            return
        for s, prev in self._prev.items():
            try:
                _signal.signal(s, prev)
            except (ValueError, TypeError):  # non-main thread / None prev
                pass
        self._prev.clear()
        self._installed = False

    def preempted(self) -> bool:
        if self._stat_pending:  # deferred from the signal handler
            self._stat_pending = False
            _stat_add("STAT_preemptions_observed")
        return self._flag.is_set()

    def clear(self):
        self._flag.clear()

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False


def _enabled() -> bool:
    return os.environ.get(CONST_ACP_ENV, "") == CONST_ACP_VALUE


def _env_manager():
    base = os.environ.get(CONST_CHECKPOINT_PATH)
    if not base:
        from ...core.errors import PreconditionNotMetError
        raise PreconditionNotMetError(
            f"[PreconditionNotMet] {CONST_ACP_ENV}={CONST_ACP_VALUE} is "
            f"set but {CONST_CHECKPOINT_PATH} is not — a cwd-relative "
            "fallback would silently lose checkpoints when the rescheduled "
            "job starts elsewhere (the reference requires the path too)")
    job = os.environ.get(CONST_JOB_ID, "default_job")
    return _ck.CheckpointManager(os.path.join(base, job))


def train_epoch_range(max_epoch_num, save_checkpoint_inter=None,
                      manager=None):
    """Resume-aware epoch iterator with the reference signature
    (auto_checkpoint.py:598).  With the EDL env set (or an explicit
    `manager` — also accepted as the second positional for continuity
    with the pre-r4 (n_epochs, manager) form), already-completed epochs —
    per the newest checkpoint's {"epoch": e} extra metadata — are
    skipped; otherwise yields the plain range like the reference's
    fallback.

    Env/manager resolution happens EAGERLY at call time (this is a plain
    function returning a generator), so misconfiguration warns/raises
    where the call is, not at first iteration."""
    if isinstance(save_checkpoint_inter, _ck.CheckpointManager):
        manager = save_checkpoint_inter  # pre-r4 positional form
    if manager is None:
        if not _enabled():
            warnings.warn(
                "auto checkpoint is OFF (set "
                f"{CONST_ACP_ENV}={CONST_ACP_VALUE} and "
                f"{CONST_CHECKPOINT_PATH}, or pass manager=): yielding a "
                "plain epoch range", stacklevel=2)
            return iter(range(max_epoch_num))
        manager = _env_manager()
    return _ck.train_epoch_range(max_epoch_num, manager)
