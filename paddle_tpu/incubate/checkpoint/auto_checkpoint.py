"""acp-compatible auto checkpoint (reference:
fluid/incubate/checkpoint/auto_checkpoint.py:598 train_epoch_range and its
EDL env contract).

Reference env contract honored here:
  PADDLE_RUNNING_ENV=PADDLE_EDL_AUTO_CHECKPOINT   enables auto checkpoint
  PADDLE_EDL_HDFS_CHECKPOINT_PATH                 checkpoint directory
  PADDLE_JOB_ID / PADDLE_EDL_ONLY_FOR_CE_TEST     job namespacing
Outside that env the iterator degrades to a plain epoch range exactly like
the reference (which warns and `_normal_yield`s).  The save side is the
TrainStep/CheckpointManager machinery (distributed/checkpoint.py) — pass
`manager=` to bind one explicitly, or let the env build it.
"""
from __future__ import annotations

import os
import warnings

from ...distributed import checkpoint as _ck

CONST_ACP_ENV = "PADDLE_RUNNING_ENV"
CONST_ACP_VALUE = "PADDLE_EDL_AUTO_CHECKPOINT"
CONST_CHECKPOINT_PATH = "PADDLE_EDL_HDFS_CHECKPOINT_PATH"
CONST_JOB_ID = "PADDLE_JOB_ID"


def _enabled() -> bool:
    return os.environ.get(CONST_ACP_ENV, "") == CONST_ACP_VALUE


def _env_manager():
    base = os.environ.get(CONST_CHECKPOINT_PATH)
    if not base:
        from ...core.errors import PreconditionNotMetError
        raise PreconditionNotMetError(
            f"[PreconditionNotMet] {CONST_ACP_ENV}={CONST_ACP_VALUE} is "
            f"set but {CONST_CHECKPOINT_PATH} is not — a cwd-relative "
            "fallback would silently lose checkpoints when the rescheduled "
            "job starts elsewhere (the reference requires the path too)")
    job = os.environ.get(CONST_JOB_ID, "default_job")
    return _ck.CheckpointManager(os.path.join(base, job))


def train_epoch_range(max_epoch_num, save_checkpoint_inter=None,
                      manager=None):
    """Resume-aware epoch iterator with the reference signature
    (auto_checkpoint.py:598).  With the EDL env set (or an explicit
    `manager` — also accepted as the second positional for continuity
    with the pre-r4 (n_epochs, manager) form), already-completed epochs —
    per the newest checkpoint's {"epoch": e} extra metadata — are
    skipped; otherwise yields the plain range like the reference's
    fallback.

    Env/manager resolution happens EAGERLY at call time (this is a plain
    function returning a generator), so misconfiguration warns/raises
    where the call is, not at first iteration."""
    if isinstance(save_checkpoint_inter, _ck.CheckpointManager):
        manager = save_checkpoint_inter  # pre-r4 positional form
    if manager is None:
        if not _enabled():
            warnings.warn(
                "auto checkpoint is OFF (set "
                f"{CONST_ACP_ENV}={CONST_ACP_VALUE} and "
                f"{CONST_CHECKPOINT_PATH}, or pass manager=): yielding a "
                "plain epoch range", stacklevel=2)
            return iter(range(max_epoch_num))
        manager = _env_manager()
    return _ck.train_epoch_range(max_epoch_num, manager)
