"""paddle.incubate.checkpoint (reference:
python/paddle/fluid/incubate/checkpoint/) — the auto-checkpoint package.
The sharded machinery lives in distributed.checkpoint and EVERY public
name there stays reachable here (module passthrough via __getattr__, so
pre-existing incubate.checkpoint.save_sharded/... calls keep working);
auto_checkpoint mirrors the reference acp module's env-driven entry."""
from ...distributed import checkpoint as _dck
from . import auto_checkpoint  # noqa: F401
from .auto_checkpoint import (PreemptionHandler,  # noqa: F401
                              train_epoch_range)


def __getattr__(name):
    return getattr(_dck, name)


def __dir__():
    return sorted(set(dir(_dck)) | {"auto_checkpoint", "train_epoch_range",
                                    "PreemptionHandler"})
