"""paddle.incubate (reference: python/paddle/incubate/__init__.py exposes
fluid.contrib.reader; fluid/incubate carries auto-checkpoint + the PS
fleet/data_generator family).  Here: reader conveniences alias the io
module (the distributed reader role is DataLoader + DistributedBatchSampler)
and checkpoint re-exports the auto-checkpoint machinery; the PS-only
data_generator/fleet halves are scoped out per SURVEY §2.3."""
from .. import io as reader  # noqa: F401
from . import checkpoint  # noqa: F401

__all__ = ["reader", "checkpoint"]
