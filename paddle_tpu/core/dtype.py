"""Dtype system.

TPU-native replacement for the reference's proto VarType dtypes and software
float16/bfloat16 emulation (reference: paddle/fluid/platform/float16.h,
platform/bfloat16.h, framework/framework.proto:107-136).  On TPU these are
hardware types handled natively by XLA, so this module is just a canonical
name <-> jnp dtype mapping plus a settable default float dtype.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtype registry: paddle-style name -> numpy/jnp dtype.
_DTYPE_MAP = {
    "float32": jnp.float32,
    "float64": jnp.float64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "uint8": jnp.uint8,
    "bool": jnp.bool_,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
}

_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "int": "int32",
    "long": "int64",
    "bf16": "bfloat16",
    "fp16": "float16",
    "fp32": "float32",
    "fp64": "float64",
}

_default_dtype = jnp.float32


def convert_dtype(dtype):
    """Normalize any dtype spec (str, np dtype, jnp dtype) to a jnp dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        name = _ALIASES.get(dtype, dtype)
        if name in _DTYPE_MAP:
            return _DTYPE_MAP[name]
        raise TypeError(f"Unsupported dtype string: {dtype!r}")
    # jnp dtypes are numpy dtypes / type classes
    try:
        return jnp.dtype(dtype)
    except TypeError:
        raise TypeError(f"Unsupported dtype: {dtype!r}")


def dtype_name(dtype) -> str:
    """Return the canonical paddle-style name for a dtype."""
    d = jnp.dtype(dtype)
    if d == jnp.bool_:
        return "bool"
    return d.name


def set_default_dtype(d):
    """Set the default float dtype used by creation ops without explicit dtype."""
    global _default_dtype
    d = convert_dtype(d)
    if jnp.dtype(d) not in (jnp.dtype(jnp.float16), jnp.dtype(jnp.bfloat16),
                            jnp.dtype(jnp.float32), jnp.dtype(jnp.float64)):
        raise TypeError("default dtype must be a floating dtype")
    _default_dtype = d


def get_default_dtype():
    return jnp.dtype(_default_dtype).name


def default_float_dtype():
    return _default_dtype


def is_floating(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.integer)


def is_complex(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.complexfloating)


def promote(*dtypes):
    return np.result_type(*[jnp.dtype(d) for d in dtypes])
