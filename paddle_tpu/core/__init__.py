from . import dtype, device, rng, op, tape  # noqa: F401
from .tensor import (Tensor, Parameter, no_grad, enable_grad,  # noqa: F401
                     is_grad_enabled, set_grad_enabled, unwrap, wrap)
from .op import (dispatch_cache_clear, dispatch_cache_stats,  # noqa: F401
                 dispatch_cache_size, set_dispatch_cache_size,
                 set_dispatch_cache_enabled)
from . import errors  # noqa: F401,E402
