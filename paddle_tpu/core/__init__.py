from . import dtype, device, rng, op, tape  # noqa: F401
from .tensor import (Tensor, Parameter, no_grad, enable_grad,  # noqa: F401
                     is_grad_enabled, set_grad_enabled, unwrap, wrap)
from . import errors  # noqa: F401,E402
