"""RNG state management.

TPU-native replacement for the reference's per-device Generator
(reference: paddle/fluid/framework/generator.cc, python/paddle/fluid/generator.py).
JAX randomness is functional (explicit PRNG keys); for paddle-API parity we keep
a global generator that owns a key and splits a fresh subkey per draw.  The
functional training path should instead thread keys explicitly (see
`paddle_tpu.jit`): this global state is only touched at eager op dispatch, so it
never ends up baked into a compiled program.
"""
from __future__ import annotations

import threading

import jax


class Generator:
    """A stateful PRNG: owns a key, hands out fresh subkeys."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        with getattr(self, "_lock", threading.Lock()):
            self._seed = int(seed)
            self._key = jax.random.key(int(seed))
        return self

    seed = manual_seed

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        """Split and return a fresh subkey (advances state)."""
        with self._lock:
            self._key, sub = jax.random.split(self._key)
            return sub

    def get_state(self):
        return jax.random.key_data(self._key)

    def set_state(self, state):
        self._key = jax.random.wrap_key_data(state)


_default_generator = Generator(0)

# host-side numpy samplers (e.g. the RCNN fg/bg assigners) register here so
# paddle.seed() also resets them — keeping the reproducibility contract
# without giving every call a fresh identical RandomState
_seed_listeners = []


def register_seed_listener(fn):
    _seed_listeners.append(fn)


def seed(s: int):
    """Set the global random seed (paddle.seed)."""
    _default_generator.manual_seed(s)
    for fn in _seed_listeners:
        fn(int(s))
    return _default_generator


def default_generator() -> Generator:
    return _default_generator


def next_key():
    ctx = _active_ctx()
    if ctx is not None:
        sub = jax.random.fold_in(ctx.key, ctx.count)
        ctx.count += 1
        return sub
    return _default_generator.next_key()


def example_key():
    """A constant key aval-identical to `next_key()`'s output WITHOUT
    advancing the stream — compile-only paths (TrainStep.warmup) need the
    signature but must not consume a key a bit-exact resume depends on."""
    ctx = _active_ctx()
    if ctx is not None:
        return jax.random.fold_in(ctx.key, 0)
    gen = _default_generator
    with gen._lock:
        return jax.random.fold_in(gen._key, 0)


def get_rng_state():
    return _default_generator.get_state()


def set_rng_state(state):
    _default_generator.set_state(state)


# ---------------------------------------------------------------------------
# traced-key context: randomness inside jitted programs
# ---------------------------------------------------------------------------
# Under `jax.jit`, calling next_key() at trace time would bake a *constant*
# key into the compiled program — every step would reuse identical dropout
# masks.  Compiled paths (jit.TrainStep, parallel.ShardedTrainStep) instead
# pass a fresh key argument per step and trace the forward inside key_ctx():
# next_key() then derives per-call-site subkeys from the traced key via
# fold_in, so masks differ every step while staying jit-pure.
import contextlib as _contextlib

_traced_ctx = threading.local()


class _KeyCtx:
    __slots__ = ("key", "count")

    def __init__(self, key):
        self.key = key
        self.count = 0


@_contextlib.contextmanager
def key_ctx(key):
    """Use `key` (possibly a tracer) as the randomness root for this trace."""
    prev = getattr(_traced_ctx, "ctx", None)
    _traced_ctx.ctx = _KeyCtx(key)
    try:
        yield
    finally:
        _traced_ctx.ctx = prev


def _active_ctx():
    return getattr(_traced_ctx, "ctx", None)
