"""Internal-layout policy: run conv-net hot paths channels-last on TPU.

The r5 bench pinned ResNet-50 at 12.74% MFU with two bound causes: NCHW
convs measure slower than NHWC on the MXU (98.1 vs 101.9 TF/s at b256,
probes/resnet_probe_results2.txt) and the training-BN/elementwise chain
costs ~8 HBM passes.  `layout_policy("NHWC")` attacks the first without
any user-visible API change: models keep their logical NCHW contract
(inputs, weights, state_dict all unchanged), but layout-aware ops
(conv2d / batch_norm / pool2d / the fused BN-act kernels) compute on a
physically-NHWC array and mark the produced Tensor with a layout tag.

Tag propagation is centralized in `core.op.dispatch` — the single entry
point every eager op goes through (the same place the reference hangs
its transfer_layout_pass, framework/ir/transfer_layout_elim_pass.cc):

- ops in `AWARE_OPS` handle tagged inputs themselves (they know their
  channel axis) and re-tag their outputs;
- ops in `AGNOSTIC_OPS` (shape-preserving elementwise / broadcasts) run
  directly on the NHWC data when *every* non-scalar operand is tagged,
  and the tag flows through — this is what keeps a whole residual block
  transpose-free;
- any other op is a *program boundary*: tagged inputs are transposed
  back to NCHW (through a tape-recorded transpose, so autodiff is
  exact) before the op sees them.

Under `jax.jit` tracing (TrainStep) the same dispatch path runs at trace
time, so XLA sees straight-line NHWC programs with transposes only at
the true boundaries (stem input, head flatten).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import Tensor

NHWC = "NHWC"

# fast gate: stays False until the first layout_policy() use, so non-vision
# workloads pay one bool check per dispatch and nothing else
_ENABLED_EVER = False
_POLICY: Optional[str] = None

# ops that resolve tags themselves (see their functionals); includes the
# boundary transposes so normalization cannot recurse
AWARE_OPS = {
    "conv2d", "batch_norm", "fused_bn_act", "fused_bn_act_eval",
    "fused_dual_bn_act", "fused_pool_linear_cross_entropy",
    "max_pool2d", "avg_pool2d", "adaptive_avg_pool2d", "adaptive_max_pool2d",
    "layout_to_nchw", "layout_to_nhwc",
}

# shape-preserving elementwise / broadcast ops: safe in any layout as long
# as every non-scalar operand is in the SAME physical permutation
AGNOSTIC_OPS = {
    "relu", "relu6", "leaky_relu", "sigmoid", "tanh", "silu", "swish",
    "gelu", "hardswish", "hardsigmoid", "mish", "elu", "selu", "celu",
    "softsign",
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "scale", "clip", "cast", "clone", "abs", "neg", "pow",
    # NOT dropout: its axis/mask-shape arguments (dropout2d/3d) address
    # the LOGICAL layout, so tagged inputs must boundary-normalize first
}


class _PolicyGuard:
    """Returned by layout_policy(): sets the policy immediately; usable as
    a context manager to restore the previous policy on exit."""

    def __init__(self, prev):
        self._prev = prev

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        global _POLICY
        _POLICY = self._prev
        return False


def layout_policy(fmt: Optional[str]):
    """Set the internal compute layout for conv-net ops.

    `layout_policy("NHWC")` makes Conv2D/BatchNorm/pooling built with the
    default NCHW `data_format` compute in NHWC internally (the TPU-faster
    layout), with transposes only at program boundaries.  `layout_policy
    (None)` (or "NCHW") restores the default.  Works as a plain call or a
    `with` block; must be active when a jitted step is *traced*.
    """
    global _POLICY, _ENABLED_EVER
    prev = _POLICY
    if fmt is not None and str(fmt).upper() not in (NHWC, "NCHW"):
        raise ValueError(f"layout_policy: unsupported layout {fmt!r} "
                         "(expected 'NHWC', 'NCHW', or None)")
    _POLICY = NHWC if (fmt is not None and str(fmt).upper() == NHWC) else None
    if _POLICY is not None:
        _ENABLED_EVER = True
    return _PolicyGuard(prev)


def policy() -> Optional[str]:
    return _POLICY


def enabled() -> bool:
    """Cheap dispatch gate: True once any layout policy has ever been set
    (tags may be live even after the policy context exits)."""
    return _ENABLED_EVER


def tag_of(x) -> Optional[str]:
    return x._layout if isinstance(x, Tensor) else None


def tag(x):
    """Mark a Tensor as physically NHWC (logical NCHW)."""
    if isinstance(x, Tensor) and x._data.ndim == 4:
        x._layout = NHWC
    return x


def tag_tree(out):
    """Tag every rank-4 Tensor in an op's output pytree."""

    def _t(leaf):
        if isinstance(leaf, Tensor) and leaf._data.ndim == 4:
            leaf._layout = NHWC
        return leaf
    jax.tree_util.tree_map(_t, out,
                           is_leaf=lambda l: isinstance(l, Tensor))
    return out


def to_nchw(t):
    """Physically NHWC tagged Tensor -> plain NCHW Tensor (tape-recorded)."""
    from .op import dispatch  # lazy: core.op imports this module at top
    return dispatch("layout_to_nchw",
                    lambda x: jnp.transpose(x, (0, 3, 1, 2)), t)


def to_nhwc(t):
    """Plain NCHW Tensor -> tagged physically-NHWC Tensor (tape-recorded)."""
    from .op import dispatch  # lazy: core.op imports this module at top
    out = dispatch("layout_to_nhwc",
                   lambda x: jnp.transpose(x, (0, 2, 3, 1)), t)
    return tag(out)


def ensure_nhwc(t):
    """Tensor in logical NCHW -> physically NHWC (no-op if already tagged)."""
    return t if tag_of(t) == NHWC else to_nhwc(t)


def _operand_ndim(x):
    if isinstance(x, Tensor):
        return x._data.ndim
    if isinstance(x, np.ndarray) or hasattr(x, "aval") or hasattr(x, "ndim"):
        nd = getattr(x, "ndim", None)
        return nd if isinstance(nd, int) else None
    return None  # python scalar / str / None — layout-neutral


def dispatch_prepare(name: str, flat):
    """Called by core.op.dispatch (when enabled()) before an op runs.

    Returns (flat, propagate): possibly-rewritten operand list (tagged
    inputs transposed back to NCHW at layout boundaries) and whether the
    op's rank-4 outputs should inherit the NHWC tag.
    """
    tagged = [i for i, x in enumerate(flat)
              if isinstance(x, Tensor) and x._layout is not None]
    if not tagged:
        return flat, False
    if name in AWARE_OPS:
        return flat, False
    if name in AGNOSTIC_OPS:
        safe = True
        tagged_set = set(tagged)
        for i, x in enumerate(flat):
            if i in tagged_set:
                continue
            nd = _operand_ndim(x)
            if nd not in (None, 0):
                safe = False  # mixing tagged NHWC with untagged non-scalar
                break
        if safe:
            return flat, True
    # layout boundary: hand the op plain NCHW data
    flat = list(flat)
    for i in tagged:
        flat[i] = to_nchw(flat[i])
    return flat, False
