"""Op dispatch: the single entry point every eager op goes through.

TPU-native equivalent of the reference's Tracer::TraceOp pipeline
(paddle/fluid/imperative/tracer.cc:59-113): AMP autocast -> kernel run ->
grad-node creation.  Here the "kernel" is a pure jnp function (XLA-compiled
and cached by jax's eager dispatch), the grad node is a `jax.vjp` closure, and
AMP is a dtype-cast policy consulted before the call.  Under `jax.jit` the same
path runs at trace time only, so compiled code pays zero overhead for it.

Eager fast path (the L5 overhead attack): eager per-op Python cost used to be
dominated by (a) an eager `jax.vjp` that re-traces the op on EVERY call and
runs its linearization outside any jit cache, and (b) per-call bookkeeping
(imports, placement scans, layout probes).  `dispatch` now keeps an LRU cache
keyed on the op's abstract signature

    (op_name, raw_fn identity/closure, input treedef + avals, diff mask,
     amp-policy state, layout tags, nan-check flag)

whose entries hold a pre-jitted forward that returns ``(outputs, vjp)`` — the
`jax.vjp` is taken INSIDE `jax.jit`, so forward+linearization compile once and
replay from XLA's executable cache (jax returns the pullback as a
`jax.tree_util.Partial`, i.e. a pytree of residuals, so it round-trips through
jit) — plus a pre-jitted backward that the TapeNode invokes instead of a fresh
eager vjp closure.  Signatures the cache cannot key safely (tracer inputs,
unhashable closures, ops that concretize values) fall back to the eager slow
path below, which is byte-for-byte the original dispatch semantics.

Knobs: ``PADDLE_TPU_DISPATCH_CACHE=0`` disables the fast path at import,
``PADDLE_TPU_DISPATCH_CACHE_SIZE`` bounds the LRU (default 512);
`dispatch_cache_clear()` / `set_dispatch_cache_size()` /
`set_dispatch_cache_enabled()` / `dispatch_cache_stats()` are the in-process
controls.  Profiler + FLAGS_check_nan_inf hooks fire on BOTH paths.
"""
from __future__ import annotations

import os
import sys
import time
import types
from collections import OrderedDict
from functools import partial as _fn_partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from . import layout as _layout
from .tensor import (Tensor, _is_tracer, _tapenode_fast, _tensor_fast,
                     is_grad_enabled)

_tree_flatten = jax.tree_util.tree_flatten
_tree_unflatten = jax.tree_util.tree_unflatten
_tree_map = jax.tree_util.tree_map
_tree_leaves = jax.tree_util.tree_leaves

# AMP policy hook: set by paddle_tpu.amp.  Signature: (op_name, raw_leaves,
# tensor_mask) -> raw_leaves (possibly dtype-cast).  key_fn returns a hashable
# snapshot of the policy state (None when inactive) for the dispatch cache.
_amp_hook: Optional[Callable] = None
_amp_key_fn: Optional[Callable] = None
# Profiler hook: set by paddle_tpu.utils.profiler. Signature: (op_name) -> ctx.
_profiler_hook: Optional[Callable] = None
# FLAGS_check_nan_inf consumer (reference:
# framework/details/nan_inf_utils_detail.cc — scan every op's outputs and
# abort on the first non-finite value).  Toggled by utils.flags.set_flags.
_check_nan_inf: bool = False
# placement-harmonization gate: False until the process sees its first device
# mesh (parallel.mesh.create_mesh calls note_multi_device), so single-device
# eager loops never pay the per-input sharding scan.
_multi_device_seen: bool = False


def set_amp_hook(fn, key_fn=None):
    global _amp_hook, _amp_key_fn
    _amp_hook = fn
    _amp_key_fn = key_fn
    dispatch_cache_clear()  # traced casts bake the policy hook in


def set_profiler_hook(fn):
    global _profiler_hook
    _profiler_hook = fn


def set_check_nan_inf(enabled: bool):
    global _check_nan_inf
    _check_nan_inf = bool(enabled)


def note_multi_device():
    """Arm `_harmonize_placement`: called by parallel.mesh.create_mesh the
    first time a device mesh exists, after which eager ops must tolerate
    mixed mesh-sharded / single-device operands."""
    global _multi_device_seen
    _multi_device_seen = True


def _assert_finite(name: str, out):
    """Eager-only scan of an op's float outputs for nan/inf."""
    for leaf in _tree_leaves(out, is_leaf=_is_tensor_leaf):
        arr = leaf._data if isinstance(leaf, Tensor) else leaf
        if _is_tracer(arr) or not hasattr(arr, "dtype"):
            continue
        if not _is_diff_dtype(arr):
            continue
        if not bool(jnp.all(jnp.isfinite(arr))):
            raise FloatingPointError(
                f"Operator '{name}' produced nan/inf "
                f"(FLAGS_check_nan_inf is set)")


def _harmonize_placement(raw):
    """PrepareData equivalent (reference operator.cc:1258): when an eager op
    mixes multi-device (mesh-sharded) arrays with arrays committed to a
    single device — e.g. DataParallel-sharded activations vs a host-loaded
    label — move the single-device ones onto the mesh (replicated) so the
    op compiles instead of raising an incompatible-devices error."""
    mesh_sh = None
    for x in raw:
        if (isinstance(x, jax.Array) and not _is_tracer(x)
                and isinstance(x.sharding, NamedSharding)
                and len(x.sharding.device_set) > 1):
            mesh_sh = x.sharding
            break
    if mesh_sh is None:
        return raw
    repl = NamedSharding(mesh_sh.mesh, PartitionSpec())
    out = list(raw)
    for i, x in enumerate(out):
        if (isinstance(x, jax.Array) and not _is_tracer(x)
                and len(x.sharding.device_set) == 1
                and x.sharding.device_set != mesh_sh.device_set):
            out[i] = jax.device_put(x, repl)
    return out


# ---------------------------------------------------------------------------
# dispatch fast path: signature-keyed cache of jitted forward+vjp pairs
# ---------------------------------------------------------------------------

def _env_cache_enabled() -> bool:
    return os.environ.get("PADDLE_TPU_DISPATCH_CACHE", "1").lower() not in (
        "0", "off", "false", "no")


_cache_enabled: bool = _env_cache_enabled()
_cache_max: int = max(1, int(
    os.environ.get("PADDLE_TPU_DISPATCH_CACHE_SIZE", "512")))
_cache: "OrderedDict" = OrderedDict()
_stats = {"hits": 0, "misses": 0, "fallbacks": 0, "bypass": 0, "evictions": 0}
_dispatch_count: int = 0

_MISS = object()       # sentinel: fast path declined, run the slow path
_FALLBACK = object()   # cached verdict: this signature is not jit-safe
_UNKEYABLE = object()  # freeze() verdict: value cannot live in a cache key


def dispatch_cache_clear():
    """Drop every cached executable (and un-jittable verdicts)."""
    _cache.clear()


def dispatch_cache_stats() -> dict:
    s = dict(_stats)
    s["entries"] = len(_cache)
    s["enabled"] = _cache_enabled
    s["max_entries"] = _cache_max
    return s


def dispatch_cache_size() -> int:
    return _cache_max


def set_dispatch_cache_size(n: int) -> int:
    """Resize the LRU (evicting oldest entries); returns the previous size."""
    global _cache_max
    prev = _cache_max
    _cache_max = max(1, int(n))
    while len(_cache) > _cache_max:
        _cache.popitem(last=False)
        _stats["evictions"] += 1
    return prev


def set_dispatch_cache_enabled(enabled: bool) -> bool:
    """Toggle the fast path (the in-process form of the
    PADDLE_TPU_DISPATCH_CACHE env knob); returns the previous setting."""
    global _cache_enabled
    prev = _cache_enabled
    _cache_enabled = bool(enabled)
    return prev


def dispatch_count() -> int:
    """Monotone count of tensor-carrying dispatches (probe accounting)."""
    return _dispatch_count


_diff_dtype_memo: dict = {}


def _is_diff_dtype(x) -> bool:
    try:
        dt = x.dtype
    except AttributeError:
        return False
    r = _diff_dtype_memo.get(dt)
    if r is None:
        r = bool(jnp.issubdtype(dt, jnp.floating)
                 or jnp.issubdtype(dt, jnp.complexfloating))
        _diff_dtype_memo[dt] = r
    return r


def _is_tensor_leaf(x):
    return isinstance(x, Tensor)


_PRIMS = (bool, int, float, complex, str, bytes)


def _module_global(fn) -> bool:
    """True when fn is reachable as a module attribute under its own
    __qualname__ — then its identity is process-stable and the object itself
    can key the cache (jnp.add, jax.nn.relu, defop raws, ...)."""
    mod = getattr(fn, "__module__", None)
    qn = getattr(fn, "__qualname__", None)
    if not mod or not qn or "<locals>" in qn:
        return False
    obj = sys.modules.get(mod)
    if obj is None:
        return False
    try:
        for part in qn.split("."):
            obj = getattr(obj, part)
    except AttributeError:
        return False
    return obj is fn


def _freeze(v, depth=0):
    """Hashable token for a static value, or _UNKEYABLE.  Deliberately a
    whitelist: anything mutable-and-opaque (Tensors, ndarrays, layer objects)
    must NOT be baked into a trace, so it falls back to the slow path."""
    if v is None:
        return v
    t = v.__class__
    if t in _PRIMS:
        # type-tagged: 1, 1.0 and True compare/hash equal but trace to
        # different constants (int vs float promotion) — they must not
        # collide in the cache key
        return (t.__name__, v)
    if depth > 4:
        return _UNKEYABLE
    if isinstance(v, np.dtype):
        return ("npdt", str(v))
    if isinstance(v, np.generic):  # numpy scalar: value-keyed
        return ("npg", v.item(), str(v.dtype))
    if t in (tuple, list):
        items = []
        for x in v:
            f = _freeze(x, depth + 1)
            if f is _UNKEYABLE:
                return _UNKEYABLE
            items.append(f)
        return (t.__name__, tuple(items))
    if t is dict:
        try:
            keys = sorted(v)
        except TypeError:
            return _UNKEYABLE
        items = []
        for k in keys:
            f = _freeze(v[k], depth + 1)
            if f is _UNKEYABLE:
                return _UNKEYABLE
            items.append((k, f))
        return ("dict", tuple(items))
    if t is slice:
        return ("slice", _freeze(v.start, depth + 1),
                _freeze(v.stop, depth + 1), _freeze(v.step, depth + 1))
    if t is frozenset:
        return v
    if isinstance(v, type):  # dtype classes (jnp.float32), enums' classes
        return v
    if callable(v):
        return _fn_key(v, depth + 1, None)
    return _UNKEYABLE


def _fn_key(fn, depth, dyn_cells):
    """Hashable identity for a raw_fn, or _UNKEYABLE.

    Module-global callables key by object identity.  Call-site-local
    closures (the `def raw(...)` idiom all over tensor/ and nn/functional/)
    key by (code object, frozen defaults, frozen closure cells): the same
    source location with the same closed-over config values maps to the same
    entry even though the function object is rebuilt per call.  When
    `dyn_cells` is a list, closure cells holding bare jax.Arrays (dropout's
    per-call RNG key) become DYNAMIC inputs of the jitted entry — recorded
    here by position, substituted at trace time via cell rewriting — instead
    of baked constants."""
    if depth > 4:
        return _UNKEYABLE
    if getattr(fn, "__self__", None) is not None:
        # bound method: behavior can depend on mutable `self` state that
        # lives outside __closure__ — never safe to bake into a trace
        return _UNKEYABLE
    if isinstance(fn, _fn_partial):
        f = _fn_key(fn.func, depth + 1, None)
        a = _freeze(tuple(fn.args), depth + 1)
        k = _freeze(dict(fn.keywords), depth + 1) if fn.keywords else ()
        if f is _UNKEYABLE or a is _UNKEYABLE or k is _UNKEYABLE:
            return _UNKEYABLE
        return ("partial", f, a, k)
    code = getattr(fn, "__code__", None)
    if code is None or _module_global(fn):
        try:
            hash(fn)
        except TypeError:
            return _UNKEYABLE
        return fn
    parts = [code]
    if fn.__defaults__:
        d = _freeze(tuple(fn.__defaults__), depth + 1)
        if d is _UNKEYABLE:
            return _UNKEYABLE
        parts.append(d)
    if fn.__kwdefaults__:
        d = _freeze(dict(fn.__kwdefaults__), depth + 1)
        if d is _UNKEYABLE:
            return _UNKEYABLE
        parts.append(("kw", d))
    if fn.__closure__:
        for i, c in enumerate(fn.__closure__):
            try:
                v = c.cell_contents
            except ValueError:  # empty cell
                return _UNKEYABLE
            if (dyn_cells is not None and isinstance(v, jax.Array)
                    and not isinstance(v, Tensor)):
                if _is_tracer(v):
                    return _UNKEYABLE
                dyn_cells.append(i)
                av = v.aval
                parts.append(("dyncell", i, av.shape, av.dtype, av.weak_type))
            else:
                fv = _freeze(v, depth + 1)
                if fv is _UNKEYABLE:
                    return _UNKEYABLE
                parts.append(("cell", i, fv))
    return ("fn", tuple(parts))


class _Entry:
    """One cached signature: jitted fwd (+vjp) and the positional plumbing."""

    __slots__ = ("jfwd", "jbwd", "dyn_leaf_pos", "dyn_cell_pos", "diff_pos",
                 "tensor_pos")

    def __init__(self, dyn_leaf_pos, dyn_cell_pos, diff_pos, tensor_pos):
        self.dyn_leaf_pos = dyn_leaf_pos
        self.dyn_cell_pos = dyn_cell_pos
        self.diff_pos = diff_pos
        self.tensor_pos = tensor_pos
        self.jfwd = None
        self.jbwd = None


class _CachedVjp:
    """TapeNode backward for the fast path: replays the op's pre-jitted
    pullback on this call's residuals (a jax Partial pytree) instead of
    holding a fresh eager vjp closure."""

    __slots__ = ("jbwd", "partial", "out_tree")

    def __init__(self, jbwd, partial, out_tree):
        self.jbwd = jbwd
        self.partial = partial
        self.out_tree = out_tree

    def __call__(self, cts):
        if not isinstance(cts, tuple):
            cts = (cts,)
        ct_tree = _tree_unflatten(self.out_tree, list(cts))
        return self.jbwd(self.partial, ct_tree)


def _call_vjp(vjp_partial, ct_tree):
    return vjp_partial(ct_tree)


def _build_key(name, raw_fn, flat):
    """Abstract signature of this dispatch, or None (bypass the fast path).

    Returns (key, dyn_leaf_pos, dyn_cell_pos, diff_pos, tensor_pos)."""
    grad_on = is_grad_enabled()
    desc = []
    dyn_leaf_pos = []
    diff_pos = []
    tensor_pos = []
    for i, x in enumerate(flat):
        if isinstance(x, Tensor):
            d = x._data
            if _is_tracer(d):
                return None  # inside a jit trace: overhead is trace-time only
            tensor_pos.append(i)
            dyn_leaf_pos.append(i)
            diff = grad_on and not x.stop_gradient and _is_diff_dtype(d)
            if diff:
                diff_pos.append(i)
            av = getattr(d, "aval", None)
            if av is not None:
                desc.append(("T", av.shape, av.dtype, av.weak_type, diff,
                             x._layout))
            else:
                # _set_data can leave a raw np.ndarray in _data
                shape = getattr(d, "shape", None)
                dt = getattr(d, "dtype", None)
                if shape is None or dt is None:
                    return None
                desc.append(("T", tuple(shape), str(dt), False, diff,
                             x._layout))
        elif isinstance(x, jax.Array):
            if _is_tracer(x):
                return None
            dyn_leaf_pos.append(i)
            av = x.aval
            desc.append(("A", av.shape, av.dtype, av.weak_type))
        elif isinstance(x, np.ndarray):
            dyn_leaf_pos.append(i)
            desc.append(("A", x.shape, x.dtype.str, False))
        elif x.__class__ is float:
            # bare float leaves (scales, eps, clip bounds) are DYNAMIC
            # weak-typed inputs: a per-step-varying scalar must not compile
            # a fresh executable per value.  ints/bools stay static — they
            # are structural (axis, k, sizes) and must be trace constants.
            dyn_leaf_pos.append(i)
            desc.append(("F",))
        else:
            f = _freeze(x)
            if f is _UNKEYABLE:
                return None
            desc.append(("S", f))
    dyn_cells = []
    fk = _fn_key(raw_fn, 0, dyn_cells)
    if fk is _UNKEYABLE:
        return None
    if _amp_hook is not None:
        if _amp_key_fn is None:
            return None  # unknown policy state: cannot key safely
        amp_key = _amp_key_fn()
    else:
        amp_key = None
    key = (name, fk, tuple(desc), amp_key, _check_nan_inf)
    return key, dyn_leaf_pos, tuple(dyn_cells), diff_pos, tensor_pos


def _rebuild_with_cells(proto, dyn_cell_pos, dyn_vals):
    """Clone proto with the dyn closure cells replaced by dyn_vals (tracers
    at trace time) — how a per-call RNG key becomes a jit input."""
    sub = dict(zip(dyn_cell_pos, dyn_vals))
    cells = tuple(
        types.CellType(sub[i]) if i in sub else c
        for i, c in enumerate(proto.__closure__))
    fn = types.FunctionType(proto.__code__, proto.__globals__,
                            proto.__name__, proto.__defaults__, cells)
    if proto.__kwdefaults__:
        fn.__kwdefaults__ = proto.__kwdefaults__
    return fn


def _make_entry(name, raw_fn, flat, treedef, dyn_leaf_pos, dyn_cell_pos,
                diff_pos, tensor_pos):
    entry = _Entry(dyn_leaf_pos, dyn_cell_pos, diff_pos, tensor_pos)
    dyn_set = set(dyn_leaf_pos)
    static_leaves = [None if i in dyn_set else x for i, x in enumerate(flat)]
    n_leaf = len(dyn_leaf_pos)
    amp = _amp_hook
    proto = raw_fn  # entry keeps the creating call's fn for globals/cells

    def assemble(dyn):
        leaves = list(static_leaves)
        for p, v in zip(dyn_leaf_pos, dyn):
            leaves[p] = v
        if dyn_cell_pos:
            fn = _rebuild_with_cells(proto, dyn_cell_pos, dyn[n_leaf:])
        else:
            fn = proto
        return leaves, fn

    if diff_pos:
        def fwd(*dyn):
            leaves, fn = assemble(dyn)

            def closed(*diff_vals):
                lv = list(leaves)
                for p, v in zip(diff_pos, diff_vals):
                    lv[p] = v
                if amp is not None:
                    lv = amp(name, lv, tensor_pos)
                a2, k2 = _tree_unflatten(treedef, lv)
                return fn(*a2, **k2)

            # the vjp INSIDE jit: forward + linearization compile once; the
            # pullback is a Partial pytree (residual leaves), jit-returnable
            return jax.vjp(closed, *[leaves[p] for p in diff_pos])

        entry.jfwd = jax.jit(fwd)
        entry.jbwd = jax.jit(_call_vjp)
    else:
        def fwd(*dyn):
            leaves, fn = assemble(dyn)
            if amp is not None:
                leaves = amp(name, leaves, tensor_pos)
            a2, k2 = _tree_unflatten(treedef, leaves)
            return fn(*a2, **k2)

        entry.jfwd = jax.jit(fwd)
    return entry


def _run_entry(entry, name, raw_fn, flat, tag_out):
    dyn = [x._data if isinstance(x, Tensor) else x
           for x in (flat[p] for p in entry.dyn_leaf_pos)]
    if entry.dyn_cell_pos:
        cells = raw_fn.__closure__
        dyn += [cells[p].cell_contents for p in entry.dyn_cell_pos]
    if _multi_device_seen:
        dyn = _harmonize_placement(dyn)
    prof = _profiler_hook(name) if _profiler_hook is not None else None
    try:
        if prof is not None:
            prof.__enter__()
        if entry.diff_pos:
            out_raw, vjp_partial = entry.jfwd(*dyn)
            if _check_nan_inf:
                _assert_finite(name, out_raw)
            out_flat, out_tree = _tree_flatten(out_raw)
            out_tensors = [_tensor_fast(x, False) for x in out_flat]
            node = _tapenode_fast(
                name, _CachedVjp(entry.jbwd, vjp_partial, out_tree),
                [flat[p] for p in entry.diff_pos], out_tensors)
            for i, t in enumerate(out_tensors):
                t._node = node
                t._out_index = i
            wrapped = _tree_unflatten(out_tree, out_tensors)
        else:
            out = entry.jfwd(*dyn)
            if _check_nan_inf:
                _assert_finite(name, out)
            wrapped = _tree_map(lambda x: _tensor_fast(x, True), out)
        return _layout.tag_tree(wrapped) if tag_out else wrapped
    finally:
        if prof is not None:
            prof.__exit__(None, None, None)


def _dispatch_fast(name, raw_fn, flat, treedef, tag_out):
    built = _build_key(name, raw_fn, flat)
    if built is None:
        _stats["bypass"] += 1
        return _MISS
    key0, dyn_leaf_pos, dyn_cell_pos, diff_pos, tensor_pos = built
    key = (key0, treedef)
    entry = _cache.get(key)
    if entry is _FALLBACK:
        return _MISS
    if entry is not None:
        _cache.move_to_end(key)
        _stats["hits"] += 1
        return _run_entry(entry, name, raw_fn, flat, tag_out)
    _stats["misses"] += 1
    _consult_program_store()
    t_compile = time.perf_counter()
    entry = _make_entry(name, raw_fn, flat, treedef, dyn_leaf_pos,
                        dyn_cell_pos, diff_pos, tensor_pos)
    try:
        result = _run_entry(entry, name, raw_fn, flat, tag_out)
    except FloatingPointError:
        # FLAGS_check_nan_inf data error AFTER a successful trace: the
        # entry is valid — keep it (later finite calls stay on the fast
        # path) and surface the error without re-running the op eagerly
        _cache[key] = entry
        if len(_cache) > _cache_max:
            _cache.popitem(last=False)
            _stats["evictions"] += 1
        raise
    except Exception:
        # un-jittable op (concretizes values, host control flow, ...): record
        # the verdict and let the eager slow path run it — a genuine error
        # re-raises identically there
        _cache[key] = _FALLBACK
        _stats["fallbacks"] += 1
        result = _MISS
    else:
        _cache[key] = entry
        # miss = trace+compile+first run; compile dominates — record it in
        # the compiled-program registry (no extra lowering: per-op cost
        # analysis would double-compile every eager signature)
        _note_compile(name, time.perf_counter() - t_compile)
    if len(_cache) > _cache_max:  # bound holds for _FALLBACK verdicts too
        _cache.popitem(last=False)
        _stats["evictions"] += 1
    return result


_store_consulted = False


def _consult_program_store():
    """Before the first dispatch-cache miss compiles anything, make sure
    the persistent program store is live when the env opts in
    (PDTPU_PROGRAM_CACHE_DIR): every per-op jit this cache builds then
    reads/writes the shared on-disk cache, so a second process replays
    the whole eager warm-up from disk instead of recompiling it.
    Best-effort and once: the store must never gate dispatch."""
    global _store_consulted
    if _store_consulted:
        return
    _store_consulted = True
    try:
        from ..programs.store import ensure_enabled
        ensure_enabled()
    except Exception:
        pass


def _note_compile(name, seconds):
    """Report a dispatch-cache miss compile to the observability program
    registry (best-effort: telemetry must never break dispatch)."""
    try:
        from ..observability.programs import note_compile
        note_compile("dispatch:" + name, seconds)
    except Exception:
        pass


def _dispatch_cache_collector():
    """Surface the hot-path cache dict in the metrics registry at scrape
    time — the counters 'move into the registry' without dispatch paying a
    registry lock per op."""
    s = dispatch_cache_stats()
    total = s["hits"] + s["misses"]
    return [
        {"name": "dispatch_cache_hits_total", "kind": "counter",
         "value": s["hits"], "help": "eager dispatch fast-path cache hits"},
        {"name": "dispatch_cache_misses_total", "kind": "counter",
         "value": s["misses"], "help": "eager dispatch fast-path misses"},
        {"name": "dispatch_cache_fallbacks_total", "kind": "counter",
         "value": s["fallbacks"], "help": "signatures not jit-safe"},
        {"name": "dispatch_cache_bypass_total", "kind": "counter",
         "value": s["bypass"], "help": "dispatches that bypassed the cache"},
        {"name": "dispatch_cache_evictions_total", "kind": "counter",
         "value": s["evictions"], "help": "LRU evictions"},
        {"name": "dispatch_cache_entries", "kind": "gauge",
         "value": s["entries"], "help": "live cache entries"},
        {"name": "dispatch_cache_hit_rate", "kind": "gauge",
         "value": (s["hits"] / total) if total else 0.0,
         "help": "hits / (hits + misses)"},
    ]


try:
    from ..observability.metrics import get_registry as _obs_get_registry
    _obs_get_registry().register_collector(_dispatch_cache_collector)
except Exception:  # observability must never gate the op system
    pass


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

def dispatch(name: str, raw_fn: Callable, *args, **kwargs):
    """Run `raw_fn` over args where Tensor leaves are unwrapped.

    - If no arg is a Tensor: pure functional call, returns raw values
      (this is the fast jit path for layers called with plain jax arrays).
    - If Tensors present but no grad needed: compute, wrap outputs.
    - Else: `jax.vjp` through the op, record a TapeNode.
    Output pytree structure of raw_fn is preserved; array leaves become
    Tensors when any input was a Tensor.
    """
    global _dispatch_count
    flat, treedef = _tree_flatten((args, kwargs), is_leaf=_is_tensor_leaf)
    tensor_idx = [i for i, x in enumerate(flat) if isinstance(x, Tensor)]

    if not tensor_idx:
        return raw_fn(*args, **kwargs)
    _dispatch_count += 1

    # layout-policy hook (core.layout): transpose tagged-NHWC inputs back
    # to NCHW at layout boundaries; layout-agnostic elementwise ops run on
    # the NHWC data directly and propagate the tag to their outputs
    tag_out = False
    if _layout._ENABLED_EVER:
        flat2, tag_out = _layout.dispatch_prepare(name, flat)
        if flat2 is not flat:
            flat = flat2
            args, kwargs = _tree_unflatten(treedef, flat)

    if _cache_enabled:
        res = _dispatch_fast(name, raw_fn, flat, treedef, tag_out)
        if res is not _MISS:
            return res

    raw = [x._data if isinstance(x, Tensor) else x for x in flat]
    if _multi_device_seen:
        raw = _harmonize_placement(raw)
    # NOTE: the AMP cast runs INSIDE the differentiated closure below, so the
    # vjp of the cast maps cotangents back to each input's original dtype
    # (bf16 activations get bf16 grads, f32 master params get f32 grads even
    # when the op computed in bf16).  Casting before jax.vjp instead would
    # hand the tape cotangents in the compute dtype and break accumulation
    # against upstream nodes recorded in the storage dtype.
    amp = _amp_hook

    def apply_amp(leaves):
        return amp(name, leaves, tensor_idx) if amp is not None else leaves

    need_grad = (is_grad_enabled()
                 and any(not flat[i].stop_gradient for i in tensor_idx))

    prof = _profiler_hook(name) if _profiler_hook is not None else None
    try:
        if prof is not None:
            prof.__enter__()
        if not need_grad:
            a2, k2 = _tree_unflatten(treedef, apply_amp(raw))
            out = raw_fn(*a2, **k2)
            if _check_nan_inf:
                _assert_finite(name, out)
            wrapped = _tree_map(lambda x: _tensor_fast(x, True), out)
            return _layout.tag_tree(wrapped) if tag_out else wrapped

        # differentiable inputs: float/complex Tensors not marked stop_gradient
        diff_idx = [i for i in tensor_idx
                    if not flat[i].stop_gradient and _is_diff_dtype(raw[i])]
        if not diff_idx:
            a2, k2 = _tree_unflatten(treedef, apply_amp(raw))
            out = raw_fn(*a2, **k2)
            if _check_nan_inf:
                _assert_finite(name, out)
            wrapped = _tree_map(lambda x: _tensor_fast(x, True), out)
            return _layout.tag_tree(wrapped) if tag_out else wrapped

        def closed(*diff_vals):
            leaves = list(raw)
            for i, v in zip(diff_idx, diff_vals):
                leaves[i] = v
            a2, k2 = _tree_unflatten(treedef, apply_amp(leaves))
            return raw_fn(*a2, **k2)

        out_raw, vjp_fn = jax.vjp(closed, *[raw[i] for i in diff_idx])
        if _check_nan_inf:
            _assert_finite(name, out_raw)

        out_flat, out_tree = _tree_flatten(out_raw)
        out_tensors = [_tensor_fast(x, False) for x in out_flat]
        node = _tapenode_fast(name, _TreeVjp(vjp_fn, out_tree),
                              [flat[i] for i in diff_idx], out_tensors)
        for i, t in enumerate(out_tensors):
            t._node = node
            t._out_index = i
        wrapped = _tree_unflatten(out_tree, out_tensors)
        return _layout.tag_tree(wrapped) if tag_out else wrapped
    finally:
        if prof is not None:
            prof.__exit__(None, None, None)


class _TreeVjp:
    """Adapts a pytree-output vjp to the flat cotangent list the tape passes."""

    __slots__ = ("vjp_fn", "out_tree")

    def __init__(self, vjp_fn, out_tree):
        self.vjp_fn = vjp_fn
        self.out_tree = out_tree

    def __call__(self, cts):
        if not isinstance(cts, tuple):
            cts = (cts,)
        ct_tree = _tree_unflatten(self.out_tree, list(cts))
        return self.vjp_fn(ct_tree)


def defop(name: str):
    """Decorator: turn a pure jnp function into a tape-aware eager op."""
    def deco(raw_fn):
        def op(*args, **kwargs):
            return dispatch(name, raw_fn, *args, **kwargs)
        op.__name__ = name
        op.raw = raw_fn
        op.__doc__ = raw_fn.__doc__
        return op
    return deco
