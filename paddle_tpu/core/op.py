"""Op dispatch: the single entry point every eager op goes through.

TPU-native equivalent of the reference's Tracer::TraceOp pipeline
(paddle/fluid/imperative/tracer.cc:59-113): AMP autocast -> kernel run ->
grad-node creation.  Here the "kernel" is a pure jnp function (XLA-compiled
and cached by jax's eager dispatch), the grad node is a `jax.vjp` closure, and
AMP is a dtype-cast policy consulted before the call.  Under `jax.jit` the same
path runs at trace time only, so compiled code pays zero overhead for it.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax

from .tensor import Tensor, TapeNode, _is_tracer, is_grad_enabled

# AMP policy hook: set by paddle_tpu.amp.  Signature: (op_name, raw_leaves,
# tensor_mask) -> raw_leaves (possibly dtype-cast).
_amp_hook: Optional[Callable] = None
# Profiler hook: set by paddle_tpu.utils.profiler. Signature: (op_name) -> ctx.
_profiler_hook: Optional[Callable] = None
# FLAGS_check_nan_inf consumer (reference:
# framework/details/nan_inf_utils_detail.cc — scan every op's outputs and
# abort on the first non-finite value).  Toggled by utils.flags.set_flags.
_check_nan_inf: bool = False


def set_amp_hook(fn):
    global _amp_hook
    _amp_hook = fn


def set_profiler_hook(fn):
    global _profiler_hook
    _profiler_hook = fn


def set_check_nan_inf(enabled: bool):
    global _check_nan_inf
    _check_nan_inf = bool(enabled)


def _assert_finite(name: str, out):
    """Eager-only scan of an op's float outputs for nan/inf."""
    import jax.numpy as jnp
    for leaf in jax.tree_util.tree_leaves(
            out, is_leaf=lambda x: isinstance(x, Tensor)):
        arr = leaf._data if isinstance(leaf, Tensor) else leaf
        if _is_tracer(arr) or not hasattr(arr, "dtype"):
            continue
        if not _is_diff_dtype(arr):
            continue
        if not bool(jnp.all(jnp.isfinite(arr))):
            raise FloatingPointError(
                f"Operator '{name}' produced nan/inf "
                f"(FLAGS_check_nan_inf is set)")


def _harmonize_placement(raw):
    """PrepareData equivalent (reference operator.cc:1258): when an eager op
    mixes multi-device (mesh-sharded) arrays with arrays committed to a
    single device — e.g. DataParallel-sharded activations vs a host-loaded
    label — move the single-device ones onto the mesh (replicated) so the
    op compiles instead of raising an incompatible-devices error."""
    from jax.sharding import NamedSharding, PartitionSpec
    mesh_sh = None
    for x in raw:
        if (isinstance(x, jax.Array) and not _is_tracer(x)
                and isinstance(x.sharding, NamedSharding)
                and len(x.sharding.device_set) > 1):
            mesh_sh = x.sharding
            break
    if mesh_sh is None:
        return raw
    repl = NamedSharding(mesh_sh.mesh, PartitionSpec())
    out = list(raw)
    for i, x in enumerate(out):
        if (isinstance(x, jax.Array) and not _is_tracer(x)
                and len(x.sharding.device_set) == 1
                and x.sharding.device_set != mesh_sh.device_set):
            out[i] = jax.device_put(x, repl)
    return out


def dispatch(name: str, raw_fn: Callable, *args, **kwargs):
    """Run `raw_fn` over args where Tensor leaves are unwrapped.

    - If no arg is a Tensor: pure functional call, returns raw values
      (this is the fast jit path for layers called with plain jax arrays).
    - If Tensors present but no grad needed: compute, wrap outputs.
    - Else: `jax.vjp` through the op, record a TapeNode.
    Output pytree structure of raw_fn is preserved; array leaves become
    Tensors when any input was a Tensor.
    """
    flat, treedef = jax.tree_util.tree_flatten(
        (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
    tensor_idx = [i for i, x in enumerate(flat) if isinstance(x, Tensor)]

    if not tensor_idx:
        return raw_fn(*args, **kwargs)

    # layout-policy hook (core.layout): transpose tagged-NHWC inputs back
    # to NCHW at layout boundaries; layout-agnostic elementwise ops run on
    # the NHWC data directly and propagate the tag to their outputs
    tag_out = False
    from . import layout as _layout
    if _layout.enabled():
        flat2, tag_out = _layout.dispatch_prepare(name, flat)
        if flat2 is not flat:
            flat = flat2
            args, kwargs = jax.tree_util.tree_unflatten(treedef, flat)

    raw = _harmonize_placement(
        [x._data if isinstance(x, Tensor) else x for x in flat])
    # NOTE: the AMP cast runs INSIDE the differentiated closure below, so the
    # vjp of the cast maps cotangents back to each input's original dtype
    # (bf16 activations get bf16 grads, f32 master params get f32 grads even
    # when the op computed in bf16).  Casting before jax.vjp instead would
    # hand the tape cotangents in the compute dtype and break accumulation
    # against upstream nodes recorded in the storage dtype.
    amp = _amp_hook

    def apply_amp(leaves):
        return amp(name, leaves, tensor_idx) if amp is not None else leaves

    need_grad = (is_grad_enabled()
                 and any(not flat[i].stop_gradient for i in tensor_idx))

    prof = _profiler_hook(name) if _profiler_hook is not None else None
    try:
        if prof is not None:
            prof.__enter__()
        if not need_grad:
            a2, k2 = jax.tree_util.tree_unflatten(treedef, apply_amp(raw))
            out = raw_fn(*a2, **k2)
            if _check_nan_inf:
                _assert_finite(name, out)
            wrapped = jax.tree_util.tree_map(
                lambda x: Tensor(x, stop_gradient=True), out)
            return _layout.tag_tree(wrapped) if tag_out else wrapped

        # differentiable inputs: float/complex Tensors not marked stop_gradient
        diff_idx = [i for i in tensor_idx
                    if not flat[i].stop_gradient and _is_diff_dtype(raw[i])]
        if not diff_idx:
            a2, k2 = jax.tree_util.tree_unflatten(treedef, apply_amp(raw))
            out = raw_fn(*a2, **k2)
            if _check_nan_inf:
                _assert_finite(name, out)
            wrapped = jax.tree_util.tree_map(
                lambda x: Tensor(x, stop_gradient=True), out)
            return _layout.tag_tree(wrapped) if tag_out else wrapped

        def closed(*diff_vals):
            leaves = list(raw)
            for i, v in zip(diff_idx, diff_vals):
                leaves[i] = v
            a2, k2 = jax.tree_util.tree_unflatten(treedef, apply_amp(leaves))
            return raw_fn(*a2, **k2)

        out_raw, vjp_fn = jax.vjp(closed, *[raw[i] for i in diff_idx])
        if _check_nan_inf:
            _assert_finite(name, out_raw)

        out_flat, out_tree = jax.tree_util.tree_flatten(out_raw)
        out_tensors = [Tensor(x, stop_gradient=False) for x in out_flat]
        node = TapeNode(name, _TreeVjp(vjp_fn, out_tree),
                        [flat[i] for i in diff_idx], out_tensors)
        for i, t in enumerate(out_tensors):
            t._node = node
            t._out_index = i
        wrapped = jax.tree_util.tree_unflatten(out_tree, out_tensors)
        return _layout.tag_tree(wrapped) if tag_out else wrapped
    finally:
        if prof is not None:
            prof.__exit__(None, None, None)


class _TreeVjp:
    """Adapts a pytree-output vjp to the flat cotangent list the tape passes."""

    __slots__ = ("vjp_fn", "out_tree")

    def __init__(self, vjp_fn, out_tree):
        self.vjp_fn = vjp_fn
        self.out_tree = out_tree

    def __call__(self, cts):
        if not isinstance(cts, tuple):
            cts = (cts,)
        ct_tree = jax.tree_util.tree_unflatten(self.out_tree, list(cts))
        return self.vjp_fn(ct_tree)


def _is_diff_dtype(x) -> bool:
    try:
        dt = x.dtype
    except AttributeError:
        return False
    import jax.numpy as jnp
    return jnp.issubdtype(dt, jnp.floating) or jnp.issubdtype(dt, jnp.complexfloating)


def defop(name: str):
    """Decorator: turn a pure jnp function into a tape-aware eager op."""
    def deco(raw_fn):
        def op(*args, **kwargs):
            return dispatch(name, raw_fn, *args, **kwargs)
        op.__name__ = name
        op.raw = raw_fn
        op.__doc__ = raw_fn.__doc__
        return op
    return deco
