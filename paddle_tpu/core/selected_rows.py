"""RowSparseGrad — the TPU-native SelectedRows.

Reference: paddle/fluid/framework/selected_rows.h:1 (a rows index vector +
value tensor on a (height, width) frame, produced by sparse lookup-table
grads) and paddle/fluid/operators/optimizers/adam_op.h:1 (lazy mode: only
touched rows get a moment/param update).

TPU-native design: under jit every shape is static, so a "set of touched
rows" cannot be a dynamically-sized array.  The rep therefore keeps the FULL
lookup-count rows/values arrays — duplicates included — and
`optimizer.sparse.merge_rows` (the analogue of scatter::MergeAdd) segment-sums
duplicates into same-shape buffers with out-of-range sentinels that the
row-wise lazy update drops via `mode="drop"` scatters.  Grads stay
O(lookups·width) instead of O(vocab·width) end to end.

Two delivery paths:
- eager: `F.embedding(..., sparse=True)` records a tape node whose vjp emits
  a RowSparseGrad; `Optimizer.step` applies the lazy row update.
- jit (TrainStep): a SparseGradContext threads per-lookup zero leaves through
  `jax.value_and_grad` (the embedding adds a zeros tensor to the gathered
  rows, so the zeros' cotangent IS the per-lookup grad) and the step applies
  the same lazy update inside the compiled program.
"""
from __future__ import annotations

import contextlib
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import Tensor, TapeNode, unwrap


class RowSparseGrad:
    """rows (N,) int32 lookup ids + values (N, width); dense_shape=(height, width).

    Duplicate rows are allowed (merged lazily by the optimizer).  Supports
    `+` with another RowSparseGrad (concat — SelectedRows accumulation) and
    with a dense array (densifies).
    """

    __slots__ = ("rows", "values", "dense_shape")

    def __init__(self, rows, values, dense_shape):
        self.rows = rows
        self.values = values
        self.dense_shape = tuple(int(s) for s in dense_shape)

    @property
    def shape(self):
        return self.dense_shape

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def is_sparse(self):
        return True

    def to_dense(self):
        out = jnp.zeros(self.dense_shape, self.values.dtype)
        return out.at[self.rows].add(self.values, mode="drop")

    def __add__(self, other):
        if isinstance(other, RowSparseGrad):
            return RowSparseGrad(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.values.astype(jnp.result_type(
                    self.values, other.values)),
                    other.values.astype(jnp.result_type(
                        self.values, other.values))]),
                self.dense_shape)
        return self.to_dense() + other

    __radd__ = __add__

    def numpy(self):
        """Dense materialization (Tensor.gradient parity for sparse grads)."""
        return np.asarray(self.to_dense())

    def __repr__(self):
        return (f"RowSparseGrad(rows={self.rows.shape[0]}, "
                f"dense_shape={self.dense_shape}, dtype={self.dtype})")


jax.tree_util.register_pytree_node(
    RowSparseGrad,
    lambda g: ((g.rows, g.values), g.dense_shape),
    lambda aux, kids: RowSparseGrad(kids[0], kids[1], aux),
)


# ---------------------------------------------------------------------------
# jit path: sparse-grad collection context


class SparseGradContext:
    """Trace-time channel between F.embedding and the compiled train step.

    mode "record": a shape-probe pass (jax.eval_shape) that notes each sparse
    lookup's (n_lookups, width, dtype) so the step can allocate zero leaves.
    mode "apply": the real trace; the embedding adds `zeros[key]` to its
    gathered rows (so d zeros == per-lookup grad) and logs the lookup ids.
    Keys are `param_name@call_index` — stable across both passes because both
    trace the same forward.
    """

    def __init__(self, mode: str, zeros: Optional[Dict] = None, deny=()):
        self.mode = mode
        self.zeros = zeros or {}
        # param names DEMOTED to dense grads (tied weights — see
        # TrainStep.__init__): F.embedding skips the sparse channel for
        # these and lets the weight stay in the differentiated set
        self.deny = frozenset(deny)
        self.specs: Dict[str, tuple] = {}
        self.ids: Dict[str, jax.Array] = {}
        self._counts: Dict[str, int] = {}

    def wants(self, name: str) -> bool:
        return name not in self.deny

    def key_for(self, name: str) -> str:
        i = self._counts.get(name, 0)
        self._counts[name] = i + 1
        return f"{name}@{i}"


_CTX: Optional[SparseGradContext] = None


def current_ctx() -> Optional[SparseGradContext]:
    return _CTX


@contextlib.contextmanager
def use_ctx(ctx: SparseGradContext):
    global _CTX
    prev = _CTX
    _CTX = ctx
    try:
        yield ctx
    finally:
        _CTX = prev


def param_name(key: str) -> str:
    return key.rsplit("@", 1)[0]


def ctx_embedding(ctx: SparseGradContext, x, weight, padding_idx=None):
    """Embedding lookup inside a TrainStep trace with sparse grads requested.

    NOTE (matches the reference's sparse lookup-table restrictions): a
    sparse=True weight must ONLY be consumed through F.embedding — sharing it
    with dense ops (e.g. a tied LM head) silently drops those other grads,
    because the weight is excluded from the differentiated param set.
    """
    ids = unwrap(x).astype(jnp.int32)
    w = unwrap(weight)
    name = getattr(weight, "name", None) or "embedding"
    key = ctx.key_for(name)
    width = w.shape[1]
    n = int(np.prod(ids.shape))

    if ctx.mode == "record":
        ctx.specs[key] = (n, width, w.dtype)
        out = jnp.take(w, ids, axis=0)
    else:
        z = ctx.zeros[key]
        ctx.ids[key] = ids.reshape(-1)
        out = (jnp.take(jax.lax.stop_gradient(w), ids, axis=0)
               + z.reshape(ids.shape + (width,)))
    if padding_idx is not None:
        out = jnp.where((ids == padding_idx)[..., None],
                        jnp.zeros((), out.dtype), out)
    return Tensor(out, stop_gradient=True)


# ---------------------------------------------------------------------------
# misuse guard: a sparse weight consumed outside F.embedding would silently
# lose those gradients (it is excluded from the differentiated params), so the
# train-step build probes the traced forward and hard-errors instead.


def dense_consumed_uses(probe_fn, sparse_vals: Dict[str, jax.Array]):
    """Return (state_key, primitive_name) pairs for every sparse param the
    traced forward consumes OUTSIDE the sanctioned ctx_embedding
    stop_gradient path (e.g. a tied LM head).  Conservative: unrecognized
    call-like primitives consuming a sparse weight also count.

    probe_fn(sparse_vals_dict) must run the forward with an apply-mode
    SparseGradContext active.
    """
    closed = jax.make_jaxpr(probe_fn)(sparse_vals)
    leaves, _ = jax.tree_util.tree_flatten(sparse_vals)
    keys = sorted(sparse_vals)
    tracked = {v: k for v, k in zip(closed.jaxpr.invars[:len(leaves)], keys)}
    return _find_dense_consumers(closed.jaxpr, tracked)


def dense_consumed_keys(probe_fn, sparse_vals: Dict[str, jax.Array]):
    """Just the offending state keys (TrainStep's demotion wants a set)."""
    return {k for k, _ in dense_consumed_uses(probe_fn, sparse_vals)}


def check_embedding_only_use(probe_fn, sparse_vals: Dict[str, jax.Array]):
    """Raise ValueError if any sparse param feeds an op other than the
    stop_gradient that ctx_embedding wraps it in (e.g. a tied LM head).
    TrainStep no longer uses this (it demotes such weights to dense grads
    with a warning); kept for direct callers who want the hard guard.
    """
    bad = dense_consumed_uses(probe_fn, sparse_vals)
    if bad:
        uses = ", ".join(sorted({f"'{k}' used by {p}" for k, p in bad}))
        raise ValueError(
            "Embedding(sparse=True) weights must only be consumed via "
            f"F.embedding, but the traced forward also uses: {uses}. "
            "Those gradients would be silently dropped — untie the weight "
            "or use sparse=False.")


def _find_dense_consumers(jaxpr, tracked):
    bad = []
    for eqn in jaxpr.eqns:
        hits = [(i, v) for i, v in enumerate(eqn.invars)
                if not isinstance(v, jax.extend.core.Literal) and v in tracked]
        if not hits:
            continue
        if eqn.primitive.name == "stop_gradient":
            continue  # the sanctioned ctx_embedding path
        inner = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
        if inner is not None and eqn.primitive.name in (
                "pjit", "closed_call", "remat2", "custom_vjp_call",
                "custom_jvp_call"):
            ij = getattr(inner, "jaxpr", inner)
            # these call primitives map eqn.invars positionally onto the
            # inner jaxpr's invars
            inner_tracked = {ij.invars[i]: tracked[v] for i, v in hits
                             if i < len(ij.invars)}
            bad += _find_dense_consumers(ij, inner_tracked)
        else:
            bad += [(tracked[v], eqn.primitive.name) for _, v in hits]
    return bad


# ---------------------------------------------------------------------------
# eager path: tape node emitting a RowSparseGrad


def eager_sparse_embedding(x, weight, padding_idx=None):
    ids = unwrap(x).astype(jnp.int32)
    w = weight._data
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None:
        out = jnp.where((ids == padding_idx)[..., None],
                        jnp.zeros((), out.dtype), out)
    out_t = Tensor(out, stop_gradient=False)
    flat_ids = ids.reshape(-1)
    width = w.shape[1]
    dense_shape = w.shape
    pad = padding_idx

    def vjp_fn(ct):
        vals = ct.reshape(-1, width)
        if pad is not None:
            vals = jnp.where((flat_ids == pad)[:, None],
                             jnp.zeros((), vals.dtype), vals)
        return (RowSparseGrad(flat_ids, vals, dense_shape),)

    node = TapeNode("embedding_sparse_grad", vjp_fn, [weight], [out_t])
    out_t._node = node
    out_t._out_index = 0
    return out_t
