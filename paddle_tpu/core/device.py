"""Device / place management.

TPU-native replacement for the reference's Place hierarchy and
DeviceContextPool (reference: paddle/fluid/platform/place.h,
platform/device_context.h).  In XLA there are no user-managed streams or
per-place kernel registries: a "place" reduces to a `jax.Device`, and stream
ordering / allocator / context concerns are handled by PJRT.  We keep a
paddle-compatible `set_device`/`get_device` string API ("cpu", "tpu:0").
"""
from __future__ import annotations

import jax

_current_device = None  # None -> jax default


class Place:
    """Lightweight place descriptor wrapping a jax.Device."""

    def __init__(self, device: "jax.Device"):
        self._device = device

    @property
    def jax_device(self):
        return self._device

    def is_cpu_place(self):
        return self._device.platform == "cpu"

    def is_tpu_place(self):
        return self._device.platform in ("tpu", "axon")

    def is_gpu_place(self):
        return self._device.platform == "gpu"

    def __repr__(self):
        return f"Place({self._device.platform}:{self._device.id})"

    def __eq__(self, other):
        return isinstance(other, Place) and self._device == other._device


def CPUPlace():
    cpus = [d for d in jax.devices("cpu")] if _has_platform("cpu") else []
    if not cpus:
        # jax may be running pure-TPU; fall back to default device
        return Place(jax.devices()[0])
    return Place(cpus[0])


def TPUPlace(idx: int = 0):
    devs = jax.devices()
    return Place(devs[idx % len(devs)])


# Paddle alias: CUDAPlace maps onto the accelerator place.
CUDAPlace = TPUPlace
XPUPlace = TPUPlace


def _has_platform(platform: str) -> bool:
    try:
        jax.devices(platform)
        return True
    except RuntimeError:
        return False


def set_device(device: str):
    """Set the default device by paddle-style string: 'cpu', 'tpu', 'tpu:1'."""
    global _current_device
    if device is None:
        _current_device = None
        return
    name = device.lower()
    if ":" in name:
        platform, _, idx = name.partition(":")
        idx = int(idx)
    else:
        platform, idx = name, 0
    if platform in ("gpu", "cuda", "xpu", "tpu"):
        # all accelerator names map to the default accelerator backend
        devs = jax.devices()
        dev = devs[idx % len(devs)]
    elif platform == "cpu":
        dev = jax.devices("cpu")[0] if _has_platform("cpu") else jax.devices()[0]
    else:
        raise ValueError(f"Unknown device {device!r}")
    _current_device = dev
    jax.config.update("jax_default_device", dev)
    return Place(dev)


def get_device() -> str:
    dev = _current_device or jax.devices()[0]
    platform = "tpu" if dev.platform in ("tpu", "axon") else dev.platform
    return f"{platform}:{dev.id}"


def current_jax_device():
    return _current_device or jax.devices()[0]


def device_count() -> int:
    return jax.device_count()


def is_compiled_with_cuda() -> bool:
    """Paddle-API compat: reports accelerator availability (TPU here)."""
    return False


def is_compiled_with_tpu() -> bool:
    return any(d.platform in ("tpu", "axon") for d in jax.devices())


def is_compiled_with_xpu() -> bool:
    """Paddle-API compat: Baidu-Kunlun XPU — never present here."""
    return False


def get_cudnn_version():
    """Paddle-API compat: no cuDNN in the XLA/TPU stack."""
    return None


# paddle exposes CUDAPinnedPlace for pinned host staging buffers; host
# memory management is XLA's job here, so it aliases the host place.
CUDAPinnedPlace = CPUPlace
