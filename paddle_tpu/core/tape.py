"""Reverse-mode tape walk.

TPU-native equivalent of the reference's dygraph autograd engine
(paddle/fluid/imperative/basic_engine.cc:38,110,184 — PrepareDeps + reverse
topological queue + GradientAccumulator).  Nodes are `TapeNode`s recorded by
`core.op.dispatch`; each node's backward is a `jax.vjp` closure, so grad math
itself runs as compiled XLA, only the graph walk is Python.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp

from .selected_rows import RowSparseGrad
from .tensor import Tensor, TapeNode, wrap


def _topo_order(root_nodes) -> List[TapeNode]:
    """DFS topological sort over tape nodes (inputs point upstream)."""
    order: List[TapeNode] = []
    seen = set()
    stack = [(n, False) for n in root_nodes]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            if t._node is not None and id(t._node) not in seen:
                stack.append((t._node, False))
    return order  # upstream-first; iterate reversed for backward


def backward(loss: Tensor, grad_tensor: Optional[Tensor] = None,
             retain_graph: bool = False,
             inputs: Optional[List[Tensor]] = None,
             accumulate_into_grad: bool = True) -> Optional[Dict[int, object]]:
    """Run reverse-mode accumulation from `loss`.

    If `inputs` is given, returns {id(tensor): raw_grad} for those tensors
    (the `paddle.grad` path); otherwise grads are accumulated into `.grad` of
    leaf tensors (the `.backward()` path, reference
    dygraph/varbase_patch_methods.py).
    """
    if loss._node is None and loss.stop_gradient:
        raise RuntimeError("backward() on a tensor that does not require grad")

    if grad_tensor is None:
        if loss.size != 1:
            raise RuntimeError(
                "grad_tensor must be provided for non-scalar backward "
                f"(got shape {loss.shape})")
        init = jnp.ones_like(loss._data)
    else:
        init = grad_tensor._data if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)
        # a layout-tagged root is physically NHWC; a caller-supplied
        # cotangent in the logical (NCHW) layout must be transposed to
        # match (an equally-tagged cotangent is already physical)
        if (loss._layout is not None and init.ndim == 4
                and not (isinstance(grad_tensor, Tensor)
                         and grad_tensor._layout == loss._layout)):
            init = jnp.transpose(init, (0, 2, 3, 1))

    # cotangent accumulator keyed by tensor identity
    cotangents: Dict[int, object] = {id(loss): init}
    wanted = None if inputs is None else {id(t) for t in inputs}
    results: Dict[int, object] = {}

    if loss._node is None:
        # leaf with requires-grad: its grad is just init
        _deposit(loss, init, accumulate_into_grad, wanted, results)
        return results if inputs is not None else None

    order = _topo_order([loss._node])

    for node in reversed(order):
        # gather cotangents for this node's outputs
        out_cts = []
        any_ct = False
        for ref, (shape, dt) in zip(node.out_refs, node.out_avals):
            t = ref()
            ct = cotangents.pop(id(t), None) if t is not None else None
            if ct is None:
                ct = jnp.zeros(shape, dt)
            else:
                any_ct = True
                if t is not None and t._hooks:
                    ct = _run_hooks(t, ct)
            out_cts.append(ct)
        if not any_ct:
            continue
        ct_arg = out_cts[0] if len(out_cts) == 1 else tuple(out_cts)
        in_grads = node.vjp_fn(ct_arg)
        for t, g in zip(node.inputs, in_grads):
            if g is None:
                continue
            prev = cotangents.get(id(t))
            acc = g if prev is None else prev + g
            if t._node is None:
                # leaf: deposit and keep out of the queue
                _deposit(t, acc, accumulate_into_grad, wanted, results)
                if wanted is not None:
                    cotangents[id(t)] = acc  # may also be interior-requested
            else:
                cotangents[id(t)] = acc
                if wanted is not None and id(t) in wanted:
                    out = acc
                    if (t._layout is not None
                            and getattr(out, "ndim", 0) == 4):
                        out = jnp.transpose(out, (0, 3, 1, 2))
                    results[id(t)] = out
        if not retain_graph:
            node.vjp_fn = None  # free residuals

    if not retain_graph:
        for node in order:
            node.inputs = []
    return results if inputs is not None else None


def _run_hooks(t: Tensor, ct):
    """Invoke t's grad hooks on a cotangent.  Hooks observe the LOGICAL
    layout: a layout-tagged primal's physically-NHWC cotangent is shown
    (and taken back) as NCHW."""
    tagged4 = t._layout is not None and getattr(ct, "ndim", 0) == 4
    if tagged4:
        ct = jnp.transpose(ct, (0, 3, 1, 2))
    for hook in t._hooks:
        new = hook(wrap(ct))
        if new is not None:
            ct = new._data if isinstance(new, Tensor) else jnp.asarray(new)
    if tagged4:
        ct = jnp.transpose(ct, (0, 2, 3, 1))
    return ct


def _deposit(t: Tensor, raw_grad, accumulate, wanted, results):
    if wanted is not None:
        if id(t) in wanted:
            # paddle.grad results are raw arrays handed straight to the
            # caller — return the LOGICAL layout for tagged primals
            if t._layout is not None and getattr(raw_grad, "ndim", 0) == 4:
                raw_grad = jnp.transpose(raw_grad, (0, 3, 1, 2))
            results[id(t)] = raw_grad
        return
    if t.stop_gradient:
        return
    if isinstance(raw_grad, RowSparseGrad):
        if t._hooks:
            # hooks operate on dense Tensors: densify so registered hooks
            # keep firing (costs the sparsity, preserves semantics)
            raw_grad = raw_grad.to_dense()
        else:
            # SelectedRows grad: stored as-is on .grad (reference keeps the
            # sparse rep on the VarBase grad too)
            if t.grad is None or not accumulate:
                t.grad = raw_grad
            elif isinstance(t.grad, RowSparseGrad):
                t.grad = t.grad + raw_grad
            else:
                t.grad = Tensor(t.grad._data + raw_grad.to_dense(),
                                stop_gradient=True)
            return
    if t._hooks:
        raw_grad = _run_hooks(t, raw_grad)
    if t.grad is None or not accumulate:
        t.grad = Tensor(raw_grad, stop_gradient=True)
    elif isinstance(t.grad, RowSparseGrad):
        t.grad = Tensor(t.grad.to_dense() + raw_grad, stop_gradient=True)
    else:
        t.grad = Tensor(t.grad._data + raw_grad, stop_gradient=True)
    # a layout-tagged primal's cotangent is in the same physical layout:
    # carry the tag so .grad.numpy()/shape present the logical view
    if t._layout is not None and t.grad._data.ndim == 4:
        t.grad._layout = t._layout


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """paddle.grad: compute grads of outputs wrt inputs without touching .grad.

    Reference: imperative/partial_grad_engine.cc via paddle.grad.
    `create_graph` is not yet supported (second-order autodiff goes through the
    functional `jax.grad` path in paddle_tpu.jit instead).
    """
    if create_graph:
        raise NotImplementedError(
            "create_graph=True: use paddle_tpu.jit functional transforms for "
            "higher-order gradients")
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]

    retain = True if retain_graph is None else retain_graph
    total: Dict[int, object] = {}
    for out, go in zip(outputs, grad_outputs):
        res = backward(out, go, retain_graph=retain, inputs=list(inputs),
                       accumulate_into_grad=False)
        for k, v in (res or {}).items():
            total[k] = total[k] + v if k in total else v

    grads = []
    for t in inputs:
        if id(t) in total:
            g = total[id(t)]
            grads.append(g if isinstance(g, RowSparseGrad)
                         else Tensor(g, stop_gradient=True))
        elif allow_unused:
            grads.append(None)
        else:
            raise RuntimeError(
                "One of the differentiated tensors appears to not have been "
                "used in the graph. Set allow_unused=True if this is desired.")
    return grads
