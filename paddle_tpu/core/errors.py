"""Typed error classes + enforce helpers.

Reference: paddle/fluid/platform/enforce.h (PADDLE_ENFORCE* macros raising
EnforceNotMet with a typed error code) and paddle/fluid/platform/errors.h
(the 12-code taxonomy: InvalidArgument, NotFound, OutOfRange, AlreadyExists,
ResourceExhausted, PreconditionNotMet, PermissionDenied, ExecutionTimeout,
Unimplemented, Unavailable, Fatal, External).  TPU-native: each code is a
Python exception that ALSO subclasses the builtin users naturally catch
(InvalidArgumentError is a ValueError, NotFoundError a FileNotFoundError,
…), so framework call sites can raise typed errors without breaking
existing `except ValueError` handling.
"""
from __future__ import annotations

__all__ = [
    "EnforceNotMet", "InvalidArgumentError", "NotFoundError",
    "OutOfRangeError", "AlreadyExistsError", "ResourceExhaustedError",
    "PreconditionNotMetError", "PermissionDeniedError",
    "ExecutionTimeoutError", "UnimplementedError", "UnavailableError",
    "FatalError", "ExternalError", "enforce", "enforce_eq",
]


class EnforceNotMet(Exception):
    """Base of every typed framework error (enforce.h EnforceNotMet)."""
    code = "Unknown"


class InvalidArgumentError(EnforceNotMet, ValueError):
    code = "InvalidArgument"


class NotFoundError(EnforceNotMet, FileNotFoundError):
    code = "NotFound"


class OutOfRangeError(EnforceNotMet, IndexError):
    code = "OutOfRange"


class AlreadyExistsError(EnforceNotMet, FileExistsError):
    code = "AlreadyExists"


class ResourceExhaustedError(EnforceNotMet, MemoryError):
    code = "ResourceExhausted"


class PreconditionNotMetError(EnforceNotMet, RuntimeError):
    code = "PreconditionNotMet"


class PermissionDeniedError(EnforceNotMet, PermissionError):
    code = "PermissionDenied"


class ExecutionTimeoutError(EnforceNotMet, TimeoutError, RuntimeError):
    # RuntimeError base kept for continuity: timeout paths (DataLoader)
    # raised RuntimeError before the taxonomy existed
    code = "ExecutionTimeout"


class UnimplementedError(EnforceNotMet, NotImplementedError):
    code = "Unimplemented"


class UnavailableError(EnforceNotMet, RuntimeError):
    code = "Unavailable"


class FatalError(EnforceNotMet, RuntimeError):
    code = "Fatal"


class ExternalError(EnforceNotMet, OSError):
    code = "External"


def enforce(cond, message, error=InvalidArgumentError):
    """PADDLE_ENFORCE: raise `error` with the typed-code prefix when cond
    is falsy."""
    if not cond:
        raise error(f"[{error.code}] {message}")


def enforce_eq(a, b, message="", error=InvalidArgumentError):
    """PADDLE_ENFORCE_EQ."""
    if a != b:
        raise error(f"[{error.code}] expected {a!r} == {b!r}. {message}")
