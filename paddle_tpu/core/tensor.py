"""Eager Tensor with tape-based autograd.

TPU-native collapse of the reference's two-world design (SURVEY.md §1): the
reference has a C++ dygraph `Tracer`/`VarBase`/`BasicEngine`
(paddle/fluid/imperative/tracer.cc:59, layer.h:65, basic_engine.cc:38) for eager
mode and a protobuf ProgramDesc + Executor for graph mode.  Here a single
`Tensor` wraps a `jax.Array` (or a tracer, when inside `jax.jit`): eager ops
dispatch straight to XLA, autograd is a Python tape whose per-op backward is
`jax.vjp` (the analogue of the reference's per-op GradOpMaker,
framework/grad_op_desc_maker.h), and the *same* ops trace under `jit` where the
Tensor wrapper is trace-time-only overhead — this is what replaces the whole
static-graph world.
"""
from __future__ import annotations

import weakref
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as _dtype_mod

# ---------------------------------------------------------------------------
# grad mode
# ---------------------------------------------------------------------------

_grad_enabled = True


class no_grad:
    """Context manager / decorator disabling tape recording (paddle.no_grad)."""

    def __enter__(self):
        global _grad_enabled
        self._prev = _grad_enabled
        _grad_enabled = False
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._prev
        return False

    def __call__(self, fn):
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)
        return wrapper


class enable_grad:
    def __enter__(self):
        global _grad_enabled
        self._prev = _grad_enabled
        _grad_enabled = True
        return self

    def __exit__(self, *exc):
        global _grad_enabled
        _grad_enabled = self._prev
        return False


def is_grad_enabled() -> bool:
    return _grad_enabled


def set_grad_enabled(mode: bool):
    global _grad_enabled
    _grad_enabled = bool(mode)


# ---------------------------------------------------------------------------
# tape node
# ---------------------------------------------------------------------------

class TapeNode:
    """One recorded op: holds the vjp closure and graph edges.

    Equivalent to the reference's GradOpNode created by Tracer::TraceOp
    (imperative/tracer.cc:113): `inputs` are the differentiable input tensors
    (tape edges to upstream nodes), `outputs` weakly reference the produced
    tensors so cotangents can be routed, `vjp_fn` is the op's backward.
    """

    __slots__ = ("name", "vjp_fn", "inputs", "out_refs", "out_avals", "__weakref__")

    def __init__(self, name, vjp_fn, inputs, outputs):
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs: List[Tensor] = inputs
        self.out_refs = [weakref.ref(t) for t in outputs]
        # store shape/dtype so we can make zero cotangents for dead outputs
        # (PHYSICAL shape: a layout-tagged tensor's cotangent must match
        # its stored NHWC data, not the logical .shape view)
        self.out_avals = [(tuple(t._data.shape), t.dtype) for t in outputs]


# ---------------------------------------------------------------------------
# Tensor
# ---------------------------------------------------------------------------

class Tensor:
    """N-d array wrapping a jax.Array, with paddle-like eager semantics."""

    __slots__ = ("_data", "stop_gradient", "grad", "_node", "_out_index",
                 "name", "persistable", "trainable", "__weakref__", "_hooks",
                 "_layout")

    def __init__(self, data, stop_gradient: bool = True, name: Optional[str] = None):
        src_layout = None
        if isinstance(data, Tensor):
            src_layout = data._layout  # copy shares the physical buffer
            data = data._data
        if not isinstance(data, jax.Array) and not _is_tracer(data):
            data = jnp.asarray(data)
        self._data = data
        self.stop_gradient = stop_gradient
        self.grad: Optional[Tensor] = None
        self._node: Optional[TapeNode] = None
        self._out_index: int = 0
        self.name = name
        self.persistable = False
        self.trainable = not stop_gradient
        self._hooks = None
        # physical-layout tag ("NHWC") set by core.layout under a layout
        # policy; None = data is in the logical (paddle) layout.  A copy
        # built FROM a Tensor shares its buffer, so it inherits the tag.
        self._layout = src_layout

    # -- basic properties ---------------------------------------------------
    @property
    def data(self):
        return self

    @data.setter
    def data(self, value):
        self._data = value._data if isinstance(value, Tensor) else jnp.asarray(value)
        self._layout = value._layout if isinstance(value, Tensor) else None

    @property
    def value(self):
        return self._data

    @property
    def shape(self):
        s = self._data.shape
        # a layout-tagged tensor is physically NHWC; report the LOGICAL
        # (NCHW) shape so user code never observes the internal layout
        if self._layout is not None and len(s) == 4:
            return [s[0], s[3], s[1], s[2]]
        return list(s)

    @property
    def ndim(self):
        return self._data.ndim

    def dim(self):
        return self._data.ndim

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self):
        from .device import Place
        devs = getattr(self._data, "devices", None)
        if devs is None or _is_tracer(self._data):
            from .device import current_jax_device
            return Place(current_jax_device())
        return Place(next(iter(self._data.devices())))

    @property
    def is_leaf(self):
        return self._node is None

    # -- conversion ---------------------------------------------------------
    def numpy(self):
        a = np.asarray(self._data)
        # materialization boundary: a layout-tagged tensor is physically
        # NHWC — hand the caller the logical NCHW view
        if self._layout is not None and a.ndim == 4:
            a = np.transpose(a, (0, 3, 1, 2))
        return a

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def item(self, *args):
        return self.numpy().item(*args)

    def tolist(self):
        return self.numpy().tolist()

    def detach(self) -> "Tensor":
        t = Tensor(self._data, stop_gradient=True, name=self.name)
        t._layout = self._layout
        return t

    def clone(self) -> "Tensor":
        from . import op as _op
        return _op.dispatch("clone", lambda x: jnp.copy(x), self)

    def numel(self):
        return self.size

    def element_size(self):
        return self._data.dtype.itemsize

    def cpu(self):
        t = Tensor(jax.device_get(self._data), stop_gradient=self.stop_gradient)
        t._layout = self._layout
        return t

    def pin_memory(self):
        return self

    def cuda(self, *a, **k):
        return self

    def block_until_ready(self):
        if hasattr(self._data, "block_until_ready"):
            self._data.block_until_ready()
        return self

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        from .tape import backward as _backward
        _backward(self, grad_tensor, retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def zero_grad(self):
        self.grad = None

    def register_hook(self, hook):
        """Register a grad hook: fn(grad_tensor) -> new grad or None."""
        if self._hooks is None:
            self._hooks = []
        self._hooks.append(hook)
        handle = _HookHandle(self._hooks, hook)
        return handle

    @property
    def gradient(self):
        return None if self.grad is None else self.grad.numpy()

    def _set_data(self, raw):
        """In-place replace the underlying buffer (optimizer updates).
        The new buffer is in the logical layout — drop any stale NHWC tag
        (in-place ops route through dispatch, which normalizes first)."""
        self._data = raw
        self._layout = None

    # -- misc dunder --------------------------------------------------------
    def __len__(self):
        if self._data.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __repr__(self):
        sg = self.stop_gradient
        if _is_tracer(self._data):
            return f"Tensor(shape={self.shape}, dtype={_dtype_mod.dtype_name(self.dtype)}, traced)"
        return (f"Tensor(shape={self.shape}, dtype={_dtype_mod.dtype_name(self.dtype)}, "
                f"stop_gradient={sg},\n       {np.asarray(self._data)!r})")

    def __bool__(self):
        return bool(self._data)

    def __int__(self):
        return int(np.asarray(self._data))

    def __float__(self):
        return float(np.asarray(self._data))

    def __index__(self):
        return int(np.asarray(self._data))

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    # __eq__ and friends are patched in paddle_tpu.tensor.patch to be
    # elementwise (paddle semantics); identity compare via `is`.

    def __jax_array__(self):
        return self._data


_Tensor_new = Tensor.__new__


def _tensor_fast(data, stop_gradient=True, name=None) -> Tensor:
    """__slots__-based fast constructor for the dispatch hot path: direct
    slot assignment, no isinstance ladder for the common case (data is
    already a jax.Array / tracer coming out of an op)."""
    if not isinstance(data, jax.Array) and not _is_tracer(data):
        data = jnp.asarray(data)
    t = _Tensor_new(Tensor)
    t._data = data
    t.stop_gradient = stop_gradient
    t.grad = None
    t._node = None
    t._out_index = 0
    t.name = name
    t.persistable = False
    t.trainable = not stop_gradient
    t._hooks = None
    t._layout = None
    return t


_TapeNode_new = TapeNode.__new__


def _tapenode_fast(name, vjp_fn, inputs, outputs) -> TapeNode:
    """__slots__-based fast constructor mirroring TapeNode.__init__ but
    reading `_data` slots directly (no property lookups)."""
    n = _TapeNode_new(TapeNode)
    n.name = name
    n.vjp_fn = vjp_fn
    n.inputs = inputs
    n.out_refs = [weakref.ref(t) for t in outputs]
    n.out_avals = [(tuple(t._data.shape), t._data.dtype) for t in outputs]
    return n


class _HookHandle:
    def __init__(self, hooks, hook):
        self._hooks, self._hook = hooks, hook

    def remove(self):
        try:
            self._hooks.remove(self._hook)
        except ValueError:
            pass


class Parameter(Tensor):
    """Trainable tensor (reference: framework.py Parameter / ParamBase)."""

    __slots__ = ("optimize_attr", "regularizer", "need_clip", "is_distributed",
                 "sparse_grad", "row_shard_axis", "row_shard_mesh")

    def __init__(self, data, name=None, trainable=True):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False
        self.sparse_grad = False  # set by Embedding(sparse=True)
        # row-sharded giant-table metadata, set by embedding.ShardedEmbedding:
        # the mesh axis the leading (row) dim is sharded over + the Mesh.
        # The lazy sparse optimizer update consults these to run PER SHARD
        # (embedding.functional.sharded_lazy_row_update) instead of over the
        # whole table.
        self.row_shard_axis = None
        self.row_shard_mesh = None

    def __repr__(self):
        return "Parameter " + super().__repr__()


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def unwrap(x):
    """Tensor -> raw jax value; passthrough otherwise."""
    return x._data if isinstance(x, Tensor) else x


def wrap(x, stop_gradient=True) -> Tensor:
    return x if isinstance(x, Tensor) else Tensor(x, stop_gradient=stop_gradient)


# pytree registration: Tensors can live inside jitted pytrees (state dicts).
jax.tree_util.register_pytree_node(
    Tensor,
    lambda t: ((t._data,), (t.stop_gradient, t.name)),
    lambda aux, children: Tensor(children[0], stop_gradient=aux[0], name=aux[1]),
)
jax.tree_util.register_pytree_node(
    Parameter,
    lambda t: ((t._data,), (t.name, t.trainable)),
    lambda aux, children: Parameter(children[0], name=aux[0], trainable=aux[1]),
)
