"""Activation-recompute policy: `jax.checkpoint` over tagged Layer subtrees.

The r5 ResNet-50 decomposition showed the step bound by HBM passes over
5.7 GB of live activations; the classic fix (Chen et al., "Training Deep
Nets with Sublinear Memory Cost") is to bound activation liveness by
recomputing stage interiors in the backward.  This module is the
`jit.layout_policy`-shaped knob for it:

    with jit.recompute_policy("stages"):
        step = TrainStep(model, loss_fn, opt, ...)
        step(x, y)   # traced with tagged stages under jax.checkpoint

- `"stages"` wraps every Layer whose `_remat_stage` attribute is truthy —
  ResNet/MobileNet/VGG stages and GPT blocks ship pre-tagged; mark your
  own boundaries with `layer._remat_stage = True`.
- a Layer subclass (or tuple of them), a set of type names, or a
  predicate `layer -> bool` select subtrees structurally.
- `policy=` picks what the checkpoint may keep: "dots_saveable"
  (default — matmul outputs survive, elementwise/norm chains recompute),
  "nothing_saveable", or any `jax.checkpoint_policies` attribute name.

The wrap happens in `Layer.__call__` at *trace* time only (inputs are
tracers and the tape is off — i.e. inside TrainStep/ShardedTrainStep/
to_static builds); eager execution never pays it.  Like layout_policy,
the policy must be active when the step is traced.  BatchNorm running-
stat updates recorded inside a wrapped subtree are re-exported through
the checkpoint boundary as explicit outputs, so the functional
buffer-update contract (core.buffer_updates) survives recompute.
"""
from __future__ import annotations

import threading
from typing import Optional

_POLICY = None          # (matcher spec, checkpoint-policy name or None)
_ENABLED_EVER = False   # fast gate for Layer.__call__
_tls = threading.local()


class _PolicyGuard:
    """Returned by recompute_policy(): sets the policy immediately; usable
    as a context manager to restore the previous policy on exit."""

    def __init__(self, prev):
        self._prev = prev

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        global _POLICY
        _POLICY = self._prev
        return False


def recompute_policy(spec, policy: Optional[str] = "dots_saveable"):
    """Set (or clear, with spec=None) the activation-recompute policy.

    Mirrors `jit.layout_policy`: plain call or `with` block; must be
    active while a jitted step is traced.  See the module docstring for
    the accepted spec forms.
    """
    global _POLICY, _ENABLED_EVER
    prev = _POLICY
    if spec is None:
        _POLICY = None
    else:
        if policy is not None:
            _resolve_jax_policy(policy)  # validate eagerly, not at trace
        _POLICY = (spec, policy)
        _ENABLED_EVER = True
    return _PolicyGuard(prev)


def policy():
    return _POLICY


def enabled() -> bool:
    """Cheap per-call gate: True once any recompute policy was ever set."""
    return _ENABLED_EVER


def inside_checkpoint() -> bool:
    """True while tracing inside a recompute-wrapped subtree.  The fused
    recompute-backward ops (ops/fused_bn_act.py) consult this and fall
    back to their plain differentiable composites there: a custom_vjp's
    residuals are opaque to jax.checkpoint (they get saved across the
    boundary no matter the policy), so keeping the custom rule inside a
    checkpointed region would pin exactly the per-op activations the
    policy is trying to free.  Under the checkpoint the hand recompute is
    redundant anyway — jax rematerializes the whole subtree."""
    return getattr(_tls, "depth", 0) > 0


def checkpoint(fn, policy: Optional[str] = None):
    """`jax.checkpoint` with the inside-checkpoint flag held while `fn`
    traces — the TrainStep/ShardedTrainStep `remat=True` spelling.  The
    fused conv-net ops (ops/fused_bn_act.py) consult the flag and fall
    back to their plain differentiable references under it: a custom_vjp
    rule's residuals are opaque to jax.checkpoint (saved regardless of
    policy), so bare jax.checkpoint over a paddle_tpu model would pin
    exactly the per-op activations the remat exists to free."""
    import jax

    def flagged(*args, **kwargs):
        depth = getattr(_tls, "depth", 0)
        _tls.depth = depth + 1
        try:
            return fn(*args, **kwargs)
        finally:
            _tls.depth = depth

    kw = {} if policy is None else {"policy": _resolve_jax_policy(policy)}
    return jax.checkpoint(flagged, **kw)


def _resolve_jax_policy(name: Optional[str]):
    if name is None:
        return None
    import jax
    try:
        return getattr(jax.checkpoint_policies, name)
    except AttributeError:
        raise ValueError(
            f"recompute_policy: unknown checkpoint policy {name!r} "
            "(expected a jax.checkpoint_policies attribute name, e.g. "
            "'dots_saveable', 'nothing_saveable')") from None


def _matches(layer) -> bool:
    spec = _POLICY[0]
    if spec == "stages":
        return bool(getattr(layer, "_remat_stage", False))
    if isinstance(spec, type):
        return isinstance(layer, spec)
    if isinstance(spec, tuple) and all(isinstance(s, type) for s in spec):
        return isinstance(layer, spec)
    if callable(spec):
        return bool(spec(layer))
    if isinstance(spec, (set, frozenset, list)):
        return type(layer).__name__ in spec
    return False


def should_wrap(layer, inputs) -> bool:
    """Wrap iff: a policy is active, this layer matches, we are not
    already inside a wrapped subtree, the tape is off, and at least one
    input is a tracer (i.e. a functional jit trace is in progress —
    recompute is a compiled-step concept, eager calls never pay it)."""
    if _POLICY is None or getattr(_tls, "depth", 0) > 0:
        return False
    if not _matches(layer):
        return False
    from .tensor import Tensor, is_grad_enabled
    if is_grad_enabled():
        return False  # tape autodiff path: checkpoint regions would hide it
    import jax

    def _traced(x):
        if isinstance(x, Tensor):
            x = x._data
        return isinstance(x, jax.core.Tracer)

    return any(_traced(x) for x in inputs)


def run_wrapped(layer, inputs, kwargs, runner):
    """Execute `runner(inputs, kwargs)` (the layer's hook+forward body)
    under jax.checkpoint.  The layer's state (params + buffers) and every
    array-valued input become explicit checkpoint arguments so the
    backward recomputes the subtree interior from them; layout tags and
    output pytree structure ride out-of-band (they are trace-time static);
    buffer updates captured inside are re-exported to the caller's
    capture scope."""
    import jax
    from . import buffer_updates as _bufup
    from .tensor import Tensor

    sd = layer.state_dict()
    state = {k: t._data for k, t in sd.items()}

    flat_in, in_tree = jax.tree_util.tree_flatten(
        (tuple(inputs), kwargs), is_leaf=lambda x: isinstance(x, Tensor))

    def _arrayish(x):
        return isinstance(x, (jax.Array, jax.core.Tracer)) or (
            hasattr(x, "shape") and hasattr(x, "dtype"))

    dyn_idx, dyn_vals, tags = [], [], {}
    for i, x in enumerate(flat_in):
        if isinstance(x, Tensor):
            dyn_idx.append(i)
            dyn_vals.append(x._data)
            tags[i] = x._layout
        elif _arrayish(x):
            dyn_idx.append(i)
            dyn_vals.append(x)
    dyn_set = {i: j for j, i in enumerate(dyn_idx)}
    meta = {}

    def fn(state_vals, dyn):
        originals = {k: t._data for k, t in sd.items()}
        try:
            for k, t in sd.items():
                t._data = state_vals[k]
            leaves = list(flat_in)
            for i, j in dyn_set.items():
                if i in tags:
                    t = Tensor(dyn[j])
                    t._layout = tags[i]
                    leaves[i] = t
                else:
                    leaves[i] = dyn[j]
            args, kw = jax.tree_util.tree_unflatten(in_tree, leaves)
            with _bufup.capture() as log:
                out = runner(args, kw)
            bufs = _bufup.resolve(log, sd)
            flat_out, out_tree = jax.tree_util.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, Tensor))
            meta["tree"] = out_tree
            meta["tensor"] = [isinstance(x, Tensor) for x in flat_out]
            meta["tags"] = [getattr(x, "_layout", None) for x in flat_out]
            raw = [x._data if isinstance(x, Tensor) else x
                   for x in flat_out]
            return raw, bufs
        finally:
            for k, t in sd.items():
                t._data = originals[k]

    ckpt = jax.checkpoint(fn, policy=_resolve_jax_policy(_POLICY[1]))
    depth = getattr(_tls, "depth", 0)
    _tls.depth = depth + 1
    try:
        raw_out, bufs = ckpt(state, dyn_vals)
    finally:
        _tls.depth = depth
    for k, v in bufs.items():
        _bufup.apply(sd[k], v)
    leaves = []
    for x, is_t, tag in zip(raw_out, meta["tensor"], meta["tags"]):
        if is_t:
            t = Tensor(x)
            t._layout = tag
            x = t
        leaves.append(x)
    return jax.tree_util.tree_unflatten(meta["tree"], leaves)
