"""Functional buffer updates: fold running-stat writes into compiled steps.

Eagerly, BatchNorm's running mean/var update is an in-place
`Tensor._set_data` (the reference's MomentumTensor outputs).  Under a
traced functional step (jit.TrainStep / jit.functional_call) an in-place
write of a tracer is meaningless — the value would be discarded when
`functional_call` restores the layer's original buffers, silently
freezing the running stats inside compiled training (and forcing the old
eager pre-compute to run the batch reduction twice per step).

This module is the bridge: a norm functional calls `apply(buffer, raw)`.
If a capture scope is active (functional_call under TrainStep), the new
traced value is *recorded* and surfaced as a functional output that the
compiled step folds into its next-state pytree — one XLA program, no
host round-trip.  With no scope active it falls back to the eager
in-place `_set_data`, so eager semantics are unchanged.
"""
from __future__ import annotations

import contextlib
from typing import List, Optional, Tuple

_ACTIVE: Optional[List[Tuple[object, object]]] = None


@contextlib.contextmanager
def capture():
    """Collect (buffer_tensor, new_raw_value) updates instead of applying
    them in place.  Yields the log list; nestable (innermost wins)."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = []
    try:
        yield _ACTIVE
    finally:
        _ACTIVE = prev


def capturing() -> bool:
    return _ACTIVE is not None


def apply(buffer, raw) -> None:
    """Update a (non-trainable) buffer: record under capture, else eager
    in-place."""
    if _ACTIVE is not None:
        _ACTIVE.append((buffer, raw))
    else:
        buffer._set_data(raw)


def resolve(log, state_dict) -> dict:
    """Map a capture log to {state_key: raw_value} by buffer identity.
    Later records win (a layer run twice keeps its last update)."""
    by_id = {id(t): k for k, t in state_dict.items()}
    out = {}
    for buf, raw in log:
        key = by_id.get(id(buf))
        if key is not None:
            out[key] = raw
    return out
