"""Version metadata (reference: python/paddle/version.py, generated at
build time)."""
full_version = "2.0.0-tpu"
major = "2"
minor = "0"
patch = "0"
rc = "0"
istaged = False
commit = "tpu-native-rewrite"
with_mkl = "OFF"


def show():
    print(f"full_version: {full_version}\ncommit: {commit}")
