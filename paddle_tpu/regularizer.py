"""paddle.regularizer — L1/L2 weight-decay regularizers.

Reference: python/paddle/regularizer.py (L1Decay/L2Decay) and
fluid/regularizer.py append_regularization_ops: the regularizer adds its
penalty gradient (coeff * sign(w) for L1, coeff * w for L2) to each
trainable parameter's gradient before the optimizer update.  Here the
optimizer consumes the object directly (`weight_decay=L2Decay(1e-4)`) and
folds the penalty into its fused jitted update — no separate regularizer
op pass.  On the dygraph optimizer path, a per-parameter regularizer set
via ParamAttr overrides the optimizer-level one (reference semantics);
the functional apply_updates path (sharded train steps) applies the
optimizer-level decay uniformly.
"""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class _Decay:
    mode: str = ""

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L1Decay(_Decay):
    """Lasso: penalty grad = coeff * sign(w)."""
    mode = "l1"


class L2Decay(_Decay):
    """Ridge: penalty grad = coeff * w."""
    mode = "l2"
