"""Convolutions (reference: python/paddle/nn/functional/conv.py,
operators/conv_op.cc + conv_cudnn_op.cu).  TPU-native: a single
`lax.conv_general_dilated` lowering — XLA tiles convs onto the MXU; there is no
algo-search/workspace machinery to port."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.op import dispatch


def _norm_tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _padding(padding, n, data_format):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    # paddle also allows [[0,0],[0,0],[h0,h1],[w0,w1]] including batch/channel
    pads = [tuple(int(q) for q in p) for p in padding]
    if data_format.startswith("NC"):
        return pads[2:]
    return pads[1:-1]


def _dims(n, channel_last):
    if n == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if n == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv(x, weight, bias, stride, padding, dilation, groups, n, data_format):
    channel_last = not data_format.startswith("NC")
    from ...core import layout as _layout
    from ...core.errors import InvalidArgumentError
    from ...core.tensor import Tensor as _Tensor
    from ...core.tensor import unwrap as _unwrap
    # layout policy: a logical-NCHW conv2d computes in NHWC (the faster
    # MXU layout) when the policy is on — the input is either already
    # physically NHWC (tagged by an upstream layout-aware op) or gets the
    # one boundary transpose here; the output carries the tag onward
    tag_output = False
    if n == 2 and not channel_last and isinstance(x, _Tensor):
        if _layout.tag_of(x) == _layout.NHWC:
            channel_last, tag_output = True, True
        elif _layout.policy() == _layout.NHWC and _unwrap(x).ndim == 4:
            x = _layout.ensure_nhwc(x)
            channel_last, tag_output = True, True
    xv, wv = _unwrap(x), _unwrap(weight)
    if xv.ndim != n + 2:
        raise InvalidArgumentError(
            f"[conv{n}d] expected a rank-{n + 2} input ({data_format}), "
            f"got shape {tuple(xv.shape)}")
    cin = xv.shape[1] if not channel_last else xv.shape[-1]
    if wv.shape[1] * groups != cin:
        raise InvalidArgumentError(
            f"[conv{n}d] input channels {cin} != weight in_channels "
            f"{wv.shape[1]} * groups {groups} (weight shape "
            f"{tuple(wv.shape)}, layout (out_c, in_c/groups, *k))")
    if wv.shape[0] % groups:
        raise InvalidArgumentError(
            f"[conv{n}d] out_channels {wv.shape[0]} not divisible by "
            f"groups {groups}")
    lhs_spec, rhs_spec, out_spec = _dims(n, channel_last)
    stride = _norm_tuple(stride, n)
    dilation = _norm_tuple(dilation, n)
    pad = _padding(padding, n, data_format)

    def raw(x, w, b):
        # paddle weight layout is (out_c, in_c/groups, *k) == OI* — matches rhs_spec
        if channel_last:
            w_t = jnp.moveaxis(w, (0, 1), (-1, -2))  # OI* -> *IO
            w_use = w_t
        else:
            w_use = w
        out = jax.lax.conv_general_dilated(
            x, w_use, window_strides=stride, padding=pad,
            rhs_dilation=dilation, feature_group_count=groups,
            dimension_numbers=(lhs_spec, rhs_spec, out_spec))
        if b is not None:
            shape = [1] * out.ndim
            shape[1 if not channel_last else -1] = b.shape[0]
            out = out + b.reshape(shape)
        return out
    out = dispatch(f"conv{n}d", raw, x, weight, bias)
    if tag_output:
        _layout.tag(out)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    df = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, df)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, groups,
                    dilation, n, data_format, output_size=None):
    channel_last = not data_format.startswith("NC")
    lhs_spec, rhs_spec, out_spec = _dims(n, channel_last)
    stride = _norm_tuple(stride, n)
    dilation = _norm_tuple(dilation, n)
    opad = _norm_tuple(output_padding, n)
    pad_arg = _padding(padding, n, data_format)

    def raw(x, w, b):
        # paddle transpose-conv weight layout: (in_c, out_c/groups, *k) == IO*
        # grad-of-conv formulation: lhs_dilation=stride
        if isinstance(pad_arg, str):
            pads = pad_arg
        else:
            k = [(w.shape[2 + i] - 1) * dilation[i] + 1 for i in range(n)]
            pads = [(k[i] - 1 - pad_arg[i][0],
                     k[i] - 1 - pad_arg[i][1] + opad[i]) for i in range(n)]
        w_flip = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        # IO* -> OI* with group interleave
        i_c, o_cg = w.shape[0], w.shape[1]
        if groups > 1:
            wg = w_flip.reshape((groups, i_c // groups, o_cg) + w.shape[2:])
            wg = jnp.swapaxes(wg, 1, 2)
            w_oi = wg.reshape((groups * o_cg, i_c // groups) + w.shape[2:])
        else:
            w_oi = jnp.swapaxes(w_flip, 0, 1)
        if channel_last:
            w_use = jnp.moveaxis(w_oi, (0, 1), (-1, -2))
        else:
            w_use = w_oi
        out = jax.lax.conv_general_dilated(
            x, w_use, window_strides=(1,) * n, padding=pads,
            lhs_dilation=stride, rhs_dilation=dilation,
            feature_group_count=groups,
            dimension_numbers=(lhs_spec, rhs_spec, out_spec))
        if b is not None:
            shape = [1] * out.ndim
            shape[1 if not channel_last else -1] = b.shape[0]
            out = out + b.reshape(shape)
        return out
    return dispatch(f"conv{n}d_transpose", raw, x, weight, bias)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    df = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, 1, df, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, 2, data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           groups, dilation, 3, data_format, output_size)
