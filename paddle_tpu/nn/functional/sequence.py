"""Sequence (ragged) ops — the TPU-native answer to LoDTensor.

Reference: paddle/fluid/framework/lod_tensor.h:114 (LoD = per-sequence
offset table over a packed buffer) and operators/sequence_ops/ (49 kernels
walking those offsets).  On TPU, dynamic per-row extents are hostile to
XLA's static-shape compilation, so the ragged representation is
(padded dense tensor, lengths vector) — every op below is a masked dense
computation.  Ops are jit-friendly given a static `maxlen`; with
maxlen=None the time extent is read from the data (one host sync, eager
only).  sequence_unpad is inherently host-side (data-dependent output
shape).

  reference LoDTensor op          here
  sequence_pad / unpad            pack <-> padded converters
  sequence_mask                   nn.functional.sequence_mask
  sequence_pool (6 modes)         sequence_pool — masked reductions
  sequence_softmax                sequence_softmax — masked softmax
  sequence_reverse                sequence_reverse — prefix flip gather
  sequence_concat                 sequence_concat — per-row concat
  sequence_enumerate              sequence_enumerate — sliding windows
  sequence_expand_as              sequence_expand_as — row broadcast

For packed-sequence training (many short sequences per row, the LoD
batching trick), `paddle_tpu.text.pack_sequences` emits segment ids that
flow through the flash-attention kernel's q/kv_segment_ids masking.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.op import dispatch
from ...core.tensor import Tensor, unwrap

__all__ = [
    "sequence_pad", "sequence_unpad", "sequence_pool", "sequence_softmax",
    "sequence_reverse", "sequence_concat", "sequence_enumerate",
    "sequence_expand_as", "sequence_first_step", "sequence_last_step",
]

# finite stand-in for -inf: exp(x - max) underflows to exactly 0 for
# masked entries, but (unlike -inf) an all-masked row stays NaN-free in
# both the forward softmax and its vjp
_MASKED = -1e30


def _lengths(lengths):
    return unwrap(lengths).astype(jnp.int32)


def _time_mask(lv, maxlen, ndim):
    """(B, T, 1...) bool mask of valid positions for an (B, T, ...) value
    with `ndim` dims — the single source of the mask shape logic."""
    m = jnp.arange(maxlen)[None, :] < lv[:, None]
    return m.reshape(m.shape + (1,) * (ndim - 2))


def sequence_pad(x, lengths, maxlen=None, pad_value=0.0, name=None):
    """Packed (total, ...) + lengths (B,) -> padded (B, maxlen, ...).

    Reference: sequence_pad_op (LoD -> padded)."""
    lv = _lengths(lengths)
    if maxlen is None:
        maxlen = int(jax.device_get(jnp.max(lv)))

    def raw(x, lv):
        offsets = jnp.cumsum(lv) - lv                      # (B,)
        t = jnp.arange(maxlen)                             # (T,)
        idx = jnp.clip(offsets[:, None] + t[None, :], 0, x.shape[0] - 1)
        out = x[idx]                                       # (B, T, ...)
        return jnp.where(_time_mask(lv, maxlen, out.ndim), out,
                         jnp.asarray(pad_value, out.dtype))
    return dispatch("sequence_pad", raw, x, Tensor(lv, stop_gradient=True))


def sequence_unpad(x, lengths, name=None):
    """Padded (B, T, ...) + lengths -> packed (total, ...).

    The output extent sum(lengths) is data-dependent, so this op runs
    host-side (eager only) — the LoD direction of sequence_pad_op."""
    lv = np.asarray(jax.device_get(_lengths(lengths)))
    rows = np.repeat(np.arange(len(lv)), lv)
    cols = np.concatenate([np.arange(n) for n in lv]) if len(lv) else \
        np.zeros((0,), np.int64)

    def raw(x):
        return x[jnp.asarray(rows), jnp.asarray(cols)]
    return dispatch("sequence_unpad", raw, x)


def sequence_pool(x, lengths, pool_type="average", pad_value=0.0, name=None):
    """Masked pooling over the time axis (B, T, ...) -> (B, ...).

    Empty sequences (length 0) yield pad_value in every mode (reference:
    sequence_pool_op pad_value attribute)."""
    pool_type = pool_type.lower()
    lv = _lengths(lengths)

    def raw(x, lv):
        mask = _time_mask(lv, x.shape[1], x.ndim)
        n = jnp.maximum(lv, 1).reshape((-1,) + (1,) * (x.ndim - 2))
        empty = (lv == 0).reshape((-1,) + (1,) * (x.ndim - 2))
        pad = jnp.asarray(pad_value, x.dtype)
        if pool_type == "sum":
            out = jnp.where(mask, x, 0).sum(1)
        elif pool_type == "average":
            out = jnp.where(mask, x, 0).sum(1) / n
        elif pool_type == "sqrt":
            out = jnp.where(mask, x, 0).sum(1) / jnp.sqrt(
                n.astype(x.dtype))
        elif pool_type == "max":
            out = jnp.where(mask, x, _MASKED).max(1)
        elif pool_type == "min":
            out = jnp.where(mask, x, -_MASKED).min(1)
        elif pool_type == "first":
            out = x[:, 0]
        elif pool_type == "last":
            idx = jnp.maximum(lv - 1, 0)
            out = jnp.take_along_axis(
                x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1
            )[:, 0]
        else:
            from ...core.errors import InvalidArgumentError
            raise InvalidArgumentError(
                f"[sequence_pool] unknown pool_type {pool_type!r}")
        return jnp.where(empty, pad, out)
    return dispatch("sequence_pool", raw, x, Tensor(lv, stop_gradient=True))


def sequence_first_step(x, lengths=None, name=None):
    if lengths is None:
        lengths = jnp.full((unwrap(x).shape[0],), unwrap(x).shape[1])
    return sequence_pool(x, lengths, "first")


def sequence_last_step(x, lengths, name=None):
    return sequence_pool(x, lengths, "last")


def sequence_softmax(x, lengths, name=None):
    """Masked softmax over the time axis (reference: sequence_softmax_op).
    Empty rows output 0 with finite (zero) gradients — the masking uses a
    large-negative sentinel rather than -inf to keep the softmax vjp
    NaN-free."""
    lv = _lengths(lengths)

    def raw(x, lv):
        mask = _time_mask(lv, x.shape[1], x.ndim)
        s = jnp.where(mask, x, _MASKED)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=1)
        return jnp.where(mask, p, 0).astype(x.dtype)
    return dispatch("sequence_softmax", raw, x,
                    Tensor(lv, stop_gradient=True))


def sequence_reverse(x, lengths, name=None):
    """Reverse each row's valid prefix, keep padding in place
    (reference: sequence_reverse_op)."""
    lv = _lengths(lengths)

    def raw(x, lv):
        t = jnp.arange(x.shape[1])
        rev = lv[:, None] - 1 - t[None, :]
        idx = jnp.where(t[None, :] < lv[:, None], rev, t[None, :])
        return jnp.take_along_axis(
            x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)
    return dispatch("sequence_reverse", raw, x,
                    Tensor(lv, stop_gradient=True))


def sequence_concat(xs, lengths_list, maxlen=None, name=None):
    """Per-row concatenation of ragged sequences
    (reference: sequence_concat_op).  Returns (padded, lengths)."""
    lvs = [_lengths(l) for l in lengths_list]
    total = sum(lvs)
    if maxlen is None:
        maxlen = int(jax.device_get(jnp.max(total)))

    def raw(*args):
        n = len(args) // 2
        xs, lvs = args[:n], args[n:]
        b = xs[0].shape[0]
        t = jnp.arange(maxlen)
        out = jnp.zeros((b, maxlen) + xs[0].shape[2:], xs[0].dtype)
        start = jnp.zeros((b,), jnp.int32)
        for xi, li in zip(xs, lvs):
            # place xi's valid prefix at offset `start` in each row
            src_t = t[None, :] - start[:, None]            # (B, T)
            valid = jnp.logical_and(src_t >= 0, src_t < li[:, None])
            src = jnp.clip(src_t, 0, xi.shape[1] - 1)
            gathered = jnp.take_along_axis(
                xi, src.reshape(src.shape + (1,) * (xi.ndim - 2)), axis=1)
            vshape = valid.shape + (1,) * (xi.ndim - 2)
            out = jnp.where(valid.reshape(vshape), gathered, out)
            start = start + li
        return out
    parts = list(xs) + [Tensor(l, stop_gradient=True) for l in lvs]
    return dispatch("sequence_concat", raw, *parts), \
        Tensor(total, stop_gradient=True)


def sequence_enumerate(x, win_size, lengths=None, pad_value=0, name=None):
    """Sliding windows over ids: (B, T) -> (B, T, win_size); windows read
    past a row's length (or the array end) as pad_value
    (reference: sequence_enumerate_op is LoD-aware the same way)."""
    lv = None if lengths is None else _lengths(lengths)

    def raw(x, *opt):
        t = jnp.arange(x.shape[1])[:, None] + jnp.arange(win_size)[None, :]
        if opt:
            end = opt[0][:, None, None]                    # (B, 1, 1)
            valid = t[None, :, :] < end
        else:
            valid = (t < x.shape[1])[None]
        tc = jnp.clip(t, 0, x.shape[1] - 1)
        out = x[:, tc]                                     # (B, T, W)
        return jnp.where(valid, out, jnp.asarray(pad_value, x.dtype))
    if lv is None:
        return dispatch("sequence_enumerate", raw, x)
    return dispatch("sequence_enumerate", raw, x,
                    Tensor(lv, stop_gradient=True))


def sequence_expand_as(x, lengths, maxlen=None, name=None):
    """Broadcast each row vector x[b] across its sequence positions:
    (B, ...) + lengths -> (B, maxlen, ...).  Dense analogue of
    sequence_expand_as_op: result[b, t] = x[b] for t < lengths[b], else 0.
    Pass maxlen for a jit-traceable call."""
    lv = _lengths(lengths)
    if maxlen is None:
        maxlen = int(jax.device_get(jnp.max(lv))) if lv.size else 0

    def raw(x, lv):
        mask = _time_mask(lv, maxlen, x.ndim + 1)
        tiled = jnp.broadcast_to(x[:, None], (x.shape[0], maxlen)
                                 + x.shape[1:])
        return jnp.where(mask, tiled, 0)
    return dispatch("sequence_expand_as", raw, x,
                    Tensor(lv, stop_gradient=True))
