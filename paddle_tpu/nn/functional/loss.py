"""Loss functionals (reference: python/paddle/nn/functional/loss.py,
operators/softmax_with_cross_entropy_op, cross_entropy_op, bce_loss_op…)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.op import dispatch
from ...core.tensor import unwrap


def _reduce(loss, reduction, weight_sum=None):
    if reduction == "none":
        return loss
    if reduction == "sum":
        return jnp.sum(loss)
    if weight_sum is not None:
        return jnp.sum(loss) / weight_sum
    return jnp.mean(loss)


from functools import partial as _partial


@_partial(jax.custom_vjp, nondiff_argnums=(2,))
def _softmax_ce_fast(logits, lbl, ignore_index):
    loss, _ = _softmax_ce_fast_fwd(logits, lbl, ignore_index)
    return loss


def _softmax_ce_fast_fwd(logits, lbl, ignore_index):
    lf = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(lf, axis=-1)                      # (...,)
    safe = jnp.where(lbl == ignore_index, 0, lbl)
    picked = jnp.take_along_axis(lf, safe[..., None], axis=-1)[..., 0]
    loss = jnp.where(lbl == ignore_index, 0.0, lse - picked)
    return loss, (logits, lbl, lse)


def _softmax_ce_fast_bwd(ignore_index, res, ct):
    logits, lbl, lse = res
    valid = lbl != ignore_index
    safe = jnp.where(valid, lbl, 0)
    scale = (ct * valid.astype(jnp.float32))[..., None]
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    onehot = jax.lax.broadcasted_iota(
        jnp.int32, logits.shape, logits.ndim - 1) == safe[..., None]
    d = (p - onehot.astype(jnp.float32)) * scale
    return d.astype(logits.dtype), None


_softmax_ce_fast.defvjp(_softmax_ce_fast_fwd, _softmax_ce_fast_bwd)


def cross_entropy(input, label, weight=None, ignore_index=-100,  # noqa: A002
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    """Fused softmax+CE (reference: operators/softmax_with_cross_entropy_op.cc).

    The common case (hard int labels, no class weights, no smoothing, last
    axis) takes a custom-vjp FAST PATH: per-token loss = logsumexp - picked
    with a closed-form backward (softmax - onehot, onehot built from a
    fused iota compare).  Two wins measured on v5e (r5 BERT head probe):
    the generic path's take_along_axis GRADIENT lowers to a serialized
    scatter over the (tokens, vocab) logits, and the AMP black-list cast
    materializes an f32 logits copy — the fast path dispatches under its
    own un-black-listed name, reads bf16 logits directly and does all
    reduction math in f32 in-register (numerics identical to the f32
    path)."""
    import os
    lv = unwrap(input)
    lab_v = unwrap(label)
    # PDTPU_CE_GENERIC=1 forces the generic log_softmax path (perf-probe
    # escape hatch: probes/bert_head_probe.py re-measures the pre-r5
    # implementations against the fast path)
    fast = (use_softmax and not soft_label and weight is None
            and label_smoothing == 0.0 and axis in (-1, lv.ndim - 1)
            and jnp.issubdtype(lab_v.dtype, jnp.integer)
            and lv.ndim >= 1
            and os.environ.get("PDTPU_CE_GENERIC") != "1")

    if fast:
        def raw_fast(logits, lbl):
            lbl = lbl.astype(jnp.int32)
            if lbl.ndim == logits.ndim and lbl.shape[-1] == 1:
                lbl = jnp.squeeze(lbl, -1)
            loss = _softmax_ce_fast(logits, lbl, ignore_index)
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(
                    (lbl != ignore_index).astype(jnp.float32)), 1.0)
                return jnp.sum(loss) / denom
            return _reduce(loss, reduction)
        return dispatch("softmax_ce_fast", raw_fast, input, label)

    def raw(logits, label, w):
        if use_softmax:
            logp = jax.nn.log_softmax(logits, axis=axis)
        else:
            logp = jnp.log(jnp.maximum(logits, 1e-30))
        if soft_label or (not jnp.issubdtype(label.dtype, jnp.integer)
                          and label.ndim == logits.ndim
                          and label.shape == logits.shape):
            if label_smoothing > 0.0:
                k = logits.shape[axis]
                label = label * (1 - label_smoothing) + label_smoothing / k
            loss = -jnp.sum(label * logp, axis=axis)
            return _reduce(loss, reduction)
        lbl = label.astype(jnp.int32)
        squeeze = (lbl.ndim == logits.ndim and lbl.shape[axis] == 1)
        if squeeze:
            lbl = jnp.squeeze(lbl, axis=axis)
        if label_smoothing > 0.0:
            k = logits.shape[axis]
            onehot = jax.nn.one_hot(lbl, k, axis=axis)
            soft = onehot * (1 - label_smoothing) + label_smoothing / k
            loss = -jnp.sum(soft * logp, axis=axis)
        else:
            lbl_safe = jnp.where(lbl == ignore_index, 0, lbl)
            loss = -jnp.take_along_axis(
                logp, jnp.expand_dims(lbl_safe, axis), axis=axis)
            loss = jnp.squeeze(loss, axis=axis)
        mask = (lbl != ignore_index)
        loss = jnp.where(mask, loss, 0.0)
        if w is not None:
            wsel = jnp.take(w, jnp.where(lbl == ignore_index, 0, lbl))
            wsel = jnp.where(mask, wsel, 0.0)
            loss = loss * wsel
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(wsel), 1e-12)
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(mask.astype(loss.dtype)), 1.0)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)
    return dispatch("cross_entropy", raw, input, label, weight)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    out = cross_entropy(logits, label, soft_label=soft_label,
                        ignore_index=ignore_index, reduction="none", axis=axis)
    from .activation import softmax as _softmax
    out = out.unsqueeze(axis) if out.ndim < unwrap(logits).ndim else out
    if return_softmax:
        return out, _softmax(logits, axis=axis)
    return out


def nll_loss(input, label, weight=None, ignore_index=-100,  # noqa: A002
             reduction="mean", name=None):
    def raw(logp, label, w):
        lbl = label.astype(jnp.int32)
        lbl_safe = jnp.where(lbl == ignore_index, 0, lbl)
        if logp.ndim > 2:
            # (N, C, d1...) -> move C last
            lp = jnp.moveaxis(logp, 1, -1)
            loss = -jnp.take_along_axis(lp, lbl_safe[..., None], axis=-1)[..., 0]
        else:
            loss = -jnp.take_along_axis(logp, lbl_safe[..., None], axis=-1)[..., 0]
        mask = (lbl != ignore_index)
        loss = jnp.where(mask, loss, 0.0)
        if w is not None:
            wsel = jnp.take(w, lbl_safe) * mask.astype(loss.dtype)
            loss = loss * wsel
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(wsel), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(mask.astype(loss.dtype)), 1.0)
        return _reduce(loss, reduction)
    return dispatch("nll_loss", raw, input, label, weight)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):  # noqa: A002
    def raw(p, y, w):
        eps = 1e-12
        loss = -(y * jnp.log(jnp.maximum(p, eps))
                 + (1 - y) * jnp.log(jnp.maximum(1 - p, eps)))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    return dispatch("binary_cross_entropy", raw, input, label, weight)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None, name=None):
    def raw(z, y, w, pw):
        neg_abs = -jnp.abs(z)
        if pw is not None:
            log_w = (pw - 1) * y + 1
            loss = (1 - y) * z + log_w * (jnp.log1p(jnp.exp(neg_abs))
                                          + jnp.maximum(-z, 0.0))
        else:
            loss = jnp.maximum(z, 0.0) - z * y + jnp.log1p(jnp.exp(neg_abs))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    return dispatch("bce_with_logits", raw, logit, label, weight, pos_weight)


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    def raw(x, y):
        return _reduce(jnp.square(x - y), reduction)
    return dispatch("mse_loss", raw, input, label)


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    def raw(x, y):
        return _reduce(jnp.abs(x - y), reduction)
    return dispatch("l1_loss", raw, input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    def raw(x, y):
        d = jnp.abs(x - y)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)
    return dispatch("smooth_l1_loss", raw, input, label)


def kl_div(input, label, reduction="mean", log_target=False, name=None):  # noqa: A002
    def raw(logp, y):
        if log_target:
            loss = jnp.exp(y) * (y - logp)
        else:
            loss = jnp.where(y > 0, y * (jnp.log(jnp.maximum(y, 1e-12)) - logp), 0.0)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return dispatch("kl_div", raw, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):  # noqa: A002
    def raw(x1, x2, y):
        loss = jnp.maximum(-y * (x1 - x2) + margin, 0.0)
        return _reduce(loss, reduction)
    return dispatch("margin_ranking_loss", raw, input, other, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):  # noqa: A002
    def raw(x, y):
        loss = jnp.where(y == 1, x, jnp.maximum(margin - x, 0.0))
        return _reduce(loss, reduction)
    return dispatch("hinge_embedding_loss", raw, input, label)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def raw(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(cos - margin, 0.0))
        return _reduce(loss, reduction)
    return dispatch("cosine_embedding_loss", raw, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,  # noqa: A002
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def raw(a, pos, neg):
        def dist(u, v):
            return jnp.sum(jnp.abs(u - v + epsilon) ** p, axis=-1) ** (1.0 / p)
        d_ap = dist(a, pos)
        d_an = dist(a, neg)
        if swap:
            d_pn = dist(pos, neg)
            d_an = jnp.minimum(d_an, d_pn)
        loss = jnp.maximum(d_ap - d_an + margin, 0.0)
        return _reduce(loss, reduction)
    return dispatch("triplet_margin_loss", raw, input, positive, negative)


def square_error_cost(input, label):  # noqa: A002
    def raw(x, y):
        return jnp.square(x - y)
    return dispatch("square_error_cost", raw, input, label)


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    def raw(p, y):
        return -(y * jnp.log(p + epsilon) + (1 - y) * jnp.log(1 - p + epsilon))
    return dispatch("log_loss", raw, input, label)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def raw(z, y, norm):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0.0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if norm is not None:
            loss = loss / norm
        return _reduce(loss, reduction)
    return dispatch("sigmoid_focal_loss", raw, logit, label, normalizer)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC (reference: operators/warpctc_op → warp-ctc).  TPU-native: dynamic-
    programming forward in log space via lax.scan, fully jittable."""
    def raw(logp, labels, in_len, lbl_len):
        # logp: (T, N, C) paddle layout
        T, N, C = logp.shape
        S = labels.shape[1]
        # extended label seq with blanks: length 2S+1
        ext = jnp.full((N, 2 * S + 1), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(labels.astype(jnp.int32))
        ext_len = 2 * lbl_len.astype(jnp.int32) + 1

        neg_inf = jnp.asarray(-1e30, logp.dtype)
        alpha0 = jnp.full((N, 2 * S + 1), neg_inf, logp.dtype)
        alpha0 = alpha0.at[:, 0].set(logp[0, jnp.arange(N), ext[:, 0]])
        valid1 = (ext_len > 1)
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(valid1, logp[0, jnp.arange(N), ext[:, 1]], neg_inf))

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((N, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, t):
            lp = logp[t]  # (N, C)
            a_shift1 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
            a2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, a_shift1), a2)
            emit = jnp.take_along_axis(lp, ext, axis=1)
            new_alpha = merged + emit
            # freeze past input length
            new_alpha = jnp.where((t < in_len)[:, None], new_alpha, alpha)
            return new_alpha, None

        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        idx_last = ext_len - 1
        ar = jnp.arange(N)
        ll = jnp.logaddexp(alpha[ar, idx_last],
                           jnp.where(idx_last - 1 >= 0, alpha[ar, jnp.maximum(idx_last - 1, 0)], neg_inf))
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lbl_len.astype(loss.dtype), 1.0))
        return _reduce(loss, reduction)
    return dispatch("ctc_loss", raw, log_probs, labels, input_lengths, label_lengths)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def raw(a, p, y):
        reg = l2_reg * (jnp.sum(jnp.mean(a * a, axis=1)) + jnp.sum(jnp.mean(p * p, axis=1))) * 0.25
        logits = a @ p.T
        same = (y[:, None] == y[None, :]).astype(logits.dtype)
        same = same / jnp.sum(same, axis=1, keepdims=True)
        xe = -jnp.sum(same * jax.nn.log_softmax(logits, axis=1), axis=1)
        return jnp.mean(xe) + reg
    return dispatch("npair_loss", raw, anchor, positive, labels)
