"""Vision functionals: affine_grid / grid_sample / temporal_shift.

Reference: operators/affine_grid_op.cc, grid_sampler_op.cc (cudnn spatial
transformer kernels), temporal_shift_op.cc — surfaced via
python/paddle/nn/functional/vision.py.  TPU-native: the sampler is the same
vectorized bilinear corner-gather used by deform_conv2d/roi_align (take
along flattened spatial + weighted sum — XLA fuses it; fully
differentiable), not a cudnn descriptor call.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.op import dispatch

__all__ = ["affine_grid", "grid_sample", "temporal_shift"]


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """theta (N, 2, 3) -> sampling grid (N, H, W, 2) in [-1, 1] coords."""
    from ...core.tensor import unwrap
    if not isinstance(out_shape, (list, tuple)):
        out_shape = [int(v) for v in unwrap(out_shape)]
    n, _, h, w = [int(v) for v in out_shape]

    def raw(theta):
        def axis_coords(size):
            if align_corners:
                return jnp.linspace(-1.0, 1.0, size)
            step = 2.0 / size
            return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)
        xs = axis_coords(w)
        ys = axis_coords(h)
        gx, gy = jnp.meshgrid(xs, ys)              # (H, W)
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # (H, W, 3)
        # (N, 2, 3) x (H, W, 3) -> (N, H, W, 2)
        return jnp.einsum("nij,hwj->nhwi", theta.astype(jnp.float32), base)
    return dispatch("affine_grid", raw, theta)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x (N, C, H, W) at grid (N, Hg, Wg, 2) of [-1, 1] xy coords.

    modes: bilinear | nearest; padding_mode: zeros | border | reflection.
    """
    if mode not in ("bilinear", "nearest"):
        from ...core.errors import InvalidArgumentError
        raise InvalidArgumentError(f"[grid_sample] unsupported mode {mode!r}")
    if padding_mode not in ("zeros", "border", "reflection"):
        from ...core.errors import InvalidArgumentError
        raise InvalidArgumentError(
            f"[grid_sample] unsupported padding_mode {padding_mode!r}")

    def raw(xv, gv):
        n, c, h, w = xv.shape
        gx = gv[..., 0].astype(jnp.float32)        # (N, Hg, Wg)
        gy = gv[..., 1].astype(jnp.float32)

        def unnorm(coord, size):
            if align_corners:
                return (coord + 1.0) * (size - 1) / 2.0
            return ((coord + 1.0) * size - 1.0) / 2.0

        fx = unnorm(gx, w)
        fy = unnorm(gy, h)

        if padding_mode == "reflection":
            def reflect(v, size):
                if align_corners:
                    span = 2.0 * (size - 1)
                    v = jnp.abs(jnp.mod(v, span))
                    return jnp.where(v > size - 1, span - v, v)
                # reference grid_sampler_op.h: reflect around the -0.5 /
                # size-0.5 pixel-edge line: extra = |v+0.5| mod 2*size,
                # reflected = min(extra, 2*size-extra) - 0.5
                span = 2.0 * size
                extra = jnp.mod(jnp.abs(v + 0.5), span)
                v = jnp.minimum(extra, span - extra) - 0.5
                return jnp.clip(v, 0, size - 1)
            fx = reflect(fx, w)
            fy = reflect(fy, h)

        def gather(yy, xx, wgt=None):
            inside = (yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1)
            yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            flat = (yc * w + xc).reshape(n, 1, -1)
            g = jnp.take_along_axis(
                xv.reshape(n, c, h * w),
                jnp.broadcast_to(flat, (n, c, flat.shape[-1])), axis=2)
            g = g.reshape(n, c, *yy.shape[1:])
            if padding_mode == "zeros":
                g = g * inside[:, None].astype(g.dtype)
            if wgt is not None:
                g = g * wgt[:, None].astype(g.dtype)
            return g

        if mode == "nearest":
            return gather(jnp.round(fy), jnp.round(fx))

        y0 = jnp.floor(fy)
        x0 = jnp.floor(fx)
        ly = fy - y0
        lx = fx - x0
        return (gather(y0, x0, (1 - ly) * (1 - lx))
                + gather(y0, x0 + 1, (1 - ly) * lx)
                + gather(y0 + 1, x0, ly * (1 - lx))
                + gather(y0 + 1, x0 + 1, ly * lx))

    return dispatch("grid_sample", raw, x, grid)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    """TSM temporal shift (reference: operators/temporal_shift_op): fold
    (N*T, C, H, W) into segments and shift the first shift_ratio*C channels
    back, the next block forward, zero-padding the ends."""
    if data_format != "NCHW":
        raise ValueError("temporal_shift: only NCHW here")

    def raw(xv):
        nt, c, h, w = xv.shape
        t = seg_num
        nb = nt // t
        v = xv.reshape(nb, t, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        back = jnp.concatenate(
            [v[:, 1:, :c1], jnp.zeros_like(v[:, :1, :c1])], axis=1)
        fwd = jnp.concatenate(
            [jnp.zeros_like(v[:, :1, c1:c2]), v[:, :-1, c1:c2]], axis=1)
        rest = v[:, :, c2:]
        return jnp.concatenate([back, fwd, rest], axis=2).reshape(
            nt, c, h, w)
    return dispatch("temporal_shift", raw, x)
